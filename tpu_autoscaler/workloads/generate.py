"""Runnable generation CLI: serve a checkpoint trained by train.py.

``python -m tpu_autoscaler.workloads.generate --checkpoint-dir ...``
restores the latest checkpoint's params (the trainer's state layout) and
runs the KV-cache decode path (workloads/decode.py) — the serving-side
proof that a slice the autoscaler provisioned answers, not just trains.

The model flags must match the training run (same rule as resume); the
prompt is token ids (comma-separated) or random with ``--prompt-len``.
"""

from __future__ import annotations

import logging
import sys

import click

log = logging.getLogger(__name__)


from tpu_autoscaler.workloads._cli import model_arch_options, model_config


@click.command()
@click.option("--checkpoint-dir", default="/tmp/tpu-train-ckpt",
              show_default=True)
@click.option("--steps", default=32, show_default=True,
              help="Tokens to generate.")
@click.option("--prompt", default=None,
              help="Comma-separated token ids (default: random).")
@click.option("--prompt-len", default=8, show_default=True,
              help="Random prompt length when --prompt is not given.")
@click.option("--batch", default=1, show_default=True)
@click.option("--temperature", default=0.0, show_default=True,
              help="0 = greedy; > 0 samples.")
@click.option("--top-k", default=None, type=click.IntRange(min=1))
@click.option("--top-p", default=None, type=click.FloatRange(min=0.0,
                                                             max=1.0,
                                                             min_open=True),
              help="Nucleus sampling: keep the smallest token set with "
                   "cumulative probability >= this.")
@click.option("--seed", default=0, show_default=True)
@click.option("--tp", "tp_degree", default=None, type=int,
              help="Serve under a (data, model) mesh via "
                   "make_sharded_generate: prompts shard over data, "
                   "params + KV cache over 'model' (the trainer's TP "
                   "layout).  Default: single-device.")
@model_arch_options
@click.option("--platform", default=None,
              help="Force a jax platform (e.g. cpu).")
def main(checkpoint_dir, steps, prompt, prompt_len, batch, temperature,
         top_k, top_p, seed, tp_degree, vocab, seq_len, d_model, n_layers,
         n_kv_heads, attention_window, no_rope, moe_experts, moe_top_k,
         platform):
    """Generate tokens from the latest checkpoint in --checkpoint-dir."""
    logging.basicConfig(level=logging.INFO, stream=sys.stderr,
                        format="%(asctime)s %(levelname)s: %(message)s")
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    import jax.numpy as jnp

    from tpu_autoscaler.workloads.checkpoint import (
        latest_step,
        restore_checkpoint,
    )
    from tpu_autoscaler.workloads.decode import generate
    from tpu_autoscaler.workloads.model import init_params

    cfg = model_config(vocab, seq_len, d_model, n_layers, n_kv_heads,
                       attention_window, no_rope, moe_experts, moe_top_k)
    if top_k is not None and top_k > cfg.vocab:
        raise click.UsageError(
            f"--top-k {top_k} exceeds the vocab size {cfg.vocab}")
    if temperature == 0.0 and (top_k is not None or top_p is not None):
        raise click.UsageError(
            "--top-k/--top-p need --temperature > 0 (the default 0 is "
            "greedy decoding, which ignores truncation)")

    step = latest_step(checkpoint_dir)
    if step is None:
        raise click.UsageError(
            f"no checkpoint found in {checkpoint_dir!r} (train first: "
            f"python -m tpu_autoscaler.workloads.train)")
    # The trainer checkpoints {"params": ..., "opt": ...}.  Restore
    # WITHOUT an abstract tree (orbax reads the saved structure from its
    # own metadata): serving must not depend on which optimizer recipe —
    # schedules, clipping, accumulation all change the opt-state SHAPE —
    # produced the checkpoint.  Genuine I/O failures (permissions,
    # truncation) propagate with their own error; only a params-tree
    # mismatch against the flags is diagnosed as a flag mismatch.
    state = restore_checkpoint(checkpoint_dir, step, None)
    if not isinstance(state, dict) or "params" not in state:
        raise click.UsageError(
            f"checkpoint at step {step} is not a trainer checkpoint "
            f"(expected a {{'params', 'opt'}} tree)")
    abstract = jax.eval_shape(
        lambda key: init_params(key, cfg), jax.random.PRNGKey(0))
    got_paths = jax.tree_util.tree_flatten_with_path(state["params"])[0]
    want_paths = jax.tree_util.tree_flatten_with_path(abstract)[0]

    def path_str(path):
        return "/".join(str(k.key) for k in path)

    if [path_str(p) for p, _ in got_paths] \
            != [path_str(p) for p, _ in want_paths]:
        raise click.UsageError(
            "checkpoint params tree does not match the model flags "
            "(train and generate must agree on --d-model/--n-layers/...)")
    mismatches = [
        f"{path_str(path)}: checkpoint {tuple(got.shape)} vs flags "
        f"{tuple(want.shape)}"
        for (path, got), (_, want) in zip(got_paths, want_paths)
        if tuple(got.shape) != tuple(want.shape)]
    if mismatches:
        raise click.UsageError(
            "checkpoint does not match the model flags: "
            + "; ".join(mismatches[:4]))
    params = state["params"]
    log.info("restored step %d from %s", step, checkpoint_dir)

    if prompt is not None:
        try:
            ids = [int(t) for t in prompt.split(",") if t.strip()]
        except ValueError as e:
            raise click.UsageError(
                f"--prompt must be comma-separated ints: {e}") from e
        if not ids:
            raise click.UsageError("--prompt is empty")
        if any(t < 0 or t >= cfg.vocab for t in ids):
            raise click.UsageError(
                f"--prompt ids must be in [0, {cfg.vocab})")
        tokens = jnp.asarray([ids] * batch, jnp.int32)
    else:
        tokens = jax.random.randint(jax.random.PRNGKey(seed),
                                    (batch, prompt_len), 0, cfg.vocab,
                                    dtype=jnp.int32)

    key = jax.random.PRNGKey(seed) if temperature > 0 else None
    if tp_degree is not None and tp_degree > 1:
        from tpu_autoscaler.workloads.decode import make_sharded_generate
        from tpu_autoscaler.workloads.model import make_mesh, param_specs

        n_dev = len(jax.devices())
        if n_dev % tp_degree:
            raise click.UsageError(
                f"--tp {tp_degree} must divide the {n_dev} available "
                f"devices")
        mesh = make_mesh(tp=tp_degree)
        dp = n_dev // tp_degree
        if batch % dp:
            raise click.UsageError(
                f"--batch {batch} must divide over the {dp} "
                f"data-parallel devices (devices / tp)")
        log.info("serving under mesh %s", dict(mesh.shape))
        from jax.sharding import NamedSharding, PartitionSpec as P

        p_shard = jax.tree.map(
            lambda spec: NamedSharding(mesh, spec),
            param_specs(cfg.resolved_for_mesh(mesh)),
            is_leaf=lambda x: isinstance(x, P))
        # Restored params arrive committed to their saved shardings;
        # re-place them onto this mesh's TP layout.
        params = jax.device_put(params, p_shard)
        run = make_sharded_generate(
            mesh, cfg, steps, temperature=temperature, top_k=top_k,
            top_p=top_p)
        out = run(params, tokens,
                  key if key is not None else jax.random.PRNGKey(seed))
    else:
        out = generate(params, tokens, cfg, steps, key=key,
                       temperature=temperature, top_k=top_k, top_p=top_p)
    prompt_n = tokens.shape[1]
    for row in out:
        ids = [int(t) for t in row]
        print(f"{','.join(map(str, ids[:prompt_n]))} | "
              f"{','.join(map(str, ids[prompt_n:]))}")


if __name__ == "__main__":
    main()
