"""Checkpoint-aware drain: the job-side contract (BASELINE config #5).

The controller reclaims slices by annotating workload pods with
``autoscaler.tpu.dev/checkpoint-requested`` (controller/reconciler.py
§CHECKPOINT_ANNOTATION) and waiting ``drain_grace_seconds`` before force
eviction.  A job that wants graceful preemption runs a ``DrainWatcher``:

- the pod mounts its own annotations via the downward API
  (``/etc/podinfo/annotations``, the standard ``key="value"`` lines format);
- between steps the training loop calls ``watcher.drain_requested()``;
- on True it saves an orbax checkpoint and exits 0 — well inside the drain
  window, so the slice is reclaimed with zero lost work.

This is new scope relative to the reference (SURVEY.md §6.4: the reference
had no checkpoint story; statelessness was its resume strategy).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Callable, Mapping

log = logging.getLogger(__name__)

CHECKPOINT_ANNOTATION = "autoscaler.tpu.dev/checkpoint-requested"
DEFAULT_ANNOTATIONS_PATH = "/etc/podinfo/annotations"


def parse_downward_annotations(text: str) -> dict[str, str]:
    """Parse the downward-API annotations file (``key="escaped value"``)."""
    out: dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or "=" not in line:
            continue
        key, _, value = line.partition("=")
        value = value.strip()
        if value.startswith('"') and value.endswith('"') and len(value) >= 2:
            value = value[1:-1].encode().decode("unicode_escape")
        out[key.strip()] = value
    return out


class DrainWatcher:
    """Polls a source of pod annotations for the checkpoint request.

    ``source`` is either a path to a downward-API annotations file or a
    callable returning the annotation dict (tests, or a kube-API poller).
    """

    def __init__(self,
                 source: str | Callable[[], Mapping[str, str]]
                 = DEFAULT_ANNOTATIONS_PATH,
                 min_poll_interval: float = 2.0):
        self._source = source
        self._min_interval = min_poll_interval
        self._last_poll = 0.0
        self._cached = False

    def _annotations(self) -> Mapping[str, str]:
        if callable(self._source):
            return self._source()
        try:
            with open(self._source) as f:
                return parse_downward_annotations(f.read())
        except OSError:
            return {}

    def drain_requested(self) -> bool:
        """Cheap enough to call every training step (rate-limited poll)."""
        now = time.monotonic()
        if self._cached or now - self._last_poll < self._min_interval:
            return self._cached
        self._last_poll = now
        self._cached = CHECKPOINT_ANNOTATION in self._annotations()
        if self._cached:
            log.info("drain requested via %s annotation",
                     CHECKPOINT_ANNOTATION)
        return self._cached


# ---- orbax checkpoint io ------------------------------------------------

def save_checkpoint(directory: str, step: int, state) -> str:
    """Save a pytree checkpoint (blocking); returns the checkpoint path."""
    writer = AsyncCheckpointWriter()
    path = writer.save(directory, step, state)
    writer.wait()
    return path


class AsyncCheckpointWriter:
    """Overlap checkpoint writes with training steps.

    orbax's async path snapshots device arrays, returns immediately, and
    serializes to disk in the background — the train loop keeps stepping
    during the write instead of stalling (the periodic-checkpoint cost at
    real model sizes).  ``wait()`` blocks until the in-flight write lands;
    call it before a drain exit or process shutdown so the final
    checkpoint is durable.
    """

    def __init__(self):
        import orbax.checkpoint as ocp

        self._checkpointer = ocp.StandardCheckpointer()

    def save(self, directory: str, step: int, state) -> str:
        path = os.path.join(os.path.abspath(directory), f"step_{step}")
        # StandardCheckpointer is AsyncCheckpointer-backed: save() blocks
        # only on the PREVIOUS in-flight write + the device-to-host
        # snapshot, then serializes in the background — the disk write
        # itself (the long part at real sizes) overlaps training.
        self._checkpointer.save(path, state, force=True)
        return path

    def wait(self) -> None:
        self._checkpointer.wait_until_finished()


def restore_checkpoint(directory: str, step: int, abstract_state):
    """Restore the pytree saved by :func:`save_checkpoint`."""
    import orbax.checkpoint as ocp

    path = os.path.join(os.path.abspath(directory), f"step_{step}")
    checkpointer = ocp.StandardCheckpointer()
    return checkpointer.restore(path, abstract_state)


def latest_step(directory: str) -> int | None:
    """Largest completed step in the checkpoint dir.

    Tolerates orbax atomic-save leftovers (``step_N.orbax-checkpoint-
    tmp-<ts>`` from a save interrupted by preemption — exactly the
    scenario this module exists for) and any other non-numeric entries.
    """
    try:
        names = os.listdir(directory)
    except OSError:
        return None
    steps = []
    for name in names:
        if not name.startswith("step_"):
            continue
        suffix = name[len("step_"):]
        if suffix.isdigit():
            steps.append(int(suffix))
    return max(steps) if steps else None


def train_until_drained(step_fn: Callable, state, num_steps: int,
                        watcher: DrainWatcher, checkpoint_dir: str,
                        make_batch: Callable[[int], object],
                        start_step: int = 0,
                        checkpoint_every: int | None = None,
                        on_step: Callable[[int, object], None]
                        | None = None,
                        save_fn: Callable[[str, int, object], object]
                        | None = None) -> tuple[object, int, bool]:
    """Training loop honoring the drain contract.

    Returns ``(state, steps_done, drained)``; saves a checkpoint and stops
    early when the watcher fires, and every ``checkpoint_every`` steps when
    set.  ``on_step(step, state)`` is a logging/metrics hook.  The loop
    (poll between steps, save, exit cleanly) is THE drain-contract loop —
    tpu_autoscaler.workloads.train drives this same function, so fixes to
    the semantics land everywhere at once.
    """
    save = save_fn or save_checkpoint
    step = start_step
    while step < num_steps:
        if watcher.drain_requested():
            save(checkpoint_dir, step, state)
            return state, step, True
        state = step_fn(state, make_batch(step))
        step += 1
        if checkpoint_every and step % checkpoint_every == 0 \
                and step != num_steps:
            save(checkpoint_dir, step, state)
        if on_step is not None:
            on_step(step, state)
    save(checkpoint_dir, step, state)
    return state, step, False
