"""Flagship workload: a pjit-sharded decoder-only transformer LM.

TPU-first by construction:

- bfloat16 compute feeding the MXU; fp32 master params and fp32 loss;
- ``lax.scan`` over stacked layer params (one compiled block, no Python
  loop unrolling, static shapes throughout);
- 2-D ``Mesh`` (data, model): batch sharded over ``data`` (DP), attention
  heads and MLP hidden sharded over ``model`` (Megatron-style TP).
  Shardings are declared with ``NamedSharding``/``PartitionSpec`` and XLA
  inserts the collectives (psum over ``model`` for TP reductions, gradient
  psum over ``data``) — the scaling-book recipe: pick a mesh, annotate,
  let the compiler place collectives on ICI.

The autoscaler's job is to provision the ICI domain this mesh maps onto;
this module is how the repo proves a provisioned slice actually trains.
"""

from __future__ import annotations

import dataclasses
import functools
import warnings
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    vocab: int = 256
    d_model: int = 128
    n_layers: int = 2
    n_heads: int = 4
    d_ff: int = 512
    seq_len: int = 64
    # GQA: number of shared KV heads (llama-family layout); None means
    # n_heads (classic MHA), 1 is MQA.  Shrinks the KV projection and,
    # on the Pallas path, shares KV blocks across the head group at the
    # kernel index-map level.
    n_kv_heads: int | None = None
    # Sliding-window attention (Mistral-family): each position attends
    # to the most recent ``attention_window`` keys only.  None = full
    # causal.  On the Pallas path off-band tiles are skipped (O(s*w)
    # compute); the einsum path applies the band mask.
    attention_window: int | None = None
    dtype: Any = jnp.bfloat16
    # "auto" (default): the fused Pallas flash kernel on TPU, einsum
    # elsewhere.  "einsum" auto-partitions under pjit; "pallas"
    # (workloads/attention.py) keeps scores in VMEM and on real v5e is
    # 1.4x faster per train step at 1.4x the max batch (BENCH_TPU.json).
    # XLA cannot auto-partition a custom kernel, so under a multi-device
    # mesh _block routes it through shard_map (batch x heads); see
    # mesh_shardable for when that is legal.
    attention: str = "auto"
    # Pallas flash-attention tile sizes (ignored on the einsum path).
    # The defaults are sane for v5e at seq 1-2k / head_dim 64-128;
    # bench_tpu.py's attention phase sweeps candidates per shape so a
    # profile-driven run can pin better ones for its geometry.
    attn_block_q: int = 512
    attn_block_k: int = 1024
    # Rotary position embeddings (llama-standard).  Elementwise sin/cos
    # rotations of q/k fuse into the surrounding ops on TPU; applied
    # outside the attention kernel so flash/einsum paths share them.
    rope: bool = True
    rope_theta: float = 10000.0
    # Rematerialize block activations on the backward pass
    # (jax.checkpoint): trades ~1 extra forward of FLOPs per block for
    # O(layers) less activation HBM — the standard long-context /
    # large-batch memory lever on TPU.
    remat: bool = False
    # Chunked cross-entropy: compute the unembedding + softmax over
    # sequence chunks of this size instead of materializing the full
    # [b, s, vocab] fp32 logits (the step's largest activation at LM
    # vocab sizes).  None = full logits; must divide the loss sequence
    # length — the trainer feeds seq_len+1 tokens, so that is seq_len
    # itself — or loss_fn falls back to full logits.
    ce_chunk: int | None = None
    # Mixture-of-experts FFN: when set, every block's dense MLP becomes
    # ``moe_experts`` expert MLPs with top-``moe_top_k`` token routing
    # (workloads/moe.py::route_topk — the ep-sharded layer shares the
    # exact routing rule).  Dispatch is per-sequence (capacity =
    # moe_capacity_factor * seq * k / E per expert per row), which keeps
    # the scatter batch-local so pjit's DP sharding never crosses rows.
    # The router's load-balance and z losses are returned by
    # features_with_aux and folded into loss_fn with the weights below —
    # without them top-k routing collapses onto one expert.
    moe_experts: int | None = None
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_balance_weight: float = 0.01
    moe_z_weight: float = 1e-3

    def __post_init__(self) -> None:
        if self.attention not in {"auto", "einsum", "pallas"}:
            raise ValueError(
                f"unknown attention impl {self.attention!r}; "
                "expected 'auto', 'einsum' or 'pallas'")
        if self.attention_window is not None and self.attention_window < 1:
            raise ValueError(f"attention_window must be >= 1, got "
                             f"{self.attention_window}")
        if self.n_kv_heads is not None and self.n_kv_heads < 1:
            raise ValueError(f"n_kv_heads must be >= 1, got "
                             f"{self.n_kv_heads}")
        if self.ce_chunk is not None and self.ce_chunk < 1:
            raise ValueError(f"ce_chunk must be >= 1, got {self.ce_chunk}")
        if self.moe_experts is not None:
            if self.moe_experts < 2:
                raise ValueError(f"moe_experts must be >= 2, got "
                                 f"{self.moe_experts}")
            if not 1 <= self.moe_top_k <= self.moe_experts:
                raise ValueError(
                    f"moe_top_k must be in [1, {self.moe_experts}], got "
                    f"{self.moe_top_k}")
            if self.moe_capacity_factor <= 0:
                raise ValueError(
                    f"moe_capacity_factor must be > 0, got "
                    f"{self.moe_capacity_factor}")
        if self.n_heads % self.kv_heads:
            raise ValueError(
                f"n_heads ({self.n_heads}) must be a multiple of "
                f"n_kv_heads ({self.kv_heads})")
        if self.rope and self.head_dim % 2:
            raise ValueError(
                f"rope requires an even head_dim, got {self.head_dim} "
                f"(d_model {self.d_model} / n_heads {self.n_heads})")

    def resolved_attention(self) -> str:
        """'auto' -> the fast impl for the ambient backend (resolved at
        trace time, so the choice is baked into each compiled program)."""
        if self.attention != "auto":
            return self.attention
        return "pallas" if jax.default_backend() == "tpu" else "einsum"

    def mesh_shardable(self, mesh: "Mesh") -> bool:
        """Whether the Pallas kernel can run per-shard under ``mesh``.

        The kernel is embarrassingly parallel over batch and heads, so a
        shard_map over (non-model axes -> batch, 'model' -> heads) needs
        every shard to hold whole KV-head groups: both n_heads and
        kv_heads must divide by the 'model' axis size (kv_heads % tp == 0
        also keeps each shard's contiguous query-head range aligned to
        its own KV heads, so the kernel's group index arithmetic is the
        global layout restricted to the shard)."""
        tp = mesh.shape.get("model", 1)
        return self.n_heads % tp == 0 and self.kv_heads % tp == 0

    def resolved_for_mesh(self, mesh: "Mesh") -> "ModelConfig":
        """The config a mesh-sharded step should compile.

        Under a multi-device mesh the custom kernel cannot be
        auto-partitioned by GSPMD, but _block weaves it in through the
        shard_map wrapper (make_sharded_flash_attention), which is legal
        whenever mesh_shardable holds.  'auto' therefore resolves to
        "pallas" on TPU when shardable and to "einsum" otherwise (the
        grouped einsum is what pjit partitions natively); an explicit
        "pallas" that cannot shard is rejected here, at trace-build time,
        rather than failing inside shard_map."""
        if self.attention == "pallas" and mesh.size > 1 \
                and not self.mesh_shardable(mesh):
            raise ValueError(
                f"attention='pallas' cannot shard over mesh "
                f"{dict(mesh.shape)}: n_heads ({self.n_heads}) and "
                f"kv_heads ({self.kv_heads}) must both be multiples of "
                f"the 'model' axis size")
        if self.attention == "auto" and mesh.size > 1:
            use = ("pallas" if jax.default_backend() == "tpu"
                   and self.mesh_shardable(mesh) else "einsum")
            return dataclasses.replace(self, attention=use)
        return self

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads if self.n_kv_heads is not None \
            else self.n_heads


def init_params(key: jax.Array, cfg: ModelConfig) -> dict:
    """Stacked-layer params (leading dim = layer) for lax.scan."""
    k_emb, k_qkv, k_o, k_w1, k_w2, k_out, k_r = jax.random.split(key, 7)
    L, d, f = cfg.n_layers, cfg.d_model, cfg.d_ff

    def norm(k, shape, scale):
        return (jax.random.normal(k, shape, jnp.float32) * scale)

    if cfg.moe_experts is None:
        ffn = {
            "w1": norm(k_w1, (L, d, f), d ** -0.5),
            "w2": norm(k_w2, (L, f, d), f ** -0.5),
        }
    else:
        E = cfg.moe_experts
        ffn = {
            "router": norm(k_r, (L, d, E), 0.02),
            "w1": norm(k_w1, (L, E, d, f), d ** -0.5),
            "w2": norm(k_w2, (L, E, f, d), f ** -0.5),
        }

    return {
        "embed": norm(k_emb, (cfg.vocab, d), 0.02),
        "blocks": {
            # q projection (d wide) + k and v projections (kv_heads *
            # head_dim wide each); equals 3*d for MHA.
            "qkv": norm(k_qkv,
                        (L, d, d + 2 * cfg.kv_heads * cfg.head_dim),
                        d ** -0.5),
            "attn_out": norm(k_o, (L, d, d), d ** -0.5),
            **ffn,
            "ln1": jnp.ones((L, d), jnp.float32),
            "ln2": jnp.ones((L, d), jnp.float32),
        },
        "ln_f": jnp.ones((d,), jnp.float32),
        "unembed": norm(k_out, (d, cfg.vocab), d ** -0.5),
    }


def _rope(x: jax.Array, theta: float, offset=0) -> jax.Array:
    """Rotary embedding over [batch, heads, seq, head_dim] (pairs the
    two halves of head_dim).  Positions are absolute sequence indices
    ``offset .. offset+seq-1``; a (traced) nonzero offset is the decode
    path rotating a new token at its cache position."""
    b, h, s, hd = x.shape
    half = hd // 2
    freqs = theta ** (-jnp.arange(half, dtype=jnp.float32) / half)
    positions = offset + jnp.arange(s, dtype=jnp.float32)
    angles = positions[:, None] * freqs[None, :]
    cos = jnp.cos(angles).astype(x.dtype)                 # [s, half]
    sin = jnp.sin(angles).astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)


def _rmsnorm(x: jax.Array, gain: jax.Array) -> jax.Array:
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * gain.astype(
        x.dtype)


def _split_qkv(y: jax.Array, layer_qkv: jax.Array, cfg: ModelConfig):
    """Project [b, s, d] through the packed qkv weight -> q [b, h, s, hd],
    k/v [b, hkv, s, hd].  The single definition of the GQA packing layout
    (q | k | v, split at [d, d + hkv*hd]) — train (_block) and serve
    (workloads/decode.py) must agree on it byte for byte."""
    b, s, d = y.shape
    h, hd, hkv = cfg.n_heads, cfg.head_dim, cfg.kv_heads
    qkv = jnp.einsum("bsd,de->bse", y, layer_qkv.astype(cfg.dtype))
    q, k, v = jnp.split(qkv, [d, d + hkv * hd], axis=-1)
    q = q.reshape(b, s, h, hd).transpose(0, 2, 1, 3)
    k = k.reshape(b, s, hkv, hd).transpose(0, 2, 1, 3)
    v = v.reshape(b, s, hkv, hd).transpose(0, 2, 1, 3)
    return q, k, v


def moe_ffn(y: jax.Array, layer: dict, cfg: ModelConfig):
    """Top-k MoE FFN over [b, s, d] normed activations.

    Routing is workloads/moe.py::route_topk (the single routing rule in
    the tree); dispatch is per-sequence — each row routes its seq tokens
    into [E, cap, d] buffers via batch-local scatter, experts run as one
    batched einsum over the expert dim (MXU-friendly), and the combine
    gathers each token's k expert outputs gate-weighted.  Per-row
    dispatch keeps every tensor leading-batch so pjit's DP sharding
    passes through untouched; the ep-sharded all_to_all variant lives in
    workloads/moe.py for expert-parallel meshes.

    Returns (out [b, s, d], aux) with scalar balance/z losses averaged
    over rows.  Serving reuses this from decode.py so MoE checkpoints
    decode with the exact training semantics.
    """
    from tpu_autoscaler.workloads.moe import route_topk

    b, s, d = y.shape
    E, k = cfg.moe_experts, cfg.moe_top_k
    cap = max(1, int(cfg.moe_capacity_factor * s * k / E))
    router = layer["router"].astype(jnp.float32)
    logits = jnp.einsum("bsd,de->bse", y.astype(jnp.float32), router)

    w1 = layer["w1"].astype(cfg.dtype)
    w2 = layer["w2"].astype(cfg.dtype)

    def per_row(y_row, logits_row):
        expert, rank, gate, keep, aux = route_topk(logits_row, k, cap)
        safe_rank = jnp.where(keep, rank, 0)
        dispatch = jnp.zeros((E, cap, d), y_row.dtype)
        for c in range(k):
            dispatch = dispatch.at[expert[:, c], safe_rank[:, c]].add(
                jnp.where(keep[:, c, None], y_row, 0.0))
        h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", dispatch, w1))
        out_buf = jnp.einsum("ecf,efd->ecd", h, w2)
        out = jnp.zeros_like(y_row)
        for c in range(k):
            o = out_buf[expert[:, c], safe_rank[:, c]]
            out = out + jnp.where(keep[:, c, None],
                                  gate[:, c, None].astype(o.dtype) * o,
                                  0.0)
        return out, {"balance_loss": aux["balance_loss"],
                     "z_loss": aux["z_loss"]}

    out, aux = jax.vmap(per_row)(y, logits)
    return out, jax.tree.map(jnp.mean, aux)


def _ffn_residual(x: jax.Array, y: jax.Array, layer: dict,
                  cfg: ModelConfig) -> jax.Array:
    """The FFN half of a block (dense gelu MLP or MoE) added onto the
    residual stream; y is the post-ln2 activations.  ONE definition
    shared by the cached-decode and serving bodies (and matching
    _block's training math), so block numerics cannot diverge between
    train and serve."""
    if cfg.moe_experts is None:
        hdn = jnp.einsum("bsd,df->bsf", y, layer["w1"].astype(cfg.dtype))
        hdn = jax.nn.gelu(hdn)
        return x + jnp.einsum("bsf,fd->bsd", hdn,
                              layer["w2"].astype(cfg.dtype))
    ffn_out, _aux = moe_ffn(y, layer, cfg)
    return x + ffn_out


def _block(x: jax.Array, layer: dict, cfg: ModelConfig,
           mesh: Mesh | None = None, ffn=None) -> jax.Array:
    """One transformer block; x: [batch, seq, d_model] in compute dtype.

    Returns ``(x, aux)`` where aux holds the MoE router losses (zeros
    for dense FFN blocks, so the scan carry structure is uniform).

    ``mesh``: when given and multi-device, the Pallas attention path runs
    through shard_map (batch over the non-'model' axes, heads over
    'model') so the fused kernel composes with the pjit-sharded step —
    see make_sharded_flash_attention.

    ``ffn``: optional hook replacing the FFN half: ``ffn(y, layer) ->
    (out, aux)`` on the post-ln2 activations.  Keeps the attention path
    single-sourced for steps that only swap the FFN (the expert-parallel
    train step routes through here with its all_to_all dispatch)."""
    b, s, d = x.shape
    h, hd = cfg.n_heads, cfg.head_dim

    hkv = cfg.kv_heads
    y = _rmsnorm(x, layer["ln1"])
    q, k, v = _split_qkv(y, layer["qkv"], cfg)
    if cfg.rope:
        q = _rope(q, cfg.rope_theta)
        k = _rope(k, cfg.rope_theta)
    def einsum_attn():
        from tpu_autoscaler.workloads.attention import causal_band_mask

        # Grouped einsum (n = KV head, g = query heads per KV head):
        # GQA without materializing repeated K/V — the fallback path for
        # meshes/shapes the kernel cannot shard over and for non-TPU
        # backends, where a repeat would cost the exact HBM the layout
        # exists to save.  pjit partitions these einsums natively.
        qg = q.reshape(b, hkv, h // hkv, s, hd)
        scores = jnp.einsum("bngqd,bnkd->bngqk", qg, k) / np.sqrt(hd)
        causal = causal_band_mask(s, cfg.attention_window)
        scores = jnp.where(causal, scores.astype(jnp.float32), -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        return jnp.einsum("bngqk,bnkd->bngqd", probs, v).reshape(
            b, h, s, hd)

    if cfg.resolved_attention() == "pallas":
        from tpu_autoscaler.workloads.attention import (
            flash_attention,
            make_sharded_flash_attention,
        )

        if mesh is not None and mesh.size > 1:
            batch_axes = data_axes(mesh)
            dp = int(np.prod([mesh.shape[a] for a in batch_axes]))  # analysis: allow=TAJ401 mesh axis sizes are static ints
            if b % dp or not cfg.mesh_shardable(mesh):
                # shard_map cannot split an uneven batch (GSPMD pads;
                # shard_map does not) nor a head count the 'model' axis
                # doesn't divide.  make_sharded_train_step rejects the
                # latter up front (resolved_for_mesh); direct forward()
                # callers get the same safety net here: keep training on
                # the einsum path rather than failing mid-trace.
                why = (f"global batch {b} is not divisible by the "
                       f"{dp}-way data parallelism"
                       if b % dp else
                       f"heads ({h} q / {hkv} kv) do not divide by the "
                       f"'model' axis")
                warnings.warn(
                    f"attention='pallas': {why} of mesh "
                    f"{dict(mesh.shape)}; falling back to einsum "
                    f"attention for this step", stacklevel=2)
                attn = einsum_attn()
            else:
                attn = make_sharded_flash_attention(
                    mesh, causal=True, window=cfg.attention_window,
                    block_q=cfg.attn_block_q, block_k=cfg.attn_block_k,
                    batch_axis=batch_axes,
                    head_axis=("model" if "model" in mesh.axis_names
                               else None),
                )(q, k, v)
        else:
            attn = flash_attention(
                q, k, v, causal=True, window=cfg.attention_window,
                block_q=cfg.attn_block_q, block_k=cfg.attn_block_k,
                interpret=jax.default_backend() != "tpu")
    else:
        attn = einsum_attn()
    attn = attn.transpose(0, 2, 1, 3).reshape(b, s, d)
    x = x + jnp.einsum("bsd,de->bse", attn,
                       layer["attn_out"].astype(cfg.dtype))

    y = _rmsnorm(x, layer["ln2"])
    if ffn is not None:
        ffn_out, aux = ffn(y, layer)
        x = x + ffn_out
    elif cfg.moe_experts is None:
        hdn = jnp.einsum("bsd,df->bsf", y, layer["w1"].astype(cfg.dtype))
        hdn = jax.nn.gelu(hdn)
        x = x + jnp.einsum("bsf,fd->bsd", hdn,
                           layer["w2"].astype(cfg.dtype))
        aux = {"balance_loss": jnp.zeros((), jnp.float32),
               "z_loss": jnp.zeros((), jnp.float32)}
    else:
        ffn_out, aux = moe_ffn(y, layer, cfg)
        x = x + ffn_out
    return x, aux


def features_with_aux(params: dict, tokens: jax.Array, cfg: ModelConfig,
                      mesh: Mesh | None = None):
    """tokens [batch, seq] int32 -> (final-norm features [batch, seq,
    d_model] in compute dtype, aux dict of per-layer-MEAN router
    losses)."""
    x = params["embed"].astype(cfg.dtype)[tokens]

    block = functools.partial(_block, cfg=cfg, mesh=mesh)
    if cfg.remat:
        block = jax.checkpoint(block)

    def body(x, layer):
        x, aux = block(x, layer)
        return x, aux

    x, aux_stacked = jax.lax.scan(body, x, params["blocks"])
    aux = jax.tree.map(jnp.mean, aux_stacked)
    return _rmsnorm(x, params["ln_f"]), aux


def features(params: dict, tokens: jax.Array, cfg: ModelConfig,
             mesh: Mesh | None = None) -> jax.Array:
    """tokens [batch, seq] int32 -> final-norm features [batch, seq,
    d_model] in compute dtype (everything before the unembedding)."""
    return features_with_aux(params, tokens, cfg, mesh)[0]


def forward(params: dict, tokens: jax.Array, cfg: ModelConfig,
            mesh: Mesh | None = None) -> jax.Array:
    """tokens [batch, seq] int32 -> logits [batch, seq, vocab] fp32."""
    x = features(params, tokens, cfg, mesh)
    logits = jnp.einsum("bsd,dv->bsv", x,
                        params["unembed"].astype(cfg.dtype))
    return logits.astype(jnp.float32)


def _chunked_ce(x: jax.Array, unembed: jax.Array, targets: jax.Array,
                chunk: int, dtype) -> jax.Array:
    """Cross-entropy without materializing [b, s, vocab] logits.

    The full-vocab logits tensor is the largest single activation of the
    train step (b*s*V fp32 — ~2 GiB at b16/s1024/V32k, plus its
    gradient); scanning the unembedding over sequence chunks keeps only
    [b, chunk, V] live at a time, trading one big matmul for s/chunk
    serial ones of the same total FLOPs — the standard HBM lever for
    large-vocab LMs (same spirit as cfg.remat for the blocks).
    """
    b, s, d = x.shape
    n = s // chunk
    xc = x.reshape(b, n, chunk, d).transpose(1, 0, 2, 3)
    tc = targets.reshape(b, n, chunk).transpose(1, 0, 2)

    def body(total, inp):
        xi, ti = inp
        logits = jnp.einsum("bcd,dv->bcv", xi, unembed.astype(dtype)
                            ).astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, ti[..., None], axis=-1)[..., 0]
        return total + jnp.sum(lse - tgt), None

    total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), (xc, tc))
    return total / (b * s)


def loss_and_metrics(params: dict, tokens: jax.Array, cfg: ModelConfig,
                     mesh: Mesh | None = None):
    """Training loss and its decomposition.

    Returns ``(loss, metrics)``: loss = next-token cross-entropy plus,
    for MoE configs, the weighted router load-balance and z losses
    (without which top-k routing collapses onto one expert); metrics
    reports each term unweighted.

    With ``cfg.ce_chunk`` set (and dividing seq) the unembedding +
    softmax run chunked over the sequence (_chunked_ce); otherwise the
    straightforward full-logits form.
    """
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    s = inputs.shape[1]
    x, aux = features_with_aux(params, inputs, cfg, mesh)
    if cfg.ce_chunk is not None and s % cfg.ce_chunk == 0:
        ce = _chunked_ce(x, params["unembed"], targets, cfg.ce_chunk,
                         cfg.dtype)
    else:
        logits = jnp.einsum("bsd,dv->bsv", x,
                            params["unembed"].astype(cfg.dtype)
                            ).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
        ce = jnp.mean(nll)
    loss = ce
    metrics = {"ce": ce, **aux}
    if cfg.moe_experts is not None:
        loss = (loss + cfg.moe_balance_weight * aux["balance_loss"]
                + cfg.moe_z_weight * aux["z_loss"])
    return loss, metrics


def loss_fn(params: dict, tokens: jax.Array, cfg: ModelConfig,
            mesh: Mesh | None = None) -> jax.Array:
    """Next-token cross-entropy (+ weighted MoE router losses)."""
    return loss_and_metrics(params, tokens, cfg, mesh)[0]


# ---- optimizer ----------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Optimizer hyperparameters for a REAL training run.

    The defaults reproduce the bare ``optax.adamw(1e-3)`` the trainer
    used before schedules existed, so every existing caller/checkpoint
    is unchanged unless it opts in.

    - ``warmup_steps`` / ``decay_steps``: linear warmup from 0 to
      ``learning_rate`` then, when ``decay_steps`` is set, cosine decay
      to ``learning_rate * min_lr_ratio`` by step ``decay_steps``
      (warmup included — pass the run's total steps).  Both are counted
      in TRAINER steps (microbatches), even with ``accum_steps > 1``:
      make_optimizer rescales the schedule so accumulation never
      stretches the warmup/decay horizon.  Without ``decay_steps`` the
      LR holds constant after warmup.
    - ``grad_clip``: global-norm gradient clipping (applied before the
      Adam update, the standard LM stabilizer).
    - ``accum_steps``: gradient accumulation — every k-th step applies
      the mean of the last k microbatch gradients (optax.MultiSteps);
      multiplies the effective batch without multiplying live HBM.
    """

    learning_rate: float = 1e-3
    warmup_steps: int = 0
    decay_steps: int | None = None
    min_lr_ratio: float = 0.1
    weight_decay: float = 1e-4          # optax.adamw's default
    b1: float = 0.9
    b2: float = 0.999
    grad_clip: float | None = None
    accum_steps: int = 1

    def __post_init__(self) -> None:
        if self.warmup_steps < 0:
            raise ValueError(f"warmup_steps must be >= 0, got "
                             f"{self.warmup_steps}")
        if self.decay_steps is not None \
                and self.decay_steps <= self.warmup_steps:
            raise ValueError(
                f"decay_steps ({self.decay_steps}) must exceed "
                f"warmup_steps ({self.warmup_steps})")
        if self.grad_clip is not None and self.grad_clip <= 0:
            raise ValueError(f"grad_clip must be > 0, got {self.grad_clip}")
        if self.accum_steps < 1:
            raise ValueError(f"accum_steps must be >= 1, got "
                             f"{self.accum_steps}")

    def schedule(self):
        """The LR as an optax schedule fn (step -> lr), or a constant."""
        peak = self.learning_rate
        if self.decay_steps is not None:
            return optax.warmup_cosine_decay_schedule(
                init_value=0.0, peak_value=peak,
                warmup_steps=self.warmup_steps,
                decay_steps=self.decay_steps,
                end_value=peak * self.min_lr_ratio)
        if self.warmup_steps:
            return optax.join_schedules(
                [optax.linear_schedule(0.0, peak, self.warmup_steps),
                 optax.constant_schedule(peak)],
                [self.warmup_steps])
        return peak

    def lr_at(self, step: int) -> float:
        """Host-side LR readout for logging."""
        sched = self.schedule()
        return float(sched(step)) if callable(sched) else float(sched)


def make_optimizer(train: TrainConfig):
    """The trainer's optax chain: [clip ->] adamw(schedule) [-> accum].

    With accumulation, the inner optimizer's step count advances once
    per ``accum_steps`` microbatches, so the schedule is evaluated at
    ``count * accum_steps`` — keeping TrainConfig's warmup/decay
    horizons in trainer steps regardless of accumulation.
    """
    sched = train.schedule()
    if callable(sched) and train.accum_steps > 1:
        inner, k = sched, train.accum_steps
        sched = lambda count: inner(count * k)  # noqa: E731
    tx = optax.adamw(sched, b1=train.b1, b2=train.b2,
                     weight_decay=train.weight_decay)
    if train.grad_clip is not None:
        tx = optax.chain(optax.clip_by_global_norm(train.grad_clip), tx)
    if train.accum_steps > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=train.accum_steps)
    return tx


# ---- sharding -----------------------------------------------------------

def make_mesh(devices=None, tp: int | None = None) -> Mesh:
    """2-D (data, model) mesh over the given devices.

    tp defaults to 2 when the device count allows — enough to exercise real
    tensor-parallel collectives — with the rest data-parallel.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if tp is None:
        tp = 2 if n % 2 == 0 and n >= 2 else 1
    dp = n // tp
    arr = np.asarray(devices[: dp * tp]).reshape(dp, tp)
    return Mesh(arr, axis_names=("data", "model"))


def param_specs(cfg: ModelConfig) -> dict:
    """PartitionSpecs: Megatron TP over the 'model' axis."""
    if cfg.moe_experts is None:
        ffn = {
            "w1": P(None, None, "model"),        # column-parallel
            "w2": P(None, "model", None),        # row-parallel
        }
    else:
        # Experts replicate over 'model'; TP splits each expert's d_ff
        # (same column/row-parallel pattern as the dense MLP, one expert
        # dim to the left).  The router is tiny and replicates.
        ffn = {
            "router": P(None, None, None),
            "w1": P(None, None, None, "model"),
            "w2": P(None, None, "model", None),
        }
    return {
        "embed": P(None, "model"),
        "blocks": {
            "qkv": P(None, None, "model"),       # heads split
            "attn_out": P(None, "model", None),  # row-parallel
            **ffn,
            "ln1": P(None, None),
            "ln2": P(None, None),
        },
        "ln_f": P(None),
        "unembed": P(None, "model"),
    }


def data_axes(mesh: Mesh) -> tuple:
    """The mesh axes that carry batch: every axis except 'model'.

    The single source of the DP-axis rule — batch_spec (step I/O
    sharding) and _block's shard_map attention path (kernel batch
    sharding) both derive from it, so they cannot diverge.
    """
    return tuple(n for n in mesh.axis_names if n != "model")


def batch_spec(mesh: Mesh | None = None) -> P:
    """Batch sharding: every mesh axis except 'model' is data-parallel.

    On a plain (data, model) mesh this is P("data", None); on a
    multi-slice (dcn, data, model) mesh the batch shards over
    ("dcn", "data") — DP across slices over DCN, TP inside each slice
    over ICI (workloads/distributed.py).
    """
    if mesh is None:
        return P("data", None)
    return P(data_axes(mesh), None)


def _zero1_spec(spec: P, shape: tuple, mesh: Mesh,
                skip_axes: tuple = ()) -> P:
    """Data-axis sharding for one param-shaped buffer (ZeRO/FSDP).

    Keep the param's TP sharding and additionally shard the first
    still-replicated axis whose size divides the total data parallelism
    over the data axes.  ``skip_axes`` excludes axes that must stay
    whole (the stacked-layer scan axis: slicing it per-device would put
    a cross-device gather inside every scan iteration).  If no axis
    qualifies (tiny ln gains), the buffer stays param-sharded —
    correct, just not sliced.
    """
    daxes = data_axes(mesh)
    dp = int(np.prod([mesh.shape[a] for a in daxes])) if daxes else 1
    if dp <= 1:
        return spec
    entries = list(spec) + [None] * (len(shape) - len(spec))
    for i, (dim, entry) in enumerate(zip(shape, entries)):
        if i in skip_axes:
            continue
        if entry is None and dim % dp == 0:
            entries[i] = daxes if len(daxes) > 1 else daxes[0]
            return P(*entries)
    return spec


def fsdp_param_specs(cfg: ModelConfig, mesh: Mesh) -> dict:
    """FSDP/ZeRO-3 PartitionSpecs: TP sharding plus a data-axis shard on
    each param's first eligible replicated axis.

    Declared entirely through in/out shardings on the jitted step — the
    GSPMD way: XLA all-gathers each layer's weight shard on use inside
    the ``lax.scan`` body (one layer live at a time, the FSDP access
    pattern for free) and reduce-scatters its gradient, with no
    hand-written collectives.  Per-device param/grad/moment HBM drops by
    the DP degree — the lever that fits ≥0.5B-param models on one v5e
    chip's 16 GiB.  The stacked-layer axis (axis 0 of every ``blocks``
    leaf) is never sharded: it is the scan axis.
    """
    shapes = jax.eval_shape(
        functools.partial(init_params, cfg=cfg), jax.random.PRNGKey(0))
    specs = param_specs(cfg)

    def one(path, spec):
        leaf = shapes
        for k in path:
            leaf = leaf[k.key]
        skip = (0,) if path and path[0].key == "blocks" else ()
        return _zero1_spec(spec, leaf.shape, mesh, skip_axes=skip)

    return jax.tree_util.tree_map_with_path(
        one, specs, is_leaf=lambda x: isinstance(x, P))


def _opt_state_shardings(optimizer, params: dict, p_specs: dict,
                         mesh: Mesh, zero1: bool):
    """NamedShardings for the optimizer state.

    The moment buffers inside optax's state mirror the param tree as
    sub-trees, so each array leaf's path ends with the dict-key path of
    its param — match on that suffix to give every moment its param's
    spec (plus the ZeRO-1 data-axis slice when requested).  Leaves with
    no param suffix (step counts) replicate.
    """
    flat_specs = {
        tuple(k.key for k in path): spec
        for path, spec in jax.tree_util.tree_flatten_with_path(
            p_specs, is_leaf=lambda x: isinstance(x, P))[0]
    }
    shapes = jax.eval_shape(optimizer.init, params)

    def leaf_sharding(path, leaf):
        dict_suffix = tuple(
            k.key for k in path
            if isinstance(k, jax.tree_util.DictKey))
        spec = flat_specs.get(dict_suffix)
        if spec is None or leaf.ndim == 0:
            return NamedSharding(mesh, P())
        if zero1:
            spec = _zero1_spec(spec, leaf.shape, mesh)
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(leaf_sharding, shapes)


def opt_state_shardings(cfg: ModelConfig, optimizer, p_specs: dict,
                        mesh: Mesh, zero1: bool):
    """NamedShardings for ``optimizer``'s state given the params'
    PartitionSpecs — the one place the eval_shape + moment-suffix
    matching happens (model, pipeline and sp steps all build their
    optimizer shardings here)."""
    abstract = jax.eval_shape(
        functools.partial(init_params, cfg=cfg), jax.random.PRNGKey(0))
    return _opt_state_shardings(optimizer, abstract, p_specs, mesh,
                                zero1)


def make_sharded_train_step(mesh: Mesh, cfg: ModelConfig,
                            learning_rate: float = 1e-3,
                            zero1: bool = False,
                            train: TrainConfig | None = None,
                            shard: str | None = None):
    """Build (init_fn, step_fn) jitted over ``mesh`` with real DP+TP
    shardings.  step_fn: (params, opt_state, tokens) -> (params, opt_state,
    loss).  ``attention="auto"`` is resolved per the mesh — see
    ModelConfig.resolved_for_mesh.

    ``train``: the full optimizer recipe (LR schedule, clipping,
    accumulation — see TrainConfig); defaults to bare
    adamw(``learning_rate``) for backward compatibility.

    ``shard`` — how much state shards over the data axes (all declared
    through in/out shardings; XLA inserts the reduce-scatters and
    all-gathers, no hand-written collectives):

    - ``"none"``: params/grads/moments replicated over data (pure DP+TP).
    - ``"zero1"``: AdamW moment buffers (2x param bytes, fp32) shard
      over the data axes on top of their TP sharding; params and grads
      stay replicated.  XLA lowers the gradient psum into
      reduce-scatter ahead of the sharded moment update and all-gathers
      the updates back into the replicated params.
    - ``"fsdp"``: params, grads AND moments shard over the data axes
      (ZeRO-3, see fsdp_param_specs) — per-layer all-gather inside the
      scan on the forward/backward, reduce-scattered grads, per-device
      state HBM divided by the DP degree.

    ``zero1=True`` is the legacy spelling of ``shard="zero1"``.
    """
    if shard is None:
        shard = "zero1" if zero1 else "none"
    if shard not in {"none", "zero1", "fsdp"}:
        raise ValueError(f"unknown shard mode {shard!r}; expected "
                         "'none', 'zero1' or 'fsdp'")
    cfg = cfg.resolved_for_mesh(mesh)
    if train is None:
        train = TrainConfig(learning_rate=learning_rate)
    optimizer = make_optimizer(train)
    p_specs = (fsdp_param_specs(cfg, mesh) if shard == "fsdp"
               else param_specs(cfg))
    p_shard = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec), p_specs,
        is_leaf=lambda x: isinstance(x, P))
    b_shard = NamedSharding(mesh, batch_spec(mesh))
    replicated = NamedSharding(mesh, P())
    o_shard = opt_state_shardings(cfg, optimizer, p_specs, mesh,
                                  shard == "zero1")

    def init(key):
        params = init_params(key, cfg)
        return params, optimizer.init(params)

    attn_mesh = mesh if cfg.resolved_attention() == "pallas" else None

    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, cfg,
                                                  attn_mesh)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    init_jit = jax.jit(init, out_shardings=(p_shard, o_shard))
    step_jit = jax.jit(
        step,
        in_shardings=(p_shard, o_shard, b_shard),
        out_shardings=(p_shard, o_shard, replicated),
        donate_argnums=(0, 1),
    )
    return init_jit, step_jit
