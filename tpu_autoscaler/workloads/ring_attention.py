"""Ring attention: sequence-parallel causal attention over a mesh axis.

Long-context story for the in-tree workload (and the reason the autoscaler
is slice-atomic in the first place): when a sequence is too long for one
chip's HBM, shard it over the ICI ring.  Each device holds a sequence
block of Q, K, V; K/V blocks rotate around the ring via ``lax.ppermute``
(one ICI hop per step) while each device accumulates its Q block's
attention with an online-softmax running (max, sum, acc) — so the full
[s, s] score matrix never exists anywhere and the per-device memory is
O(s_local²) compute-transient, O(s_local·d) resident.

Composes with the round-2 attention features: K/V may carry fewer heads
than Q (GQA/MQA — the ring then also moves group-times less ICI traffic),
and ``window=w`` restricts each query to its w most recent keys, with
fully-out-of-window hops skipped entirely (compute AND ppermute payload
still rotate, but the merge is elided, so compute scales with the live
band).

This is exactly the communication pattern the autoscaler must never
bisect: the ring rides the ICI torus of ONE slice (provision atomically,
drain atomically).  Multi-slice jobs keep sequence parallelism inside each
slice and data/model parallelism across slices over DCN.

Built with ``shard_map`` so the collective schedule is explicit; composes
with data/model axes by adding them to the in/out specs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _hop_mode(src, my_idx, s_loc: int, causal: bool, window):
    """(mode, offset) for the hop whose visiting K/V block originated at
    ``src``: mode 0 = invisible (skip the merge entirely), 1 = partially
    masked (apply the causal/window mask), 2 = fully visible.  offset =
    global(q_block_start) - global(k_block_start) = (my - src)·s_loc, the
    single number the element-level mask needs.

    Causality hides src > my.  A window additionally hides blocks whose
    NEWEST key is already >= window behind this block's OLDEST query
    (offset - (s_loc-1) >= w), and forces masking on the diag block and
    on any block the window cuts through (offset + s_loc - 1 >= w)."""
    offset = (my_idx - src) * s_loc
    if not causal:
        return jnp.int32(2), offset  # window requires causal (validated)
    skip = src > my_idx
    needs_mask = offset == 0
    if window is not None:
        skip |= offset - (s_loc - 1) >= window
        needs_mask |= offset + s_loc - 1 >= window
    return jnp.where(skip, 0, jnp.where(needs_mask, 1, 2)), offset


def _ring_driver(q, k, v, *, axis_name: str, causal: bool, window, merge):
    """The ring schedule, shared by the einsum and pallas impls.

    ``merge(k_t, v_t, m, l, acc, offset=, masked=)`` folds one visiting
    K/V block into the online-softmax carry (``masked`` is static — the
    lax.switch branch — ``offset`` traced); the driver owns everything
    else — src computation, hop-visibility dispatch (invisible hops are
    SKIPPED entirely, not masked), the ppermute rotation, carry init,
    and the final normalization — so the two impls cannot drift apart on
    schedule or numerics.

    Returns (out [b,h,s_loc,d], lse [b,h,s_loc,1] f32) — the logsumexp
    the blocked backward's recompute-p needs.
    """
    from tpu_autoscaler.workloads._shard_utils import pvary

    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape

    def step(t, carry):
        m, l, acc, k_t, v_t = carry
        # k_t/v_t originated on device (my_idx - t) mod axis_size.
        src = (my_idx - t) % axis_size
        mode, offset = _hop_mode(src, my_idx, s_loc, causal, window)
        m, l, acc = jax.lax.switch(
            mode,
            [lambda c: c[:3],
             lambda c: merge(c[3], c[4], *c[:3], offset=c[5], masked=True),
             lambda c: merge(c[3], c[4], *c[:3], offset=c[5],
                             masked=False)],
            (m, l, acc, k_t, v_t, offset))
        # Rotate K/V one hop around the ring (ICI neighbor exchange).
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        k_next = jax.lax.ppermute(k_t, axis_name, perm)
        v_next = jax.lax.ppermute(v_t, axis_name, perm)
        return m, l, acc, k_next, v_next

    # pvary: the accumulators are per-device state (they will differ across
    # the ring), so mark them varying over the axis or the fori_loop carry
    # types mismatch under shard_map's varying-axis tracking.
    m0 = pvary(jnp.full((b, h, s_loc, 1), NEG_INF, jnp.float32), axis_name)
    l0 = pvary(jnp.zeros((b, h, s_loc, 1), jnp.float32), axis_name)
    acc0 = pvary(jnp.zeros((b, h, s_loc, d), jnp.float32), axis_name)
    m, l, acc, _, _ = jax.lax.fori_loop(
        0, axis_size, step, (m0, l0, acc0, k, v))
    l_safe = jnp.maximum(l, 1e-30)
    out = (acc / l_safe).astype(q.dtype)
    return out, m + jnp.log(l_safe)


def _ring_attn_local(q, k, v, *, axis_name: str, causal: bool, window,
                     sm_scale: float):
    """Per-device body under shard_map: einsum per-hop merge.

    q: [b, h, s_local, d]; k, v: [b, h_kv, s_local, d] (GQA when
    h_kv < h — the einsum runs grouped so K/V are never repeated).
    """
    b, h, s_loc, d = q.shape
    h_kv = k.shape[1]
    g = h // h_kv
    qf5 = (q.astype(jnp.float32) * sm_scale).reshape(b, h_kv, g, s_loc, d)

    def merge(k_t, v_t, m, l, acc, *, offset, masked):
        from tpu_autoscaler.workloads.attention import _rel_mask

        kf = k_t.astype(jnp.float32)
        scores = jnp.einsum("bngqd,bnkd->bngqk", qf5, kf).reshape(
            b, h, s_loc, -1)                               # [b,h,sq,sk]
        if masked:
            scores = _rel_mask(scores, offset, window)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
        p = jnp.exp(scores - m_new)
        correction = jnp.exp(m - m_new)
        l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum("bngqk,bnkd->bngqd",
                        p.reshape(b, h_kv, g, s_loc, -1),
                        v_t.astype(jnp.float32)).reshape(b, h, s_loc, d)
        acc_new = acc * correction + pv
        return m_new, l_new, acc_new

    return _ring_driver(q, k, v, axis_name=axis_name, causal=causal,
                        window=window, merge=merge)


def _ring_attn_local_pallas(q, k, v, *, axis_name: str, causal: bool,
                            window, block_q: int, interpret: bool):
    """Per-device body: the same ring schedule with the per-hop math
    fused into the Pallas ring-step kernel (attention.py::
    ring_flash_step) — the [s_local, s_local] score block of each hop
    lives in VMEM only, never HBM."""
    from tpu_autoscaler.workloads.attention import ring_flash_step

    def merge(k_t, v_t, m, l, acc, *, offset, masked):
        return ring_flash_step(q, k_t, v_t, m, l, acc, offset=offset,
                               masked=masked, window=window,
                               block_q=block_q, interpret=interpret)

    return _ring_driver(q, k, v, axis_name=axis_name, causal=causal,
                        window=window, merge=merge)


def _ring_bwd_local_pallas(q, k, v, do, lse, delta, *, axis_name: str,
                           causal: bool, window, block_q: int,
                           interpret: bool):
    """Per-device blocked backward ring: the same hop schedule run once
    more, with each hop's dq/dk/dv computed by the fused recompute-p
    kernels (attention.py::ring_flash_bwd_step) from the forward's saved
    lse — NOT by recomputing the forward.  dq accumulates locally; dk/dv
    accumulate into buffers that rotate WITH their K/V block, so after
    axis_size hops each block's gradient arrives back home."""
    from tpu_autoscaler.workloads._shard_utils import pvary
    from tpu_autoscaler.workloads.attention import ring_flash_bwd_step

    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    h_kv = k.shape[1]

    def hop(masked):
        def run(c):
            return ring_flash_bwd_step(
                q, c[0], c[1], do, lse, delta, offset=c[2], masked=masked,
                window=window, block_q=block_q, interpret=interpret)

        return run

    def skip(c):
        return (jnp.zeros((b, h, s_loc, d), jnp.float32),
                jnp.zeros((b, h_kv, s_loc, d), jnp.float32),
                jnp.zeros((b, h_kv, s_loc, d), jnp.float32))

    def step(t, carry):
        dq, k_t, v_t, dk_t, dv_t = carry
        src = (my_idx - t) % axis_size
        mode, offset = _hop_mode(src, my_idx, s_loc, causal, window)
        dq_add, dk_add, dv_add = jax.lax.switch(
            mode, [skip, hop(True), hop(False)], (k_t, v_t, offset))
        dq += dq_add
        dk_t += dk_add
        dv_t += dv_add
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        k_t = jax.lax.ppermute(k_t, axis_name, perm)
        v_t = jax.lax.ppermute(v_t, axis_name, perm)
        dk_t = jax.lax.ppermute(dk_t, axis_name, perm)
        dv_t = jax.lax.ppermute(dv_t, axis_name, perm)
        return dq, k_t, v_t, dk_t, dv_t

    dq0 = pvary(jnp.zeros((b, h, s_loc, d), jnp.float32), axis_name)
    dk0 = pvary(jnp.zeros((b, h_kv, s_loc, d), jnp.float32), axis_name)
    dv0 = pvary(jnp.zeros((b, h_kv, s_loc, d), jnp.float32), axis_name)
    dq, _, _, dk, dv = jax.lax.fori_loop(
        0, axis_size, step, (dq0, k, v, dk0, dv0))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def make_local_ring_attention(*, axis_name: str, causal: bool = True,
                              window: int | None = None,
                              block_q: int = 128,
                              interpret: bool = False):
    """Per-device pallas ring attention for use INSIDE a caller-owned
    shard_map (the sp train step embeds it in a full model step):
    ``attn(q, k, v) -> out`` on this device's sequence shard, with a
    custom_vjp running the blocked backward ring (the pallas kernels
    have no AD rules; the recompute-p backward from the saved lse is
    both the differentiation rule and the right economics).

    Validates like the other public entries (_validate_attention_args'
    rules): window requires causal here at build time — _hop_mode
    treats causal=False as fully-visible and would silently ignore the
    window — and the per-call shape checks (GQA head divisibility, k/v
    match) run on the local shards inside ``attn``."""
    from tpu_autoscaler.workloads.attention import _validate_attention_args

    if window is not None and (not causal or window < 1):
        raise ValueError(
            f"window={window} requires causal=True and window >= 1")

    def _check_shapes(q, k, v):
        _validate_attention_args(q, k, v, causal, window)

    @jax.custom_vjp
    def attn(q, k, v):
        _check_shapes(q, k, v)
        out, _ = _ring_attn_local_pallas(
            q, k, v, axis_name=axis_name, causal=causal, window=window,
            block_q=block_q, interpret=interpret)
        return out

    def attn_fwd(q, k, v):
        _check_shapes(q, k, v)
        out, lse = _ring_attn_local_pallas(
            q, k, v, axis_name=axis_name, causal=causal, window=window,
            block_q=block_q, interpret=interpret)
        return out, (q, k, v, out, lse)

    def attn_bwd(residuals, g):
        q, k, v, o, lse = residuals
        delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1, keepdims=True)
        return _ring_bwd_local_pallas(
            q, k, v, g, lse, delta, axis_name=axis_name, causal=causal,
            window=window, block_q=block_q, interpret=interpret)

    attn.defvjp(attn_fwd, attn_bwd)
    return attn


def make_ring_attention(mesh: Mesh, seq_axis: str = "sp",
                        causal: bool = True, impl: str = "einsum",
                        window: int | None = None,
                        block_q: int = 128,
                        interpret: bool | None = None):
    """Build a ring-attention callable for q [b, h, s, d] / k, v
    [b, kv_heads, s, d] arrays whose sequence axis is sharded over
    ``mesh``'s ``seq_axis``.

    Returns a function operating on GLOBAL arrays; shard_map handles the
    decomposition and the ppermute schedule rides the mesh axis.

    ``kv_heads`` may divide ``h`` (GQA; MQA at 1) — the rotating K/V
    payload then also shrinks by the group factor.  ``window=w``
    (requires causal) is sliding-window attention with out-of-window
    hops skipped.

    ``impl``:

    - ``"einsum"`` (default) — XLA-fused per-hop math, differentiable
      end-to-end through the ring (AD transposes the ppermute schedule).
    - ``"pallas"`` — each hop's QK^T→softmax-merge→PV is one fused VMEM
      kernel (attention.py::ring_flash_step), so no per-hop score block
      round-trips HBM; the backward is a second blocked ring
      (ring_flash_bwd_step) rebuilding probabilities from the saved
      logsumexp — the recompute-p flash backward, NOT a forward
      recompute — so training cost matches the single-device flash
      kernel's economics.
    """
    if impl not in {"einsum", "pallas"}:
        raise ValueError(f"unknown ring attention impl {impl!r}")
    from tpu_autoscaler.workloads.attention import _validate_attention_args

    spec = P(None, None, seq_axis, None)

    def einsum_body(q, k, v):
        d = q.shape[-1]
        return _ring_attn_local(q, k, v, axis_name=seq_axis,
                                causal=causal, window=window,
                                sm_scale=d ** -0.5)

    def einsum_attn(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
        _validate_attention_args(q, k, v, causal, window)
        out, _lse = jax.shard_map(
            einsum_body, mesh=mesh, in_specs=(spec, spec, spec),
            out_specs=(spec, spec),
        )(q, k, v)
        return out

    if impl == "einsum":
        return einsum_attn

    run_interpret = (jax.default_backend() != "tpu"
                     if interpret is None else interpret)
    local = make_local_ring_attention(
        axis_name=seq_axis, causal=causal, window=window,
        block_q=block_q, interpret=run_interpret)

    def checked(q, k, v):
        _validate_attention_args(q, k, v, causal, window)
        # check_vma=False: pallas_call's out_shape carries no
        # varying-axis metadata.
        return jax.shard_map(
            local, mesh=mesh, in_specs=(spec, spec, spec),
            out_specs=spec, check_vma=False,
        )(q, k, v)

    return checked
