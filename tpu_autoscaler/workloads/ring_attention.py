"""Ring attention: sequence-parallel causal attention over a mesh axis.

Long-context story for the in-tree workload (and the reason the autoscaler
is slice-atomic in the first place): when a sequence is too long for one
chip's HBM, shard it over the ICI ring.  Each device holds a sequence
block of Q, K, V; K/V blocks rotate around the ring via ``lax.ppermute``
(one ICI hop per step) while each device accumulates its Q block's
attention with an online-softmax running (max, sum, acc) — so the full
[s, s] score matrix never exists anywhere and the per-device memory is
O(s_local²) compute-transient, O(s_local·d) resident.

This is exactly the communication pattern the autoscaler must never
bisect: the ring rides the ICI torus of ONE slice (provision atomically,
drain atomically).  Multi-slice jobs keep sequence parallelism inside each
slice and data/model parallelism across slices over DCN.

Built with ``shard_map`` so the collective schedule is explicit; composes
with data/model axes by adding them to the in/out specs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _ring_driver(q, k, v, *, axis_name: str, causal: bool, merge):
    """The ring schedule, shared by the einsum and pallas impls.

    ``merge(k_t, v_t, m, l, acc, diag)`` folds one visiting K/V block
    into the online-softmax carry; the driver owns everything else —
    src computation, hop-visibility dispatch (a causal ring SKIPS
    invisible hops entirely instead of masking them), the ppermute
    rotation, carry init, and the final normalization — so the two
    impls cannot drift apart on schedule or numerics.
    """
    from tpu_autoscaler.workloads._shard_utils import pvary

    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape

    def step(t, carry):
        m, l, acc, k_t, v_t = carry
        # k_t/v_t originated on device (my_idx - t) mod axis_size.
        src = (my_idx - t) % axis_size
        if causal:
            # 0: later block (invisible) — skip the merge entirely;
            # 1: own block — lower-triangular; 2: earlier — all visible.
            mode = jnp.where(src > my_idx, 0,
                             jnp.where(src == my_idx, 1, 2))
            m, l, acc = jax.lax.switch(
                mode,
                [lambda c: c[:3],
                 lambda c: merge(c[3], c[4], *c[:3], diag=True),
                 lambda c: merge(c[3], c[4], *c[:3], diag=False)],
                (m, l, acc, k_t, v_t))
        else:
            m, l, acc = merge(k_t, v_t, m, l, acc, diag=False)
        # Rotate K/V one hop around the ring (ICI neighbor exchange).
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        k_next = jax.lax.ppermute(k_t, axis_name, perm)
        v_next = jax.lax.ppermute(v_t, axis_name, perm)
        return m, l, acc, k_next, v_next

    # pvary: the accumulators are per-device state (they will differ across
    # the ring), so mark them varying over the axis or the fori_loop carry
    # types mismatch under shard_map's varying-axis tracking.
    m0 = pvary(jnp.full((b, h, s_loc, 1), NEG_INF, jnp.float32), axis_name)
    l0 = pvary(jnp.zeros((b, h, s_loc, 1), jnp.float32), axis_name)
    acc0 = pvary(jnp.zeros((b, h, s_loc, d), jnp.float32), axis_name)
    m, l, acc, _, _ = jax.lax.fori_loop(
        0, axis_size, step, (m0, l0, acc0, k, v))
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def _ring_attn_local(q, k, v, *, axis_name: str, causal: bool,
                     sm_scale: float):
    """Per-device body under shard_map: einsum per-hop merge.

    q, k, v: [b, h, s_local, d] — this device's sequence block.
    """
    qf = q.astype(jnp.float32) * sm_scale

    def merge(k_t, v_t, m, l, acc, diag):
        scores = jnp.einsum("bhqd,bhkd->bhqk", qf,
                            k_t.astype(jnp.float32))   # [b,h,sq,sk]
        if diag:
            q_pos = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 2)
            k_pos = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 3)
            scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
        p = jnp.exp(scores - m_new)
        correction = jnp.exp(m - m_new)
        l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * correction + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_t.astype(jnp.float32))
        return m_new, l_new, acc_new

    return _ring_driver(q, k, v, axis_name=axis_name, causal=causal,
                        merge=merge)


def _ring_attn_local_pallas(q, k, v, *, axis_name: str, causal: bool,
                            block_q: int, interpret: bool):
    """Per-device body: the same ring schedule with the per-hop math
    fused into the Pallas ring-step kernel (attention.py::
    ring_flash_step) — the [s_local, s_local] score block of each hop
    lives in VMEM only, never HBM."""
    from tpu_autoscaler.workloads.attention import ring_flash_step

    def merge(k_t, v_t, m, l, acc, diag):
        return ring_flash_step(q, k_t, v_t, m, l, acc, diag=diag,
                               block_q=block_q, interpret=interpret)

    return _ring_driver(q, k, v, axis_name=axis_name, causal=causal,
                        merge=merge)


def make_ring_attention(mesh: Mesh, seq_axis: str = "sp",
                        causal: bool = True, impl: str = "einsum",
                        block_q: int = 128,
                        interpret: bool | None = None):
    """Build a ring-attention callable for [b, h, s, d] arrays whose
    sequence axis is sharded over ``mesh``'s ``seq_axis``.

    Returns a function operating on GLOBAL arrays; shard_map handles the
    decomposition and the ppermute schedule rides the mesh axis.

    ``impl``:

    - ``"einsum"`` (default) — XLA-fused per-hop math, differentiable
      end-to-end through the ring (use for training).
    - ``"pallas"`` — each hop's QK^T→softmax-merge→PV is one fused VMEM
      kernel (attention.py::ring_flash_step), so no per-hop score block
      round-trips HBM.  The forward is the fused ring; gradients are
      provided by a custom_vjp that recomputes through the einsum ring
      (same memory profile as training with ``impl="einsum"``, faster
      forward — the long-context eval/serving path).
    """
    if impl not in {"einsum", "pallas"}:
        raise ValueError(f"unknown ring attention impl {impl!r}")
    spec = P(None, None, seq_axis, None)

    def einsum_attn(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
        d = q.shape[-1]
        body = functools.partial(_ring_attn_local, axis_name=seq_axis,
                                 causal=causal, sm_scale=d ** -0.5)
        return jax.shard_map(
            body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        )(q, k, v)

    if impl == "einsum":
        return einsum_attn

    run_interpret = (jax.default_backend() != "tpu"
                     if interpret is None else interpret)

    def pallas_forward(q, k, v):
        body = functools.partial(
            _ring_attn_local_pallas, axis_name=seq_axis, causal=causal,
            block_q=block_q, interpret=run_interpret)
        return jax.shard_map(
            body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )(q, k, v)

    @jax.custom_vjp
    def attn(q, k, v):
        return pallas_forward(q, k, v)

    def attn_fwd(q, k, v):
        return pallas_forward(q, k, v), (q, k, v)

    def attn_bwd(residuals, g):
        q, k, v = residuals
        _, vjp = jax.vjp(einsum_attn, q, k, v)
        return vjp(g)

    attn.defvjp(attn_fwd, attn_bwd)
    return attn
