"""Small shard_map helpers shared by the parallelism modules."""

from __future__ import annotations

import jax


def pvary(x, axis_name: str):
    """Mark ``x`` as device-varying over ``axis_name``.

    jax renamed ``lax.pvary`` to ``lax.pcast(..., to='varying')``; support
    both so the workloads track jax versions without churn.
    """
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        return pcast(x, axis_name, to="varying")
    return jax.lax.pvary(x, axis_name)
