"""Context-parallel (sequence-parallel) TRAINING for the flagship model.

Ring attention (ring_attention.py) gives the long-context forward; this
module is where it meets the optimizer: the full train step — embed,
blocks, loss, grads, AdamW — runs under ``shard_map`` with the SEQUENCE
dimension sharded over the mesh's ``sp`` axis (and batch over ``data``),
so a context that does not fit one chip's HBM trains across the ICI
ring:

- every pointwise/matmul op (norms, qkv/mlp projections, unembedding,
  CE) touches only this device's [b_loc, s_loc] token block — no
  communication;
- RoPE rotates at GLOBAL positions (shard_index * s_loc offset), so the
  sharded model is bit-equivalent to the unsharded one;
- attention is the ring: K/V blocks rotate via ppermute while each
  device folds them into its online-softmax carry (einsum merge for
  training-grade AD, or the fused Pallas hop kernel);
- the loss is a psum-mean over (data, sp); reverse-mode AD through
  shard_map inserts the grad psums for the replicated params
  automatically (broadcast transposes to psum) and reverses the ring's
  ppermute schedule for dK/dV.

Memory: resident activations are O(s_local) per device; with
``cfg.remat`` the blocks recompute in the backward, which composes with
the ring exactly as on one device.  MoE blocks compose too (sp×ep): the
sp axis doubles as the expert axis — ring attention on the sequence
sharding, then tokens all_to_all to their experts across the same axis
and back (_sp_moe_ffn).

Autoscaler relevance (SURVEY §6.7/§6.8): an sp job is the purest case
for slice atomicity — the ring rides one slice's ICI torus every step,
so bisecting the slice kills the job.  The dryrun gate jits this step
over the virtual mesh the same way the driver validates dp/tp/pp/ep.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_autoscaler.workloads.model import (
    ModelConfig,
    TrainConfig,
    _rmsnorm,
    _rope,
    _split_qkv,
    init_params,
    make_optimizer,
)
from tpu_autoscaler.workloads.ring_attention import (
    _ring_attn_local,
    make_local_ring_attention,
)


def make_sp_mesh(devices=None, sp: int | None = None,
                 tp: int = 1) -> Mesh:
    """(data, sp) mesh: batch over ``data``, sequence over ``sp``.

    sp defaults to all devices (pure context parallelism); pass a
    divisor for hybrid data x context parallelism.  ``tp > 1`` appends
    a ``model`` axis — (data, sp, model) — for the sp×tp composition:
    attention heads and d_ff Megatron-shard over ``model`` inside the
    sp train step (see make_sp_train_step)."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if sp is None:
        sp = n // tp
    if n % (sp * tp):
        raise ValueError(
            f"{n} devices not divisible by sp*tp = {sp * tp}")
    if tp == 1:
        arr = np.asarray(devices).reshape(n // sp, sp)
        return Mesh(arr, axis_names=("data", "sp"))
    arr = np.asarray(devices).reshape(n // (sp * tp), sp, tp)
    return Mesh(arr, axis_names=("data", "sp", "model"))


def _local_qkv(y, layer_qkv, cfg: ModelConfig, model_axis: str | None,
               tp: int):
    """This TP rank's q/k/v heads from the packed qkv weight.

    tp == 1 is model._split_qkv exactly.  Under tp the packed q|k|v
    layout cannot be contiguously column-sharded into whole heads, so
    each rank dynamic-slices its own head columns (rank t takes q heads
    [t·h/tp, (t+1)·h/tp) and the matching GQA kv groups) and projects
    only those — column-parallel with the slice done on the replicated
    weight, no collective."""
    if tp == 1:
        return _split_qkv(y, layer_qkv, cfg)
    b, s, d = y.shape
    h, hd, hkv = cfg.n_heads, cfg.head_dim, cfg.kv_heads
    h_loc, hkv_loc = h // tp, hkv // tp
    t = jax.lax.axis_index(model_axis)
    w = layer_qkv.astype(cfg.dtype)
    wq = jax.lax.dynamic_slice_in_dim(w, t * h_loc * hd, h_loc * hd, 1)
    wk = jax.lax.dynamic_slice_in_dim(
        w, d + t * hkv_loc * hd, hkv_loc * hd, 1)
    wv = jax.lax.dynamic_slice_in_dim(
        w, d + hkv * hd + t * hkv_loc * hd, hkv_loc * hd, 1)
    q = jnp.einsum("bsd,de->bse", y, wq)
    k = jnp.einsum("bsd,de->bse", y, wk)
    v = jnp.einsum("bsd,de->bse", y, wv)
    q = q.reshape(b, s, h_loc, hd).transpose(0, 2, 1, 3)
    k = k.reshape(b, s, hkv_loc, hd).transpose(0, 2, 1, 3)
    v = v.reshape(b, s, hkv_loc, hd).transpose(0, 2, 1, 3)
    return q, k, v


def tp_attention(x, y, layer, cfg: ModelConfig, *, model_axis: str,
                 tp: int):
    """Full-sequence TP attention on the packed weights, shared by the
    tp-composed steps (ep×tp today; any future full-seq TP consumer):
    per-rank head columns via _local_qkv (whole GQA groups), the fused
    flash kernel or grouped einsum per cfg.resolved_attention(), and
    the row-parallel output projection completed by one psum over
    ``model_axis``.  Returns ``x + attention_out`` (the residual add).
    """
    import numpy as np

    b, s, d = x.shape
    h_loc = cfg.n_heads // tp
    hkv_loc = cfg.kv_heads // tp
    hd = cfg.head_dim
    q, k, v = _local_qkv(y, layer["qkv"], cfg, model_axis, tp)
    if cfg.rope:
        q = _rope(q, cfg.rope_theta)
        k = _rope(k, cfg.rope_theta)
    if cfg.resolved_attention() == "pallas":
        from tpu_autoscaler.workloads.attention import flash_attention

        attn = flash_attention(
            q, k, v, causal=True, window=cfg.attention_window,
            block_q=cfg.attn_block_q, block_k=cfg.attn_block_k,
            interpret=jax.default_backend() != "tpu")
    else:
        from tpu_autoscaler.workloads.attention import causal_band_mask

        qg = q.reshape(b, hkv_loc, h_loc // hkv_loc, s, hd)
        scores = jnp.einsum("bngqd,bnkd->bngqk", qg, k) / np.sqrt(hd)
        causal = causal_band_mask(s, cfg.attention_window)
        scores = jnp.where(causal, scores.astype(jnp.float32), -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        attn = jnp.einsum("bngqk,bnkd->bngqd", probs, v).reshape(
            b, h_loc, s, hd)
    attn = attn.transpose(0, 2, 1, 3).reshape(b, s, h_loc * hd)
    t = jax.lax.axis_index(model_axis)
    wo = jax.lax.dynamic_slice_in_dim(
        layer["attn_out"].astype(cfg.dtype), t * h_loc * hd,
        h_loc * hd, 0)
    out = jnp.einsum("bse,ed->bsd", attn, wo)
    return x + jax.lax.psum(out, model_axis)


def _sp_block(x, layer, cfg: ModelConfig, *, seq_axis: str, impl: str,
              block_q: int, interpret: bool,
              model_axis: str | None = None, tp: int = 1):
    """model._block restricted to this device's sequence shard: same
    math (model.py::_block is the parity oracle, pinned in
    tests/test_sp.py), with the attention mix replaced by the ring.

    Under sp×tp (``tp > 1``) the heads additionally shard over
    ``model_axis``: the ring rotates this rank's K/V head subset only
    (ICI traffic drops by tp), attn_out/w2 run row-parallel with one
    psum over ``model_axis`` each, and w1 column-parallel — Megatron
    inside the ring, weights replicated (under sp the ACTIVATIONS are
    the memory problem; ZeRO-1 shards the moments)."""
    b, s_loc, d = x.shape
    y = _rmsnorm(x, layer["ln1"])
    q, k, v = _local_qkv(y, layer["qkv"], cfg, model_axis, tp)
    if cfg.rope:
        # Global positions: this shard's tokens sit at offset
        # shard_index * s_loc of the full sequence.
        offset = jax.lax.axis_index(seq_axis) * s_loc
        q = _rope(q, cfg.rope_theta, offset)
        k = _rope(k, cfg.rope_theta, offset)
    if impl == "pallas":
        attn = make_local_ring_attention(
            axis_name=seq_axis, causal=True,
            window=cfg.attention_window, block_q=block_q,
            interpret=interpret)(q, k, v)
    elif impl == "ulysses":
        from tpu_autoscaler.workloads.ulysses import _ulysses_local

        # Local attention at FULL sequence -> the model's flash tile
        # knobs (cfg.attn_block_q/k) apply, not the ring's per-hop
        # block_q.  Kernel choice follows the backend (einsum is the
        # AD-able oracle off-TPU); pass interpret for pallas-on-CPU
        # debugging via make_ulysses_attention directly.
        attn = _ulysses_local(
            q, k, v, axis_name=seq_axis, causal=True,
            window=cfg.attention_window, block_q=cfg.attn_block_q,
            block_k=cfg.attn_block_k,
            impl="pallas" if jax.default_backend() == "tpu" else "einsum",
            interpret=interpret)
    else:
        attn, _lse = _ring_attn_local(
            q, k, v, axis_name=seq_axis, causal=True,
            window=cfg.attention_window, sm_scale=cfg.head_dim ** -0.5)
    h_loc = attn.shape[1]
    hd = cfg.head_dim
    attn = attn.transpose(0, 2, 1, 3).reshape(b, s_loc, h_loc * hd)
    if tp == 1:
        x = x + jnp.einsum("bsd,de->bse", attn.astype(cfg.dtype),
                           layer["attn_out"].astype(cfg.dtype))
    else:
        # Row-parallel attn_out: this rank's rows are its heads' slice.
        t = jax.lax.axis_index(model_axis)
        wo = jax.lax.dynamic_slice_in_dim(
            layer["attn_out"].astype(cfg.dtype), t * h_loc * hd,
            h_loc * hd, 0)
        out = jnp.einsum("bse,ed->bsd", attn.astype(cfg.dtype), wo)
        x = x + jax.lax.psum(out, model_axis)
    y = _rmsnorm(x, layer["ln2"])
    if cfg.moe_experts is not None:
        # sp×ep: the sp axis does double duty — sequence for the ring
        # attention above, EXPERT axis for the FFN here.  Tokens of
        # this rank's sequence shard all_to_all to their experts
        # across sp and back; see _sp_moe_ffn.
        out, aux = _sp_moe_ffn(y, layer, cfg, seq_axis=seq_axis,
                               model_axis=model_axis, tp=tp)
        return x + out, aux
    if tp == 1:
        hdn = jnp.einsum("bsd,df->bsf", y,
                         layer["w1"].astype(cfg.dtype))
        hdn = jax.nn.gelu(hdn)
        return x + jnp.einsum("bsf,fd->bsd", hdn,
                              layer["w2"].astype(cfg.dtype))
    f_loc = cfg.d_ff // tp
    w1 = jax.lax.dynamic_slice_in_dim(
        layer["w1"].astype(cfg.dtype), t * f_loc, f_loc, 1)
    w2 = jax.lax.dynamic_slice_in_dim(
        layer["w2"].astype(cfg.dtype), t * f_loc, f_loc, 0)
    hdn = jax.nn.gelu(jnp.einsum("bsd,df->bsf", y, w1))
    out = jnp.einsum("bsf,fd->bsd", hdn, w2)
    return x + jax.lax.psum(out, model_axis)


def _sp_moe_ffn(y, layer, cfg: ModelConfig, *, seq_axis: str,
                model_axis: str | None, tp: int):
    """MoE FFN under sequence parallelism (the composition sp.py's
    docstring previously excluded — VERDICT r4 item 9).

    The sp axis is reused as the expert axis: rank t owns experts
    [t·E/sp, (t+1)·E/sp), this rank's LOCAL sequence shard's tokens
    route over the whole expert set, and two all_to_all exchanges over
    ``seq_axis`` move them to their expert owners and back
    (moe._ep_moe_ffn — the exact dispatch/combine the dp×ep step
    runs, pointed at the sp axis).  Expert WEIGHTS stay replicated
    like every other sp param (sp's contract: activations are the
    memory problem, ZeRO-1 shards the moments); each rank dynamic-
    slices its expert block before the dispatch, so expert COMPUTE
    still drops by sp — and by tp on top of it (expert d_ff
    column/row-shards over ``model_axis``, moe._ep_moe_ffn's tp
    path).  Returns (ffn_out [b, s_loc, d], aux losses)."""
    from tpu_autoscaler.workloads.moe import _ep_moe_ffn

    sp = jax.lax.psum(1, seq_axis)  # static under shard_map tracing
    e_loc = cfg.moe_experts // sp
    t = jax.lax.axis_index(seq_axis)
    w1 = jax.lax.dynamic_slice_in_dim(layer["w1"], t * e_loc, e_loc, 0)
    w2 = jax.lax.dynamic_slice_in_dim(layer["w2"], t * e_loc, e_loc, 0)
    if tp > 1:
        f_loc = w1.shape[-1] // tp
        m = jax.lax.axis_index(model_axis)
        w1 = jax.lax.dynamic_slice_in_dim(w1, m * f_loc, f_loc, 2)
        w2 = jax.lax.dynamic_slice_in_dim(w2, m * f_loc, f_loc, 1)
    local = {**layer, "w1": w1, "w2": w2}
    return _ep_moe_ffn(y, local, cfg, seq_axis, sp,
                       model_axis if tp > 1 else None)


def make_sp_train_step(mesh: Mesh, cfg: ModelConfig, *,
                       train: TrainConfig | None = None,
                       impl: str | None = None,
                       learning_rate: float = 1e-3,
                       block_q: int = 128,
                       interpret: bool | None = None,
                       shard: str = "none",
                       data_axis: str = "data", seq_axis: str = "sp"):
    """Build (init_fn, step_fn) training with the sequence sharded over
    ``mesh``'s ``seq_axis`` and batch over ``data_axis``.

    A mesh carrying a ``model`` axis (make_sp_mesh(..., tp=N)) turns on
    the sp×tp composition: attention heads and d_ff Megatron-shard over
    ``model`` inside every block (the ring then rotates 1/tp of the K/V
    payload per rank), composing context and tensor parallelism in one
    step; requires n_heads, kv_heads and d_ff divisible by tp.

    step_fn: (params, opt_state, tokens [b, s+1]) -> (params, opt_state,
    loss), jitted; params replicate (under sp the ACTIVATIONS are the
    memory problem; ``shard="zero1"`` below slices the optimizer
    moments).  ``impl``: "einsum" (ring, XLA per-hop math), "pallas"
    (ring, fused hop kernel with the blocked lse backward), or
    "ulysses" (all-to-all to head sharding + local flash attention at
    full sequence — needs heads AND kv heads divisible by sp); None
    resolves like ModelConfig.attention="auto" — the pallas ring on
    TPU, the einsum ring elsewhere.  ``block_q`` is the ring impls'
    per-hop q tile; the ulysses local kernel tiles with
    cfg.attn_block_q/attn_block_k (it runs the model's own flash
    kernel at full sequence).

    ``cfg.ce_chunk`` is honored: the unembedding + CE scan over local
    sequence chunks, so long-context sp runs don't materialize
    [b_loc, s_loc, vocab] fp32 logits.

    The trainer's full optimizer recipe applies unchanged (clipping's
    global norm sees the psum'd global grads).

    ``shard="zero1"`` shards the AdamW moments over BOTH mesh axes
    (params replicate, so every axis is a "data" axis from the
    optimizer's point of view) — the fp32 moment HBM drops by the full
    device count while the step math is untouched (the optimizer runs
    under GSPMD outside the shard_map).
    """
    if shard not in {"none", "zero1"}:
        raise ValueError(
            f"sp supports shard='none' or 'zero1', got {shard!r} "
            "(params replicate under sp; fsdp belongs to the dp/tp "
            "step)")
    if impl is None:
        impl = "pallas" if jax.default_backend() == "tpu" else "einsum"
    if impl not in {"einsum", "pallas", "ulysses"}:
        raise ValueError(f"unknown sp impl {impl!r}")
    model_axis = "model" if "model" in mesh.axis_names else None
    tp = mesh.shape[model_axis] if model_axis else 1
    if tp > 1:
        if cfg.n_heads % tp or cfg.kv_heads % tp:
            raise ValueError(
                f"sp×tp needs heads divisible by the {model_axis} axis "
                f"({tp}): got {cfg.n_heads} q / {cfg.kv_heads} kv heads")
        if cfg.d_ff % tp:
            raise ValueError(
                f"sp×tp needs d_ff ({cfg.d_ff}) divisible by the "
                f"{model_axis} axis ({tp})")
    if impl == "ulysses":
        sp_size = mesh.shape[seq_axis]
        if (cfg.n_heads // tp) % sp_size or (cfg.kv_heads // tp) % sp_size:
            raise ValueError(
                f"impl='ulysses' needs per-TP-rank heads divisible by "
                f"the {seq_axis} axis ({sp_size}): got "
                f"{cfg.n_heads // tp} q / {cfg.kv_heads // tp} kv local "
                f"heads — use the ring impls for indivisible head "
                f"counts")
    moe = cfg.moe_experts is not None
    if moe:
        sp_size = mesh.shape[seq_axis]
        if cfg.moe_experts % sp_size:
            raise ValueError(
                f"sp×ep needs moe_experts ({cfg.moe_experts}) divisible "
                f"by the {seq_axis} axis ({sp_size}) — the sp axis is "
                "reused as the expert axis (_sp_moe_ffn)")
    if cfg.seq_len % mesh.shape[seq_axis]:
        raise ValueError(
            f"seq_len {cfg.seq_len} not divisible by the {seq_axis} "
            f"axis ({mesh.shape[seq_axis]})")
    if train is None:
        train = TrainConfig(learning_rate=learning_rate)
    optimizer = make_optimizer(train)
    run_interpret = (jax.default_backend() != "tpu"
                     if interpret is None else interpret)

    block = functools.partial(
        _sp_block, cfg=cfg, seq_axis=seq_axis, impl=impl,
        block_q=block_q, interpret=run_interpret,
        model_axis=model_axis, tp=tp)
    if cfg.remat:
        block = jax.checkpoint(block)

    def local_loss(params, inputs, targets):
        """This device's [b_loc, s_loc] token block through the model;
        returns the GLOBAL mean NLL (psum over both axes — every device
        sees the same scalar, keeping grads correct).  With MoE blocks
        the per-layer aux losses ride along (ep step's contract)."""
        x = params["embed"].astype(cfg.dtype)[inputs]

        def body(x, layer):
            if moe:
                return block(x, layer)  # (x, aux)
            return block(x, layer), None

        x, aux_stacked = jax.lax.scan(body, x, params["blocks"])
        x = _rmsnorm(x, params["ln_f"])
        b_loc, s_loc = inputs.shape
        if cfg.ce_chunk is not None and s_loc % cfg.ce_chunk == 0:
            # Chunked CE over the LOCAL sequence: the [b_loc, s_loc,
            # vocab] fp32 logits never materialize (the point of
            # ce_chunk, doubly so at sp's context lengths).
            from tpu_autoscaler.workloads.model import _chunked_ce

            local_sum = _chunked_ce(
                x, params["unembed"], targets, cfg.ce_chunk, cfg.dtype
            ) * (b_loc * s_loc)
        else:
            logits = jnp.einsum("bsd,dv->bsv", x,
                                params["unembed"].astype(cfg.dtype)
                                ).astype(jnp.float32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            local_sum = jnp.sum(
                -jnp.take_along_axis(logp, targets[..., None], axis=-1))
        total = jax.lax.psum(local_sum, (data_axis, seq_axis))
        n_tok = (b_loc * s_loc
                 * jax.lax.psum(1, data_axis) * jax.lax.psum(1, seq_axis))
        ce = total / n_tok
        if not moe:
            return ce
        aux = jax.tree.map(lambda a: jnp.mean(a, axis=0), aux_stacked)
        aux = jax.tree.map(
            lambda a: jax.lax.pmean(a, (data_axis, seq_axis)), aux)
        full = (ce + cfg.moe_balance_weight * aux["balance_loss"]
                + cfg.moe_z_weight * aux["z_loss"])
        return full, {"ce": ce, **aux}

    tok_spec = P(data_axis, seq_axis)
    metric_specs = {"ce": P(), "balance_loss": P(), "z_loss": P(),
                    "expert_fraction": P()}
    sharded_loss = jax.shard_map(
        local_loss, mesh=mesh,
        in_specs=(P(), tok_spec, tok_spec),
        out_specs=(P(), metric_specs) if moe else P(),
        check_vma=False,
    )

    def loss(params, tokens):
        return sharded_loss(params, tokens[:, :-1], tokens[:, 1:])

    def init(key):
        params = init_params(key, cfg)
        return params, optimizer.init(params)

    def step(params, opt_state, tokens):
        loss_val, grads = jax.value_and_grad(loss)(params, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss_val

    def step_moe(params, opt_state, tokens):
        (loss_val, metrics), grads = jax.value_and_grad(
            loss, has_aux=True)(params, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss_val, metrics

    replicated = NamedSharding(mesh, P())
    batch_shard = NamedSharding(mesh, P(data_axis, None))
    if shard == "zero1":
        from tpu_autoscaler.workloads.model import opt_state_shardings

        abstract = jax.eval_shape(
            functools.partial(init_params, cfg=cfg), jax.random.PRNGKey(0))
        o_shard = opt_state_shardings(
            cfg, optimizer, jax.tree.map(lambda _: P(), abstract), mesh,
            True)
    else:
        o_shard = replicated
    init_jit = jax.jit(init, out_shardings=(replicated, o_shard))
    if moe:
        # step_fn: (params, opt, tokens) -> (params, opt, loss, metrics)
        # — the ep step's signature, so callers treat sp×ep and dp×ep
        # uniformly.
        metric_shard = {k: replicated for k in metric_specs}
        step_jit = jax.jit(
            step_moe,
            in_shardings=(replicated, o_shard, batch_shard),
            out_shardings=(replicated, o_shard, replicated,
                           metric_shard),
            donate_argnums=(0, 1),
        )
    else:
        step_jit = jax.jit(
            step,
            in_shardings=(replicated, o_shard, batch_shard),
            out_shardings=(replicated, o_shard, replicated),
            donate_argnums=(0, 1),
        )
    return init_jit, step_jit
