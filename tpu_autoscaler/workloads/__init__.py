"""In-tree JAX training workload + checkpoint contract.

The reference is an infrastructure controller with no model code (SURVEY.md
§3); this package is the *job side* of the TPU-native rebuild's two new
contracts:

- a flagship pjit-sharded transformer train step (``model.py``) used to
  validate that provisioned slices actually run SPMD JAX — the mesh axes
  (data, model) shard over exactly the ICI domains the autoscaler
  provisions, and ``__graft_entry__.dryrun_multichip`` jits it over an
  N-device mesh;
- the checkpoint-aware drain contract (``checkpoint.py``): when the
  autoscaler reclaims a slice it annotates the workload pods
  (controller/reconciler.py §CHECKPOINT_ANNOTATION); a job using
  ``DrainWatcher`` sees the annotation, saves an orbax checkpoint, and
  exits before the drain deadline (BASELINE config #5).
"""

from tpu_autoscaler.workloads.model import (
    ModelConfig,
    TrainConfig,
    forward,
    init_params,
    loss_fn,
    make_optimizer,
    make_sharded_train_step,
    make_mesh,
)
from tpu_autoscaler.workloads.decode import (
    KVCache,
    decode_step,
    extend_step,
    generate,
    make_sharded_generate,
    prefill,
    speculative_generate,
)
from tpu_autoscaler.workloads.pipeline import (
    make_pipeline3d_train_step,
    make_pipeline_mesh,
    make_pipeline_train_step,
    merge_qkv_weights,
    split_qkv_weights,
)
from tpu_autoscaler.workloads.sp import make_sp_mesh, make_sp_train_step
from tpu_autoscaler.workloads.moe import make_ep_mesh, make_ep_train_step
from tpu_autoscaler.workloads.serving import (
    ContinuousBatcher,
    Request,
    SlotKVCache,
)
from tpu_autoscaler.workloads.checkpoint import (
    DrainWatcher,
    restore_checkpoint,
    save_checkpoint,
)

__all__ = [
    "ContinuousBatcher",
    "DrainWatcher",
    "KVCache",
    "ModelConfig",
    "Request",
    "SlotKVCache",
    "TrainConfig",
    "decode_step",
    "extend_step",
    "forward",
    "generate",
    "init_params",
    "loss_fn",
    "make_ep_mesh",
    "make_ep_train_step",
    "make_mesh",
    "make_optimizer",
    "make_pipeline3d_train_step",
    "make_pipeline_mesh",
    "make_pipeline_train_step",
    "make_sharded_generate",
    "make_sp_mesh",
    "make_sp_train_step",
    "make_sharded_train_step",
    "merge_qkv_weights",
    "prefill",
    "restore_checkpoint",
    "save_checkpoint",
    "speculative_generate",
    "split_qkv_weights",
]
