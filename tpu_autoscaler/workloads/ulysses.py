"""Ulysses-style sequence parallelism: all-to-all + local flash attention.

The second sequence-parallel strategy next to ring attention
(ring_attention.py), trading collective pattern for kernel shape:

- **Ring**: K/V blocks rotate via ``ppermute`` (axis_size - 1 neighbor
  hops over ICI), each device computes [s_loc, s_loc] score blocks with
  an online-softmax carry.  No head-count constraint; traffic is spread
  over the whole schedule.
- **Ulysses** (DeepSpeed-Ulysses pattern): ONE ``all_to_all`` re-shards
  the activations from sequence-sharded [b, h, s/sp, d] to
  head-sharded [b, h/sp, s, d]; each device then runs a plain LOCAL
  causal flash attention over the FULL sequence for its subset of
  heads, and a second all_to_all restores sequence sharding.  Two
  collectives total, and the attention itself is the single-device
  fused Pallas kernel at full sequence length — reusing its tiling,
  sliding-window banding, and custom_vjp backward unchanged.

Constraint: the head counts must divide by the axis (h % sp == 0 and,
for GQA, kv_heads % sp == 0) — exactly the shard_map head-sharding rule
of ModelConfig.mesh_shardable, but over the sp axis.  Ring has no such
constraint; that is the structural reason to keep both.

Like the ring, the all-to-alls ride the ICI of ONE slice — the
autoscaler's slice-atomic invariant is what keeps them off DCN.
"""

from __future__ import annotations

import functools

import jax
from jax.sharding import Mesh, PartitionSpec as P


def _ulysses_local(q, k, v, *, axis_name: str, causal: bool,
                   window: int | None, block_q: int, block_k: int,
                   impl: str, interpret: bool):
    """Per-device body under shard_map.

    q: [b, h_loc, s_loc, d]; k/v: [b, hkv_loc, s_loc, d] — sequence
    sharded.  all_to_all splits heads across the axis and concatenates
    sequence (tiled), attention runs locally at full sequence, and the
    inverse all_to_all restores the input sharding.
    """
    a2a = functools.partial(jax.lax.all_to_all, axis_name=axis_name,
                            tiled=True)
    qh = a2a(q, split_axis=1, concat_axis=2)   # [b, h/sp, s, d]
    kh = a2a(k, split_axis=1, concat_axis=2)
    vh = a2a(v, split_axis=1, concat_axis=2)
    if impl == "pallas":
        from tpu_autoscaler.workloads.attention import _flash_attention

        out = _flash_attention(qh, kh, vh, causal, window, block_q,
                               block_k, interpret)
    else:
        from tpu_autoscaler.workloads.attention import reference_attention

        out = reference_attention(qh, kh, vh, causal=causal, window=window)
    return a2a(out, split_axis=2, concat_axis=1)  # [b, h_loc, s_loc, d]


def make_ulysses_attention(mesh: Mesh, seq_axis: str = "sp",
                           causal: bool = True, window: int | None = None,
                           impl: str = "pallas", block_q: int = 512,
                           block_k: int = 1024,
                           interpret: bool | None = None):
    """Build an all-to-all sequence-parallel attention callable for
    [b, h, s, d] arrays whose sequence axis is sharded over ``mesh``'s
    ``seq_axis``.  Same contract as make_ring_attention: takes and
    returns GLOBAL arrays; GQA layouts (kv_heads < heads) pass through
    to the local kernel.

    ``impl="pallas"`` (default) uses the fused flash kernel locally —
    differentiable end-to-end, since both the kernel (custom_vjp) and
    all_to_all (transposes to the inverse all_to_all) have gradients.
    ``impl="einsum"`` uses the reference einsum attention locally (the
    numerics oracle, and cheap on CPU test meshes where interpret-mode
    Pallas is slow).
    """
    if impl not in {"einsum", "pallas"}:
        raise ValueError(f"unknown ulysses attention impl {impl!r}")
    sp = mesh.shape[seq_axis]
    spec = P(None, None, seq_axis, None)
    run_interpret = (jax.default_backend() != "tpu"
                     if interpret is None else interpret)

    def attn(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
        from tpu_autoscaler.workloads.attention import (
            _validate_attention_args,
        )

        # Global-shape validation (h % hkv, window-requires-causal, dim
        # agreement): the same rules hold per-shard once the head counts
        # divide sp, and skipping them means silently wrong kernel
        # output (see _validate_attention_args).
        _validate_attention_args(q, k, v, causal, window)
        h, hkv = q.shape[1], k.shape[1]
        if h % sp or hkv % sp:
            raise ValueError(
                f"ulysses needs heads divisible by the '{seq_axis}' axis "
                f"(size {sp}): got {h} q heads / {hkv} kv heads — use "
                f"ring attention for indivisible head counts")
        if q.shape[2] % sp:
            raise ValueError(
                f"sequence length {q.shape[2]} must divide by the "
                f"'{seq_axis}' axis (size {sp})")
        body = functools.partial(
            _ulysses_local, axis_name=seq_axis, causal=causal,
            window=window, block_q=block_q, block_k=block_k, impl=impl,
            interpret=run_interpret)
        return jax.shard_map(
            body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )(q, k, v)

    return attn
