"""Multi-host / multi-slice bootstrap for jobs under this autoscaler.

The autoscaler provisions the hardware; this module is how the job side
assembles it into a JAX system:

- **multi-host, one slice** (BASELINE config #3, v5e-64 = 16 hosts): every
  pod calls :func:`initialize_from_env` — coordinator address and process
  index come from the GKE TPU environment (`TPU_WORKER_HOSTNAMES`,
  `TPU_WORKER_ID`, injected by GKE on TPU node pools) — then builds one
  (data, model) mesh over all chips; collectives ride ICI.
- **multi-slice over DCN** (BASELINE config #4, 2×v5p-128): the mesh gains
  a leading ``dcn`` axis (one coordinate per slice, from
  `MEGASCALE_SLICE_ID` or the JobSet job index).  Batch shards over
  (dcn, data) — only data-parallel gradient reductions cross DCN; tensor
  parallelism stays inside each slice's ICI domain, matching how the
  autoscaler provisions each slice atomically and composes slices over
  DCN (SURVEY.md §6.8).
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Mapping

import numpy as np

log = logging.getLogger(__name__)

_COORDINATOR_PORT = 8476


@dataclasses.dataclass(frozen=True)
class HostTopology:
    """One process's view of the job topology, parsed from env."""

    coordinator: str          # "host:port" of process 0
    num_processes: int
    process_id: int
    slice_id: int = 0         # which DCN slice this host belongs to
    num_slices: int = 1

    @property
    def single_process(self) -> bool:
        return self.num_processes <= 1


def parse_gke_tpu_env(env: Mapping[str, str] | None = None
                      ) -> HostTopology | None:
    """Read the GKE TPU env contract; None when not on a TPU node pool.

    - ``TPU_WORKER_HOSTNAMES``: comma-separated hostnames of all workers
      (pods) in this slice, index order == worker id;
    - ``TPU_WORKER_ID``: this pod's index within the slice;
    - ``MEGASCALE_SLICE_ID`` / ``MEGASCALE_NUM_SLICES``: multi-slice
      coordinates (fall back to the JobSet job index label when absent).
    """
    env = os.environ if env is None else env
    hostnames = [h for h in env.get("TPU_WORKER_HOSTNAMES", "").split(",")
                 if h]
    if not hostnames:
        return None
    worker_id = int(env.get("TPU_WORKER_ID", "0"))
    slice_id = int(env.get("MEGASCALE_SLICE_ID",
                           env.get("JOB_COMPLETION_INDEX", "0")) or 0)
    num_slices = int(env.get("MEGASCALE_NUM_SLICES", "1") or 1)
    hosts_per_slice = len(hostnames)
    return HostTopology(
        coordinator=f"{hostnames[0]}:{_COORDINATOR_PORT}",
        num_processes=hosts_per_slice * num_slices,
        process_id=slice_id * hosts_per_slice + worker_id,
        slice_id=slice_id,
        num_slices=num_slices,
    )


def initialize_from_env(env: Mapping[str, str] | None = None) -> HostTopology:
    """Bring up jax.distributed from the GKE TPU environment.

    Idempotent and safe single-host: without the env contract (local dev,
    single-host v5e-8) it is a no-op returning a 1-process topology.
    """
    topo = parse_gke_tpu_env(env)
    if topo is None or topo.single_process:
        return topo or HostTopology(coordinator="localhost:0",
                                    num_processes=1, process_id=0)
    import jax

    jax.distributed.initialize(
        coordinator_address=topo.coordinator,
        num_processes=topo.num_processes,
        process_id=topo.process_id)
    log.info("jax.distributed up: process %d/%d (slice %d/%d)",
             topo.process_id, topo.num_processes, topo.slice_id,
             topo.num_slices)
    return topo


def make_multislice_mesh(num_slices: int, model: int = 2, devices=None):
    """(dcn, data, model) mesh: TP inside slices, DP within and across.

    On real multi-slice hardware prefer
    ``jax.experimental.mesh_utils.create_hybrid_device_mesh`` (it orders
    devices so the ``dcn`` axis crosses slices); on homogeneous/virtual
    device sets (tests, CPU) a plain reshape is used.
    """
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n % (num_slices * model):
        raise ValueError(
            f"{n} devices not divisible by num_slices*model = "
            f"{num_slices * model}")
    data = n // (num_slices * model)
    try:
        from jax.experimental.mesh_utils import create_hybrid_device_mesh

        arr = create_hybrid_device_mesh(
            mesh_shape=(data, model), dcn_mesh_shape=(num_slices, 1),
            devices=devices)
        # hybrid mesh returns [dcn*data, model]-shaped? normalize below.
        arr = np.asarray(arr).reshape(num_slices, data, model)
    except Exception:  # noqa: BLE001 — virtual/CPU devices: plain reshape
        arr = np.asarray(devices).reshape(num_slices, data, model)
    return Mesh(arr, axis_names=("dcn", "data", "model"))
