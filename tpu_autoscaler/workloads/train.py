"""Runnable trainer for slices this autoscaler provisions.

``python -m tpu_autoscaler.workloads.train`` is the TRAIN_IMAGE command in
deploy/example-v5e-64-jobset.yaml: it bootstraps jax.distributed from the
GKE TPU env (single-host: no-op), builds the (data, model) mesh over all
chips, trains the flagship model on synthetic data, checkpoints
periodically, resumes from the latest checkpoint after preemption, and
honors the checkpoint-aware drain contract — when the autoscaler reclaims
the slice, the DrainWatcher sees the pod annotation, a final checkpoint is
saved, and the process exits 0 inside the drain window.
"""

from __future__ import annotations

import logging
import sys

import click

log = logging.getLogger(__name__)


from tpu_autoscaler.workloads._cli import model_arch_options, model_config


@click.command()
@click.option("--steps", default=100, show_default=True)
@click.option("--batch", default=8, show_default=True)
@model_arch_options
@click.option("--remat", is_flag=True,
              help="Rematerialize activations (long-context memory lever).")
@click.option("--ce-chunk", default=None, type=int,
              help="Chunked cross-entropy: unembed+softmax over sequence "
                   "chunks of this size (large-vocab HBM lever).")
@click.option("--zero1", is_flag=True,
              help="Deprecated alias for --shard zero1.")
@click.option("--shard", "shard_mode",
              type=click.Choice(["none", "zero1", "fsdp"]), default=None,
              help="Data-axis state sharding: zero1 = AdamW moments "
                   "(cuts fp32 optimizer HBM by the DP degree); fsdp = "
                   "params+grads+moments (ZeRO-3, fits ~DPx larger "
                   "models).  Default: none.")
@click.option("--lr", default=1e-3, show_default=True,
              help="Peak learning rate.")
@click.option("--warmup-steps", default=0, show_default=True,
              help="Linear LR warmup from 0 to --lr.")
@click.option("--lr-schedule", type=click.Choice(["constant", "cosine"]),
              default="constant", show_default=True,
              help="cosine: decay to --min-lr-ratio * --lr over --steps.")
@click.option("--min-lr-ratio", default=0.1, show_default=True)
@click.option("--grad-clip", default=None, type=float,
              help="Global-norm gradient clipping threshold.")
@click.option("--accum-steps", default=1, show_default=True,
              help="Gradient accumulation: apply the optimizer every k "
                   "microbatch steps (k-times the effective batch).")
@click.option("--weight-decay", default=1e-4, show_default=True)
@click.option("--tp", "tp_degree", default=None, type=int,
              help="Tensor parallelism degree.  Composes with every "
                   "mode: alone it sets the dp+tp mesh's 'model' axis "
                   "(default: 2 when the device count is even); with "
                   "--pp-stages it builds the 3-axis dp×pp×tp GPipe "
                   "step; with --sp it Megatron-shards heads/d_ff "
                   "inside the context-parallel step.")
@click.option("--ep", "ep_degree", default=1, show_default=True,
              help="Expert parallelism (needs --moe-experts): shard "
                   "experts over this many devices with all_to_all "
                   "dispatch; the rest are data-parallel.  1 = off "
                   "(MoE runs replicated under the dp+tp step).")
@click.option("--pp-stages", default=1, show_default=True,
              help="Pipeline parallelism: split layers over this many "
                   "stages (GPipe with microbatch remat).  1 = off "
                   "(dp+tp mesh).")
@click.option("--pp-microbatches", default=4, show_default=True,
              help="Microbatches streamed through the pipeline per step "
                   "(bubble fraction = (P-1)/(m+P-1)).")
@click.option("--sp", "sp_degree", default=1, show_default=True,
              help="Context parallelism: shard the SEQUENCE over this "
                   "many devices (ring attention over the ICI ring; "
                   "remaining devices are data-parallel).  1 = off.")
@click.option("--sp-impl",
              type=click.Choice(["auto", "einsum", "pallas", "ulysses"]),
              default="auto", show_default=True,
              help="Sequence-parallel attention strategy: einsum/pallas "
                   "= ring (ppermute hops); ulysses = all-to-all to "
                   "head sharding + local flash attention (needs heads "
                   "divisible by --sp).  auto = pallas ring on TPU.")
@click.option("--data-file", default=None,
              help="Binary uint32 token shard to train on (native mmap "
                   "loader with prefetch; numpy fallback).  The repo "
                   "ships data/corpus.bin (byte-BPE vocab 8192, "
                   "data/tokenizer.json; rebuild or retokenize with "
                   "`python -m tpu_autoscaler.workloads.tokenizer`) — "
                   "pair it with --vocab 8192.  Default: synthetic "
                   "random tokens.")
@click.option("--profile-dir", default=None,
              help="Capture a jax.profiler trace of steps start+3..start+5 "
                   "into this directory (view with TensorBoard / xprof).")
@click.option("--checkpoint-dir", default="/tmp/tpu-train-ckpt",
              show_default=True)
@click.option("--checkpoint-every", default=50, show_default=True)
@click.option("--annotations-file", default=None,
              help="Downward-API annotations path (default: the standard "
                   "/etc/podinfo/annotations).")
@click.option("--platform", default=None,
              help="Force a jax platform (e.g. cpu for local smoke runs).")
def main(steps, batch, vocab, seq_len, d_model, n_layers, n_kv_heads,
         attention_window, no_rope, moe_experts, moe_top_k, remat,
         ce_chunk, zero1, shard_mode, lr, warmup_steps, lr_schedule,
         min_lr_ratio, grad_clip, accum_steps, weight_decay, tp_degree,
         ep_degree, pp_stages, pp_microbatches, sp_degree, sp_impl,
         data_file, profile_dir, checkpoint_dir, checkpoint_every,
         annotations_file, platform):
    """Train the flagship model on this job's slice (synthetic data)."""
    logging.basicConfig(level=logging.INFO, stream=sys.stderr,
                        format="%(asctime)s %(levelname)s: %(message)s")
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    import numpy as np
    from jax.sharding import NamedSharding

    from tpu_autoscaler.workloads.checkpoint import (
        DEFAULT_ANNOTATIONS_PATH,
        AsyncCheckpointWriter,
        DrainWatcher,
        latest_step,
        restore_checkpoint,
        train_until_drained,
    )
    from tpu_autoscaler.workloads.distributed import (
        initialize_from_env,
        make_multislice_mesh,
    )
    from tpu_autoscaler.workloads.model import (
        TrainConfig,
        batch_spec,
        make_mesh,
        make_sharded_train_step,
    )

    topo = initialize_from_env()
    log.info("topology: process %d/%d (slice %d/%d); devices: %d",
             topo.process_id, topo.num_processes, topo.slice_id,
             topo.num_slices, len(jax.devices()))

    cfg = model_config(vocab, seq_len, d_model, n_layers, n_kv_heads,
                       attention_window, no_rope, moe_experts, moe_top_k,
                       remat=remat, ce_chunk=ce_chunk)
    # Multi-slice jobs get the (dcn, data, model) mesh: DP crosses slices
    # over DCN, TP stays inside each slice's ICI domain.
    train_cfg = TrainConfig(
        learning_rate=lr, warmup_steps=warmup_steps,
        decay_steps=steps if lr_schedule == "cosine" else None,
        min_lr_ratio=min_lr_ratio, weight_decay=weight_decay,
        grad_clip=grad_clip, accum_steps=accum_steps)
    shard = shard_mode or ("zero1" if zero1 else "none")
    if pp_stages > 1 and sp_degree > 1:
        raise click.UsageError(
            "--pp-stages and --sp are separate strategies; pick one "
            "(pp x sp composition is not wired in the CLI)")
    if ep_degree > 1 and (pp_stages > 1 or sp_degree > 1):
        raise click.UsageError(
            "--ep composes with data parallelism (dp×ep); pick it OR "
            "--pp-stages/--sp")
    last_moe_metrics: dict = {}

    def wrap_moe_step(step4):
        """Adapt a 4-tuple MoE step (params, opt, loss, metrics) to the
        trainer loop's 3-tuple contract, siphoning the router metrics
        into the progress log."""
        def raw_step_fn(params, opt_state, tokens):
            params, opt_state, loss, metrics = step4(
                params, opt_state, tokens)
            last_moe_metrics.update(
                balance=float(metrics["balance_loss"]),
                z=float(metrics["z_loss"]))
            return params, opt_state, loss
        return raw_step_fn

    if ep_degree > 1:
        # Expert parallelism: experts over ep with all_to_all dispatch,
        # batch over data×ep (every device is data-parallel for the
        # dense ops).
        if moe_experts is None:
            raise click.UsageError("--ep needs --moe-experts")
        if shard != "none":
            raise click.UsageError(
                "--shard composes with the dp+tp step, not --ep "
                "(expert state is already partitioned)")
        if topo.num_processes > 1:
            raise click.UsageError(
                "--ep is single-process only for now; multi-host jobs "
                "should use the dp+tp step")
        ep_tp = tp_degree or 1
        n_dev = len(jax.devices())
        if n_dev % (ep_degree * ep_tp):
            raise click.UsageError(
                f"--ep {ep_degree} x --tp {ep_tp} must divide the "
                f"{n_dev} available devices")
        if batch % (n_dev // ep_tp):
            raise click.UsageError(
                f"--batch {batch} must divide over the {n_dev // ep_tp} "
                f"data×ep devices")
        from tpu_autoscaler.workloads.moe import (
            make_ep_mesh,
            make_ep_train_step,
        )

        mesh = make_ep_mesh(jax.devices(), ep=ep_degree, tp=ep_tp)
        try:
            ep_init, ep_step = make_ep_train_step(mesh, cfg,
                                                  train=train_cfg)
        except ValueError as e:
            raise click.UsageError(str(e)) from e
        init_fn = ep_init
        raw_step_fn = wrap_moe_step(ep_step)
    elif sp_degree > 1:
        # Context parallelism: sequence over the sp ring, batch over
        # the remaining (data-parallel) devices.
        if shard == "fsdp":
            raise click.UsageError(
                "--shard fsdp composes with the dp+tp step, not --sp "
                "(params replicate under sp; --shard zero1 composes)")
        if topo.num_processes > 1:
            raise click.UsageError(
                "--sp is single-process only for now; multi-host jobs "
                "should use the dp+tp step")
        sp_tp = tp_degree or 1
        if len(jax.devices()) % (sp_degree * sp_tp):
            raise click.UsageError(
                f"--sp {sp_degree} x --tp {sp_tp} must divide the "
                f"{len(jax.devices())} available devices")
        if seq_len % sp_degree:
            raise click.UsageError(
                f"--sp {sp_degree} must divide --seq-len {seq_len}")
        dp_n = len(jax.devices()) // (sp_degree * sp_tp)
        if batch % dp_n:
            raise click.UsageError(
                f"--batch {batch} must divide over the {dp_n} "
                f"data-parallel devices (devices / (sp*tp))")
        from tpu_autoscaler.workloads.sp import (
            make_sp_mesh,
            make_sp_train_step,
        )

        mesh = make_sp_mesh(jax.devices(), sp=sp_degree, tp=sp_tp)
        try:
            init_fn, sp_step = make_sp_train_step(
                mesh, cfg, train=train_cfg,
                impl=None if sp_impl == "auto" else sp_impl,
                shard=shard)
        except ValueError as e:  # e.g. ulysses head-divisibility
            raise click.UsageError(str(e)) from e
        # --sp with --moe-experts is the sp×ep composition: the MoE
        # step returns router metrics like the ep step does.
        raw_step_fn = (wrap_moe_step(sp_step) if moe_experts is not None
                       else sp_step)
    elif pp_stages > 1:
        # Pipeline mode: layers over a pp ring (GPipe, microbatch
        # remat); tokens replicate across stages.
        if shard != "none":
            raise click.UsageError(
                "--shard composes with the dp+tp step, not --pp-stages "
                "(stage-sharded state is already partitioned)")
        if batch % pp_microbatches:
            raise click.UsageError(
                f"--pp-microbatches {pp_microbatches} must divide "
                f"--batch {batch}")
        if topo.num_processes > 1:
            # The pp step replicates tokens across stages; per-process
            # batch assembly (each host building its local rows) is only
            # wired for the dp/tp data-sharded path.
            raise click.UsageError(
                "--pp-stages is single-process only for now; multi-host "
                "jobs should use the dp+tp step (--shard)")
        import numpy as _np
        from jax.sharding import Mesh

        from tpu_autoscaler.workloads.pipeline import (
            make_pipeline_mesh,
            make_pipeline_train_step,
        )

        if len(jax.devices()) < pp_stages:
            raise click.UsageError(
                f"--pp-stages {pp_stages} exceeds the {len(jax.devices())}"
                f" available devices")
        if tp_degree is not None:
            # dp×pp×tp: the 3-axis GPipe step (stage weights Megatron-
            # sharded, batch over data).  NOTE: the checkpoint pytree is
            # the split-weight form (wq/wk/wv); convert with
            # pipeline.merge_qkv_weights to serve it elsewhere.
            pp_tp = tp_degree
            n_dev = len(jax.devices())
            if n_dev % (pp_stages * pp_tp):
                raise click.UsageError(
                    f"--pp-stages {pp_stages} x --tp {pp_tp} must "
                    f"divide the {n_dev} available devices")
            dp_n = n_dev // (pp_stages * pp_tp)
            if batch % (dp_n * pp_microbatches):
                raise click.UsageError(
                    f"--batch {batch} must divide over {dp_n} data "
                    f"shards x {pp_microbatches} microbatches")
            mesh = make_pipeline_mesh(jax.devices(), pp=pp_stages,
                                      tp=pp_tp)
        else:
            mesh = Mesh(_np.asarray(jax.devices()[:pp_stages]),
                        axis_names=("pp",))
        try:
            init_fn, raw_step_fn = make_pipeline_train_step(
                mesh, cfg, num_microbatches=pp_microbatches,
                train=train_cfg)
        except ValueError as e:
            raise click.UsageError(str(e)) from e
    else:
        mesh = (make_multislice_mesh(topo.num_slices)
                if topo.num_slices > 1
                else make_mesh(tp=tp_degree))
        init_fn, raw_step_fn = make_sharded_train_step(
            mesh, cfg, train=train_cfg, shard=shard)
    params, opt_state = init_fn(jax.random.PRNGKey(0))
    log.info("mesh %s; params initialized", dict(mesh.shape))

    start = latest_step(checkpoint_dir) or 0
    state = {"params": params, "opt": opt_state}
    if start:
        # Restore WITH the live shardings: the replacement slice's device
        # layout wins over whatever topology the checkpoint was saved on.
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=x.sharding),
            state)
        state = restore_checkpoint(checkpoint_dir, start, abstract)
        log.info("resumed from checkpoint step %d", start)

    watcher = DrainWatcher(annotations_file or DEFAULT_ANNOTATIONS_PATH)
    from jax.sharding import PartitionSpec as _P

    # Pipeline stages all see the full batch (the pp loop microbatches
    # internally) unless the 3-axis mesh shards it over 'data'; sp/ep
    # meshes shard batch over their data axes ('sp' carries sequence,
    # 'ep' is also data-parallel for the dense ops); dp/tp meshes shard
    # over the data axes.
    if pp_stages > 1:
        b_spec = _P("data", None) if "data" in mesh.axis_names else _P()
    elif sp_degree > 1:
        b_spec = _P("data", None)
    elif ep_degree > 1:
        b_spec = _P(("data", "ep"), None)
    else:
        b_spec = batch_spec(mesh)
    b_sharding = NamedSharding(mesh, b_spec)
    n_proc = max(1, topo.num_processes)
    local_batch = max(1, batch // n_proc)

    loader = None
    if data_file:
        from tpu_autoscaler.dataio import open_token_loader

        # Per-process seed: each host samples disjoint crops of the
        # shared shard; the stream stays a pure function of (seed, step)
        # so resume replays it exactly.
        try:
            loader = open_token_loader(data_file, batch=local_batch,
                                       window=cfg.seq_len + 1,
                                       seed=topo.process_id)
        except (ValueError, OSError) as e:
            # ValueError from the native loader's tl_open codes;
            # OSError/FileNotFoundError from the numpy fallback's memmap.
            raise click.UsageError(str(e)) from e
        log.info("token shard %s: %d tokens (%s loader)", data_file,
                 loader.n_tokens, type(loader).__name__)

    vocab_warned = [False]

    def batch_for(step):
        # Host-local numpy rows assembled into one global array over the
        # mesh — jit cannot reshard a single-device array onto
        # non-addressable devices in multi-process JAX.
        if loader is not None:
            # Clip to the model's vocab: shards may be tokenized with a
            # larger vocabulary than this run trains.
            raw = loader.next(step)
            if not vocab_warned[0] and int(raw.max()) >= cfg.vocab:
                vocab_warned[0] = True
                log.warning(
                    "token shard contains ids >= model vocab %d; they "
                    "are aliased with modulo — retokenize or raise "
                    "--vocab if this is unintended", cfg.vocab)
            local = (raw % np.uint32(cfg.vocab)).astype(np.int32)
        else:
            rng = np.random.default_rng((step << 16) | topo.process_id)
            local = rng.integers(0, cfg.vocab,
                                 (local_batch, cfg.seq_len + 1),
                                 dtype=np.int32)
        return jax.make_array_from_process_local_data(b_sharding, local)

    last_loss = [float("nan")]

    def step_fn(state, tokens):
        params, opt_state, loss = raw_step_fn(state["params"],
                                              state["opt"], tokens)
        last_loss[0] = float(loss)
        return {"params": params, "opt": opt_state}

    # Throughput between log lines (wall time includes host data prep —
    # the number an operator compares against BENCH_TPU.json).
    import time as _time

    global_tokens_per_step = local_batch * n_proc * cfg.seq_len
    tp_state = {"t": _time.perf_counter(), "step": start}
    profiling = [False]

    def on_step(step, _state):
        if profile_dir and step == start + 2 and not profiling[0]:
            jax.profiler.start_trace(profile_dir)
            profiling[0] = True
        if profiling[0] and step >= start + 5:
            jax.profiler.stop_trace()
            profiling[0] = False
            log.info("profiler trace written to %s", profile_dir)
        if step % 10 == 0:
            now = _time.perf_counter()
            dsteps = step - tp_state["step"]
            tok_s = (global_tokens_per_step * dsteps
                     / max(now - tp_state["t"], 1e-9)) if dsteps else 0.0
            tp_state.update(t=now, step=step)
            moe_note = ""
            if last_moe_metrics:
                moe_note = (f" balance {last_moe_metrics['balance']:.3f}"
                            f" z {last_moe_metrics['z']:.3f}")
            log.info("step %d loss %.4f (%.0f tok/s)%s", step,
                     last_loss[0], tok_s, moe_note)

    writer = AsyncCheckpointWriter()
    try:
        state, step, drained = train_until_drained(
            step_fn, state, num_steps=steps, watcher=watcher,
            checkpoint_dir=checkpoint_dir, make_batch=batch_for,
            start_step=start, checkpoint_every=checkpoint_every,
            on_step=on_step, save_fn=writer.save)
    finally:
        # Always drain the writer: makes the final/drain checkpoint
        # durable AND surfaces any deferred background write error even
        # when the training loop itself raised.
        writer.wait()
        if profiling[0]:  # steps ended inside the trace window
            jax.profiler.stop_trace()
            log.info("profiler trace written to %s", profile_dir)
    if drained:
        log.info("drain requested: checkpointed at step %d, exiting "
                 "cleanly", step)
    else:
        log.info("training complete at step %d", step)


if __name__ == "__main__":
    main()
