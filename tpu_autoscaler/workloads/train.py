"""Runnable trainer for slices this autoscaler provisions.

``python -m tpu_autoscaler.workloads.train`` is the TRAIN_IMAGE command in
deploy/example-v5e-64-jobset.yaml: it bootstraps jax.distributed from the
GKE TPU env (single-host: no-op), builds the (data, model) mesh over all
chips, trains the flagship model on synthetic data, checkpoints
periodically, resumes from the latest checkpoint after preemption, and
honors the checkpoint-aware drain contract — when the autoscaler reclaims
the slice, the DrainWatcher sees the pod annotation, a final checkpoint is
saved, and the process exits 0 inside the drain window.
"""

from __future__ import annotations

import logging
import sys

import click

log = logging.getLogger(__name__)


@click.command()
@click.option("--steps", default=100, show_default=True)
@click.option("--batch", default=8, show_default=True)
@click.option("--seq-len", default=64, show_default=True)
@click.option("--d-model", default=128, show_default=True)
@click.option("--n-layers", default=2, show_default=True)
@click.option("--checkpoint-dir", default="/tmp/tpu-train-ckpt",
              show_default=True)
@click.option("--checkpoint-every", default=50, show_default=True)
@click.option("--annotations-file", default=None,
              help="Downward-API annotations path (default: the standard "
                   "/etc/podinfo/annotations).")
@click.option("--platform", default=None,
              help="Force a jax platform (e.g. cpu for local smoke runs).")
def main(steps, batch, seq_len, d_model, n_layers, checkpoint_dir,
         checkpoint_every, annotations_file, platform):
    """Train the flagship model on this job's slice (synthetic data)."""
    logging.basicConfig(level=logging.INFO, stream=sys.stderr,
                        format="%(asctime)s %(levelname)s: %(message)s")
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    import jax.numpy as jnp

    from tpu_autoscaler.workloads.checkpoint import (
        DEFAULT_ANNOTATIONS_PATH,
        DrainWatcher,
        latest_step,
        restore_checkpoint,
        save_checkpoint,
    )
    from tpu_autoscaler.workloads.distributed import initialize_from_env
    from tpu_autoscaler.workloads.model import (
        ModelConfig,
        make_mesh,
        make_sharded_train_step,
    )

    topo = initialize_from_env()
    log.info("topology: process %d/%d (slice %d/%d); devices: %d",
             topo.process_id, topo.num_processes, topo.slice_id,
             topo.num_slices, len(jax.devices()))

    cfg = ModelConfig(seq_len=seq_len, d_model=d_model, n_layers=n_layers)
    mesh = make_mesh()
    init_fn, step_fn = make_sharded_train_step(mesh, cfg)
    params, opt_state = init_fn(jax.random.PRNGKey(0))
    log.info("mesh %s; params initialized", dict(mesh.shape))

    start = latest_step(checkpoint_dir) or 0
    if start:
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            {"params": params, "opt": opt_state})
        restored = restore_checkpoint(checkpoint_dir, start, abstract)
        params, opt_state = restored["params"], restored["opt"]
        log.info("resumed from checkpoint step %d", start)

    watcher = DrainWatcher(annotations_file or DEFAULT_ANNOTATIONS_PATH)

    def batch_for(step):
        return jax.random.randint(jax.random.PRNGKey(step),
                                  (batch, cfg.seq_len + 1), 0, cfg.vocab,
                                  dtype=jnp.int32)

    step = start
    while step < steps:
        if watcher.drain_requested():
            save_checkpoint(checkpoint_dir, step,
                            {"params": params, "opt": opt_state})
            log.info("drain requested: checkpointed at step %d, exiting "
                     "cleanly", step)
            return
        params, opt_state, loss = step_fn(params, opt_state,
                                          batch_for(step))
        step += 1
        if step % checkpoint_every == 0 or step == steps:
            save_checkpoint(checkpoint_dir, step,
                            {"params": params, "opt": opt_state})
            log.info("step %d loss %.4f (checkpointed)", step, float(loss))
        elif step % 10 == 0:
            log.info("step %d loss %.4f", step, float(loss))
    log.info("training complete at step %d", step)


if __name__ == "__main__":
    main()
