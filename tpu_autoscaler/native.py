"""ctypes bindings for the native fit/pack kernels (native/fitpack.cpp).

Optional acceleration with matching semantics on the axes it models
(engine/fitter.py holds the reference implementation; tests assert the
two agree decision-for-decision on those axes).  Scope: shape scoring
covers the chip axes (total / per-pod / host slots); packing covers
cpu+memory.  The Python engine additionally binds host cpu/memory in
shape feasibility and taint admission in packing, and is authoritative
where they constrain.  The library is built on first use with the system
toolchain and cached; every entry point degrades to None when no compiler
is available, so the controller never depends on it.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

log = logging.getLogger(__name__)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")

_lock = threading.Lock()


def load_native_lib(so_name: str, *, configure,
                    cache: dict | None = None) -> ctypes.CDLL | None:
    """Shared build-on-first-use scaffolding for in-repo native libs.

    Builds ``native/build/<so_name>`` via make (target = its build path),
    CDLL-loads it, runs ``configure(lib)`` to declare prototypes, and
    caches the verdict in ``cache['lib']`` (tri-state: absent=untried,
    False=unavailable, CDLL=ready).  Returns None when no toolchain is
    available — callers degrade to their Python engines.  One
    implementation so the fitpack and tokenloader front ends cannot
    drift on build/caching/fallback policy.
    """
    cache = cache if cache is not None else {}
    with _lock:
        cached = cache.get("lib")
        if cached is False:
            return None
        if cached is not None:
            return cached
        lib_path = os.path.join(_NATIVE_DIR, "build", so_name)
        if not os.path.exists(lib_path):
            try:
                subprocess.run(
                    ["make", "-C", _NATIVE_DIR, f"build/{so_name}"],
                    check=True, capture_output=True, timeout=120)
            except Exception:  # noqa: BLE001 — no compiler: stay Python
                log.info("%s unavailable (build failed); using the "
                         "Python engine", so_name, exc_info=True)
                cache["lib"] = False
                return None
        try:
            lib = ctypes.CDLL(lib_path)
            configure(lib)
        except Exception:  # noqa: BLE001 — OSError from CDLL, or
            # AttributeError from configure() on a stale prebuilt .so
            # missing newly-declared symbols: either way the contract is
            # "degrade to the Python engine", never crash the caller.
            log.info("%s failed to load", so_name, exc_info=True)
            cache["lib"] = False
            return None
        cache["lib"] = lib
        return lib


_fitpack_cache: dict = {}


def _configure_fitpack(lib: ctypes.CDLL) -> None:
    lib.fitpack_best_shapes.argtypes = [
        ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_double),
    ]
    lib.fitpack_best_shapes.restype = None
    lib.fitpack_pack_ffd.argtypes = [
        ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
        ctypes.c_double, ctypes.c_double,
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.fitpack_pack_ffd.restype = ctypes.c_int32


def load() -> ctypes.CDLL | None:
    """Load (building if needed) the fitpack library, or None."""
    return load_native_lib("libfitpack.so", configure=_configure_fitpack,
                           cache=_fitpack_cache)


def available() -> bool:
    return load() is not None


def best_shapes(gangs: list[tuple[float, float, float]],
                shapes: list[tuple[float, float, float]]
                ) -> list[tuple[int, float]] | None:
    """[(best_shape_index | -1, stranded_chips)] per gang, or None if the
    native library is unavailable."""
    lib = load()
    if lib is None:
        return None
    g = len(gangs)
    s = len(shapes)
    garr = (ctypes.c_double * (g * 3))(*[v for row in gangs for v in row])
    sarr = (ctypes.c_double * (s * 3))(*[v for row in shapes for v in row])
    best = (ctypes.c_int32 * g)()
    stranded = (ctypes.c_double * g)()
    lib.fitpack_best_shapes(garr, g, sarr, s, best, stranded)
    return [(int(best[i]), float(stranded[i])) for i in range(g)]


def pack_ffd(pods: list[tuple[float, float]],
             free: list[tuple[float, float]],
             unit: tuple[float, float]
             ) -> tuple[int, list[int]] | None:
    """(new_units, placement per pod: -2 existing / >=0 new unit / -1
    unplaceable), or None if unavailable."""
    lib = load()
    if lib is None:
        return None
    n, f = len(pods), len(free)
    parr = (ctypes.c_double * (n * 2))(*[v for row in pods for v in row])
    farr = (ctypes.c_double * (f * 2))(*[v for row in free for v in row])
    placed = (ctypes.c_int32 * n)()
    count = lib.fitpack_pack_ffd(parr, n, farr, f, unit[0], unit[1], placed)
    return int(count), [int(placed[i]) for i in range(n)]
