"""ctypes bindings for the native fit/pack kernels (native/fitpack.cpp).

Optional acceleration with matching semantics on the axes it models
(engine/fitter.py holds the reference implementation; tests assert the
two agree decision-for-decision on those axes).  Scope: shape scoring
covers the chip axes (total / per-pod / host slots); packing covers
cpu+memory.  The Python engine additionally binds host cpu/memory in
shape feasibility and taint admission in packing, and is authoritative
where they constrain.  The library is built on first use with the system
toolchain and cached; every entry point degrades to None when no compiler
is available, so the controller never depends on it.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

log = logging.getLogger(__name__)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")

_lock = threading.Lock()


def load_native_lib(so_name: str, *, configure,
                    cache: dict | None = None) -> ctypes.CDLL | None:
    """Shared build-on-first-use scaffolding for in-repo native libs.

    Builds ``native/build/<so_name>`` via make (target = its build path),
    CDLL-loads it, runs ``configure(lib)`` to declare prototypes, and
    caches the verdict in ``cache['lib']`` (tri-state: absent=untried,
    False=unavailable, CDLL=ready).  Returns None when no toolchain is
    available — callers degrade to their Python engines.  One
    implementation so the fitpack and tokenloader front ends cannot
    drift on build/caching/fallback policy.
    """
    cache = cache if cache is not None else {}
    with _lock:
        cached = cache.get("lib")
        if cached is False:
            return None
        if cached is not None:
            return cached
        lib_path = os.path.join(_NATIVE_DIR, "build", so_name)
        # Always invoke make (a fresh build is a no-op): a prebuilt .so
        # older than its source would otherwise be loaded stale and
        # silently lack newly-added entry points.
        try:
            subprocess.run(  # analysis: allow=TAB801 single-flight build-on-first-use BY DESIGN: concurrent callers must wait for one bounded (timeout=120) make, not race it; after the first call the cache makes the lock hold O(ns)
                ["make", "-C", _NATIVE_DIR, f"build/{so_name}"],
                check=True, capture_output=True, timeout=120)
        except Exception:  # noqa: BLE001 — no compiler: stay Python
            if not os.path.exists(lib_path):
                log.info("%s unavailable (build failed); using the "
                         "Python engine", so_name, exc_info=True)
                cache["lib"] = False
                return None
            log.info("%s rebuild failed; loading the existing library",
                     so_name, exc_info=True)
        try:
            lib = ctypes.CDLL(lib_path)
            configure(lib)
        except Exception:  # noqa: BLE001 — OSError from CDLL, or
            # AttributeError from configure() on a stale prebuilt .so
            # missing newly-declared symbols: either way the contract is
            # "degrade to the Python engine", never crash the caller.
            log.info("%s failed to load", so_name, exc_info=True)
            cache["lib"] = False
            return None
        cache["lib"] = lib
        return lib


_fitpack_cache: dict = {}


def _configure_fitpack(lib: ctypes.CDLL) -> None:
    lib.fitpack_best_shapes.argtypes = [
        ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_double),
    ]
    lib.fitpack_best_shapes.restype = None
    lib.fitpack_pack_ffd.argtypes = [
        ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
        ctypes.c_double, ctypes.c_double,
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.fitpack_pack_ffd.restype = ctypes.c_int32
    # The wide multi-shape pack kernel (ISSUE 6) may be absent from a
    # stale prebuilt .so when no toolchain exists to rebuild it; the
    # legacy entry points must keep working in that case.
    try:
        lib.fitpack_pack_ffd_multi.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.fitpack_pack_ffd_multi.restype = ctypes.c_int32
        _fitpack_cache["pack_multi"] = True
    except AttributeError:
        _fitpack_cache["pack_multi"] = False


def load() -> ctypes.CDLL | None:
    """Load (building if needed) the fitpack library, or None."""
    return load_native_lib("libfitpack.so", configure=_configure_fitpack,
                           cache=_fitpack_cache)


def available() -> bool:
    return load() is not None


def best_shapes(gangs: list[tuple[float, float, float]],
                shapes: list[tuple[float, float, float]]
                ) -> list[tuple[int, float]] | None:
    """[(best_shape_index | -1, stranded_chips)] per gang, or None if the
    native library is unavailable."""
    lib = load()
    if lib is None:
        return None
    g = len(gangs)
    s = len(shapes)
    garr = (ctypes.c_double * (g * 3))(*[v for row in gangs for v in row])
    sarr = (ctypes.c_double * (s * 3))(*[v for row in shapes for v in row])
    best = (ctypes.c_int32 * g)()
    stranded = (ctypes.c_double * g)()
    lib.fitpack_best_shapes(garr, g, sarr, s, best, stranded)
    return [(int(best[i]), float(stranded[i])) for i in range(g)]


def pack_multi_available() -> bool:
    """True when the wide multi-shape pack entry point is loadable."""
    return load() is not None and bool(_fitpack_cache.get("pack_multi"))


def pack_ffd_multi(pods: list[list[float]], tmpl: list[int],
                   free: list[list[float]], admit: bytes, n_tmpl: int,
                   shapes: list[list[float]]
                   ) -> tuple[list[int], list[int], list[list[float]]] | None:
    """K-axis, multi-shape, admission-masked first-fit packing.

    ``pods`` must already be in first-fit-decreasing order (the caller
    owns ordering semantics); ``admit`` is the row-major T×F template
    admission mask.  Returns ``(placed, unit_shapes, free_after)`` —
    placement code per pod (-2 existing / -1 unplaceable / >=0 opened
    unit), the shape index of each opened unit, and the mutated free
    capacities — or None when the kernel is unavailable.
    """
    lib = load()
    if lib is None or not _fitpack_cache.get("pack_multi"):
        return None
    n, f, s = len(pods), len(free), len(shapes)
    k = len(shapes[0]) if shapes else (len(pods[0]) if pods else 0)
    if k == 0:
        return None
    parr = (ctypes.c_double * (n * k))(*[v for row in pods for v in row])
    tarr = (ctypes.c_int32 * max(n, 1))(*tmpl)
    farr = (ctypes.c_double * max(f * k, 1))(
        *[v for row in free for v in row])
    aarr = (ctypes.c_uint8 * max(len(admit), 1))(*admit)
    sarr = (ctypes.c_double * (s * k))(*[v for row in shapes for v in row])
    placed = (ctypes.c_int32 * max(n, 1))()
    unit_shape = (ctypes.c_int32 * max(n, 1))()
    lib.fitpack_pack_ffd_multi(parr, n, k, tarr, farr, f, aarr, n_tmpl,
                               sarr, s, placed, unit_shape)
    free_after = [[farr[i * k + a] for a in range(k)] for i in range(f)]
    n_units = max((placed[i] for i in range(n) if placed[i] >= 0),
                  default=-1) + 1
    return ([int(placed[i]) for i in range(n)],
            [int(unit_shape[u]) for u in range(n_units)],
            free_after)


def pack_ffd(pods: list[tuple[float, float]],
             free: list[tuple[float, float]],
             unit: tuple[float, float]
             ) -> tuple[int, list[int]] | None:
    """(new_units, placement per pod: -2 existing / >=0 new unit / -1
    unplaceable), or None if unavailable."""
    lib = load()
    if lib is None:
        return None
    n, f = len(pods), len(free)
    parr = (ctypes.c_double * (n * 2))(*[v for row in pods for v in row])
    farr = (ctypes.c_double * (f * 2))(*[v for row in free for v in row])
    placed = (ctypes.c_int32 * n)()
    count = lib.fitpack_pack_ffd(parr, n, farr, f, unit[0], unit[1], placed)
    return int(count), [int(placed[i]) for i in range(n)]
