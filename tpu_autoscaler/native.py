"""ctypes bindings for the native fit/pack kernels (native/fitpack.cpp).

Optional acceleration with matching semantics on the axes it models
(engine/fitter.py holds the reference implementation; tests assert the
two agree decision-for-decision on those axes).  Scope: shape scoring
covers the chip axes (total / per-pod / host slots); packing covers
cpu+memory.  The Python engine additionally binds host cpu/memory in
shape feasibility and taint admission in packing, and is authoritative
where they constrain.  The library is built on first use with the system
toolchain and cached; every entry point degrades to None when no compiler
is available, so the controller never depends on it.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

log = logging.getLogger(__name__)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "libfitpack.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None | bool = None  # None=untried, False=unavailable


def _build() -> bool:
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                       capture_output=True, timeout=120)
        return True
    except Exception:  # noqa: BLE001 — no compiler / make: stay Python
        log.info("native fitpack unavailable (build failed); using the "
                 "Python engine", exc_info=True)
        return False


def load() -> ctypes.CDLL | None:
    """Load (building if needed) the native library, or None."""
    global _lib
    with _lock:
        if _lib is False:
            return None
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB_PATH) and not _build():
            _lib = False
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            log.info("native fitpack failed to load", exc_info=True)
            _lib = False
            return None
        lib.fitpack_best_shapes.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_double),
        ]
        lib.fitpack_best_shapes.restype = None
        lib.fitpack_pack_ffd.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
            ctypes.c_double, ctypes.c_double,
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.fitpack_pack_ffd.restype = ctypes.c_int32
        _lib = lib
        return lib


def available() -> bool:
    return load() is not None


def best_shapes(gangs: list[tuple[float, float, float]],
                shapes: list[tuple[float, float, float]]
                ) -> list[tuple[int, float]] | None:
    """[(best_shape_index | -1, stranded_chips)] per gang, or None if the
    native library is unavailable."""
    lib = load()
    if lib is None:
        return None
    g = len(gangs)
    s = len(shapes)
    garr = (ctypes.c_double * (g * 3))(*[v for row in gangs for v in row])
    sarr = (ctypes.c_double * (s * 3))(*[v for row in shapes for v in row])
    best = (ctypes.c_int32 * g)()
    stranded = (ctypes.c_double * g)()
    lib.fitpack_best_shapes(garr, g, sarr, s, best, stranded)
    return [(int(best[i]), float(stranded[i])) for i in range(g)]


def pack_ffd(pods: list[tuple[float, float]],
             free: list[tuple[float, float]],
             unit: tuple[float, float]
             ) -> tuple[int, list[int]] | None:
    """(new_units, placement per pod: -2 existing / >=0 new unit / -1
    unplaceable), or None if unavailable."""
    lib = load()
    if lib is None:
        return None
    n, f = len(pods), len(free)
    parr = (ctypes.c_double * (n * 2))(*[v for row in pods for v in row])
    farr = (ctypes.c_double * (f * 2))(*[v for row in free for v in row])
    placed = (ctypes.c_int32 * n)()
    count = lib.fitpack_pack_ffd(parr, n, farr, f, unit[0], unit[1], placed)
    return int(count), [int(placed[i]) for i in range(n)]
