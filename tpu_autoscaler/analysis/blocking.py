"""Blocking-call-under-lock lint (TAB8xx).

A lock held across a blocking operation turns one slow syscall into a
fleet-wide stall: every thread that wants the lock queues behind the
network.  The same applies to the reconcile pass (the control plane's
single hot thread — docs/DESIGN.md's 12 ms budget) and to seqlock
sections in the TSDB (a blocked writer leaves ``_wseq`` odd and spins
every reader through its bounded retry).  This pass catalogs the
blocking operations the repo actually contains and reports each one by
the most damning context it is reachable in:

- TAB801 — blocking call while a lock may be held (held sets come from
  the TAL7xx propagation: lexical ``with`` blocks plus locks held at
  function entry across resolved call chains);
- TAB802 — blocking call reachable from the reconcile hot section
  (the transitive closure of ``Reconciler.reconcile_once`` — worker
  thunks handed to the actuation pool are SEPARATE roots by the
  callgraph's submit modeling and are correctly not in it);
- TAB803 — blocking call inside a seqlock section (any function of a
  ``_wseq``-bearing class that touches ``_wseq``, plus its callees).

The catalog (``BLOCKING_CALLS``): HTTP (``requests.*``, ``urlopen``),
``time.sleep``, ``subprocess.*`` (the ``make`` invocation in
``native.py``), builtin file I/O (``open``), blocking socket ops, and
un-timeouted ``Event.wait``/``Condition.wait``/``Queue.get``.  A timed
wait is still a schedule hazard but a bounded one; the untimeouted form
can park the holder forever, which is why only it is cataloged.

One finding per site with the highest-severity applicable code
(801 > 803 > 802) — a site under a lock inside the hot path is ONE
defect (move the call off the lock), not three.
"""

from __future__ import annotations

import ast

from tpu_autoscaler.analysis.callgraph import (
    POOL,
    SYNC_CONDITION,
    SYNC_EVENT,
    SYNC_QUEUE,
    FuncInfo,
    PackageGraph,
    _short as _short_fn,
    canonical_call_name,
    dotted_name,
    lock_id,
    shared_graph,
)
from tpu_autoscaler.analysis.core import (
    Finding,
    ProgramChecker,
    SourceFile,
)
from tpu_autoscaler.analysis.lockorder import (
    _short_lock,
    lock_order_graph,
)

#: Dotted-call patterns that block the calling thread.  Matched on the
#: full dotted name (``time.sleep``) or, for ``<root>.*`` entries, on
#: the root module name.
BLOCKING_CALLS: dict[str, str] = {
    "time.sleep": "time.sleep",
    "subprocess.*": "subprocess (spawns and waits on a child process)",
    "requests.*": "HTTP request",
    "urllib.*": "HTTP request",
    "socket.*": "blocking socket operation",
    "shutil.*": "bulk file I/O",
}

#: Bare builtins that block on the filesystem / tty.
BLOCKING_BUILTINS: dict[str, str] = {
    "open": "file I/O",
    "input": "tty read",
}

#: os.* entry points that hit the filesystem hard enough to matter.
_OS_BLOCKING = frozenset({
    "os.replace", "os.rename", "os.makedirs", "os.remove", "os.fsync",
    "os.sync",
})

#: The reconcile hot section's root (suffix match over qnames).
HOT_ROOT_SUFFIX = ".reconcile_once"

#: Roots whose bare ATTRIBUTE reference (not call) is itself a
#: blocking callable — ``http = self._http or requests.get`` binds the
#: transport to a local; calling that local blocks (the TokenProvider
#: single-flight shape).
_HTTP_ROOTS = frozenset({"requests", "urllib"})


def _is_http_ref(expr: ast.AST) -> bool:
    """A callable-valued expression that (possibly) IS an HTTP entry
    point: ``requests.get`` referenced un-called, through ``or`` /
    conditional fallbacks."""
    if isinstance(expr, ast.Attribute):
        d = dotted_name(expr)
        return d is not None and d.split(".")[0] in _HTTP_ROOTS
    if isinstance(expr, ast.IfExp):
        return _is_http_ref(expr.body) or _is_http_ref(expr.orelse)
    if isinstance(expr, ast.BoolOp):
        return any(_is_http_ref(v) for v in expr.values)
    return False


def _http_locals(fn_node: ast.AST) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and _is_http_ref(node.value):
            out.add(node.targets[0].id)
    return out

#: Attribute marking a seqlock section (obs/tsdb.py's write sequence).
SEQLOCK_ATTR = "_wseq"


def _bounded_timeout(node: ast.Call, pos: int) -> bool:
    """True when the call carries a timeout that actually bounds it.
    The timeout rides positionally at index ``pos`` or as
    ``timeout=``; an explicit ``None`` (either spelling) parks the
    holder exactly like omitting it, so only a non-None value counts."""
    t: ast.AST | None
    if len(node.args) > pos:
        t = node.args[pos]
    else:
        t = next((kw.value for kw in node.keywords
                  if kw.arg == "timeout"), None)
    return t is not None and not (isinstance(t, ast.Constant)
                                  and t.value is None)


def _blocking_kind(node: ast.Call, fn: FuncInfo,
                   locals_: dict[str, str],
                   graph: PackageGraph) -> str | None:
    """What (if anything) makes this call blocking — a catalog label,
    or None."""
    d = canonical_call_name(node.func, fn, graph)
    if d is not None:
        if d in _OS_BLOCKING:
            return "file I/O"
        full = BLOCKING_CALLS.get(d)
        if full is not None:
            return full
        root = d.split(".")[0]
        star = BLOCKING_CALLS.get(f"{root}.*")
        if star is not None:
            return star
        if d in BLOCKING_BUILTINS:
            return BLOCKING_BUILTINS[d]
    # Un-timeouted waits on typed receivers.  Timeout positions differ:
    # ``wait(timeout=None)`` takes it first, ``Queue.get(block=True,
    # timeout=None)`` second — ``q.get(True)`` and an explicit
    # ``timeout=None`` (any spelling) are still unbounded, while
    # ``q.get(False)`` / ``get(block=False)`` never blocks at all (it
    # raises ``queue.Empty`` immediately).
    if isinstance(node.func, ast.Attribute) \
            and node.func.attr in ("wait", "get"):
        t = graph.expr_type(node.func.value, fn, locals_)
        if node.func.attr == "wait" \
                and t in (SYNC_EVENT, SYNC_CONDITION) \
                and not _bounded_timeout(node, 0):
            return "un-timeouted wait (can park the holder forever)"
        if node.func.attr == "get" and t == SYNC_QUEUE \
                and not _bounded_timeout(node, 1):
            block = node.args[0] if node.args else next(
                (kw.value for kw in node.keywords
                 if kw.arg == "block"), None)
            if not (isinstance(block, ast.Constant)
                    and not block.value):
                return "un-timeouted Queue.get"
    return None


#: Sink marker: the closure is handed to a pool submit / Thread target
#: and runs on its OWN root (the callgraph models it as one) — its body
#: is off the enclosing function's hot/seqlock path.
_ESCAPE = "@escape"


def _closure_sinks(fn: FuncInfo, graph: PackageGraph,
                   locals_: dict[str, str]) -> dict[tuple[int, int],
                                                    set[str]]:
    """Where each nested def/lambda in ``fn`` actually RUNS.

    Maps the closure's line span to ``{_ESCAPE}`` when it is handed to
    a pool ``submit``/``Thread`` (another root) or to the set of
    resolved callee qnames it is passed to — a closure passed to a
    package function executes synchronously inside that callee (the
    tsdb ``_guarded`` read thunks run INSIDE the seqlock retry loop),
    so its blocking calls inherit the CALLEE's hot/seqlock context.
    Spans with no entry run where they are defined and keep the
    enclosing function's context."""
    named: dict[str, tuple[int, int]] = {}
    for n in ast.walk(fn.node):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and n is not fn.node:
            named[n.name] = (n.lineno, n.end_lineno or n.lineno)
        elif isinstance(n, ast.Assign) and len(n.targets) == 1 \
                and isinstance(n.targets[0], ast.Name) \
                and isinstance(n.value, ast.Lambda):
            # ``work = lambda: ...; pool.submit(work)`` — the bound
            # name stands for the lambda's span exactly like a nested
            # def's name does.
            named[n.targets[0].id] = (n.value.lineno,
                                      n.value.end_lineno
                                      or n.value.lineno)
    sinks: dict[tuple[int, int], set[str]] = {}
    for node in ast.walk(fn.node):
        if not isinstance(node, ast.Call):
            continue
        spans = []
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Lambda):
                spans.append((arg.lineno, arg.end_lineno or arg.lineno))
            elif isinstance(arg, ast.Name) and arg.id in named:
                spans.append(named[arg.id])
        if not spans:
            continue
        label: set[str] | None = None
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "submit":
            recv_t = graph.expr_type(node.func.value, fn, locals_)
            recv_ci = graph.classes.get(recv_t) if recv_t else None
            if recv_t == POOL or (recv_ci is not None
                                  and graph._owns_pool(recv_ci)):
                label = {_ESCAPE}
        d = dotted_name(node.func)
        if label is None and d is not None \
                and d.split(".")[-1] == "Thread":
            label = {_ESCAPE}
        if label is None:
            target = graph.resolve_callable(node.func, fn, locals_)
            if target is None:
                continue
            label = {target.qname}
        for s in spans:
            sinks.setdefault(s, set()).update(label)
    return sinks


def _innermost_sink(sinks: dict[tuple[int, int], set[str]],
                    line: int) -> set[str] | None:
    """The classification of the innermost CLASSIFIED span containing
    ``line`` (closures nest: a thunk built inside an escaping thunk is
    judged by its own sink first)."""
    best: tuple[int, set[str]] | None = None
    for (lo, hi), label in sinks.items():
        if lo <= line <= hi and (best is None or hi - lo < best[0]):
            best = (hi - lo, label)
    return best[1] if best else None


class BlockingUnderLockChecker(ProgramChecker):
    name = "blocking-under-lock"
    codes = {
        "TAB801": "blocking call while a lock may be held",
        "TAB802": "blocking call reachable from the reconcile hot "
                  "section",
        "TAB803": "blocking call inside a seqlock section",
    }

    def applies_to(self, rel_path: str) -> bool:
        return "tpu_autoscaler/testing/" not in rel_path

    def check_program(self, files: list[SourceFile]) -> list[Finding]:
        graph = shared_graph(files)
        lg = lock_order_graph(graph)

        hot_roots = {q for q in graph.funcs
                     if q.endswith(HOT_ROOT_SUFFIX)}
        hot = graph._closure(hot_roots)

        seq_roots = set()
        for fn in graph.funcs.values():
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Attribute) \
                        and node.attr == SEQLOCK_ATTR:
                    seq_roots.add(fn.qname)
                    break
        seq = graph._closure(seq_roots)

        findings: list[Finding] = []
        for fn in graph.funcs.values():
            locals_ = graph.local_types(fn)
            http_locals = _http_locals(fn.node)
            sinks = _closure_sinks(fn, graph, locals_)
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                kind = _blocking_kind(node, fn, locals_, graph)
                if kind is None and isinstance(node.func, ast.Name) \
                        and node.func.id in http_locals:
                    kind = "HTTP request (transport bound to a local)"
                if kind is None:
                    continue
                where = _short_fn(fn.qname)
                held = lg.held_at_line(fn.qname, node.lineno)
                deferred = lg.in_deferred_scope(fn.qname, node.lineno)
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "wait":
                    # Condition.wait releases its OWN lock for the
                    # duration — holding only that lock (or, for a
                    # Condition(lock), the lock it wraps) is the
                    # canonical idiom, not a stall; waiting with a
                    # SECOND lock held is TAL702's finding.
                    rel = lock_id(node.func.value, fn, locals_, graph)
                    if rel is not None:
                        held = held - lg.own_locks(rel)
                if held:
                    locks = ", ".join(sorted(
                        _short_lock(h) for h in held))
                    findings.append(Finding(
                        fn.rel_path, node.lineno, "TAB801",
                        f"{where} performs {kind} while holding "
                        f"[{locks}] — every contender queues behind "
                        f"the blocking call"))
                else:
                    # A nested def/lambda's body runs where the
                    # closure is CALLED, not where it is defined: a
                    # pool-submit/Thread-target closure runs on its
                    # own root (off this function's hot or seqlock
                    # path entirely), while one passed to a resolved
                    # package callee runs synchronously INSIDE that
                    # callee — the tsdb ``_guarded`` read thunks
                    # execute in the seqlock retry loop, so they are
                    # judged by the callee's context, not skipped.
                    ctx = {fn.qname}
                    if deferred:
                        sink = _innermost_sink(sinks, node.lineno)
                        if sink is not None:
                            if _ESCAPE in sink:
                                continue
                            ctx = sink
                    if ctx & seq:
                        findings.append(Finding(
                            fn.rel_path, node.lineno, "TAB803",
                            f"{where} performs {kind} inside a seqlock "
                            f"section — readers spin their bounded "
                            f"retry for the duration"))
                    elif ctx & hot:
                        findings.append(Finding(
                            fn.rel_path, node.lineno, "TAB802",
                            f"{where} performs {kind} on the reconcile "
                            f"hot path (reachable from reconcile_once) "
                            f"— the control loop stalls for the "
                            f"duration"))
        findings.sort(key=lambda f: (f.file, f.line, f.code))
        return findings

