"""Replay-determinism contract checker (TAD9xx).

The repo's replay oracles are load-bearing: the sharded planner's
byte-identical merge (docs/SHARDING.md), the black-box bundle replay's
exit-2 divergence gate (docs/OBSERVABILITY.md), the chaos grammar's
pure-function-of-seed contract (docs/CHAOS.md), and the
policy/serving replay benches that score every PR.  All of them reduce
to one property: a contract function run twice on the same inputs
produces the same bytes.  Nothing checked it statically — an unseeded
``random`` call, a wall-clock read, or a hash-order set iteration
breaks the oracle silently, usually only under a different
``PYTHONHASHSEED``.

Scope: every function defined in a CONTRACT module (planner + fitter,
``chaos/scenario.py``, ``policy/replay.py`` + ``forecast.py``,
``serving/replay.py``, the shard fan-out/merge) plus every digest
builder (any function whose name contains ``digest``) anywhere in the
package — closed transitively over the resolved call graph, so a
helper a contract module calls is held to the same bar.  Unresolvable
callees produce no edge (the evidence discipline shared with TAR5xx);
what the closure cannot see, the seeded replay tests still cover.

| code | meaning |
| --- | --- |
| TAD901 | wall-clock read (``time.time``/``monotonic``, ``datetime.now`` ...) |
| TAD902 | unseeded randomness (module-level ``random.*``, ``uuid``, ``os.urandom``, ``secrets``, ``np.random.*``) |
| TAD903 | ``id()``-keyed map (ids are allocation order — different every run) |
| TAD904 | unsorted set iteration feeding an order-sensitive fold |

TAD902 flags only MODULE-level randomness: a ``random.Random(seed)``
instance threaded through parameters is exactly the sanctioned pattern
(the chaos grammar's), and calls on such instances are not findings.
TAD904 exempts iteration wrapped in ``sorted(...)``, set expressions
consumed by order-insensitive folds (``len``/``min``/``max``/``sum``/
``any``/``all``/``set``/``frozenset``), and loop bodies that only
XOR-fold (``^=``) — XOR is commutative, which is why the informer's
bucket digests are legal by construction.
"""

from __future__ import annotations

import ast

from tpu_autoscaler.analysis.callgraph import (
    FuncInfo,
    PackageGraph,
    _short as _short_fn,
    canonical_call_name,
    dotted_name,
    shared_graph,
)
from tpu_autoscaler.analysis.core import (
    Finding,
    ProgramChecker,
    SourceFile,
)

#: Modules whose every function is under the replay contract, tagged
#: with the contract they anchor (the tag appears in messages so a
#: finding in a shared helper names WHY it is in scope).
CONTRACT_MODULES: dict[str, str] = {
    "tpu_autoscaler/engine/planner.py": "planner",
    "tpu_autoscaler/engine/fitter.py": "planner",
    "tpu_autoscaler/chaos/scenario.py": "chaos-grammar",
    "tpu_autoscaler/policy/replay.py": "policy-replay",
    "tpu_autoscaler/policy/forecast.py": "policy-replay",
    "tpu_autoscaler/serving/replay.py": "serving-replay",
    "tpu_autoscaler/controller/shard.py": "shard-merge",
}

#: Wall-clock reads (dotted-name match).  ``time.perf_counter`` is
#: deliberately absent: the repo uses it exclusively as a duration
#: meter feeding ``metrics.observe`` histograms (the 12 ms overhead
#: budget's instrumentation), and a duration can only reach a replayed
#: decision by first failing the TAP1xx purity gate — flagging every
#: telemetry stopwatch would bury the real leaks.
_WALL_CLOCK = frozenset({
    "time.time", "time.monotonic", "time.time_ns", "time.monotonic_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
    "datetime.now", "datetime.utcnow", "date.today",
})

#: Seeded-generator constructors: ``random.Random(seed)`` /
#: ``np.random.default_rng(seed)`` ARE the sanctioned pattern — they
#: are only findings when called with no seed at all.
_SEEDED_CTORS = frozenset({
    "random.Random", "np.random.default_rng", "numpy.random.default_rng",
})

#: Module-level randomness roots: any call ``<root>.<fn>(...)`` where
#: the root resolves to one of these MODULES is unseeded (process-
#: global state), unlike a seeded ``Random`` instance.
_RANDOM_ROOTS = frozenset({"random", "secrets"})

#: uuid is only non-deterministic through its entropy/clock-reading
#: constructors; ``uuid3``/``uuid5`` hash their inputs and ``UUID()``
#: parses, so flagging the whole module would force bogus waivers on
#: replay-safe name-based ids.
_UUID_ENTROPY = frozenset({"uuid.uuid1", "uuid.uuid4"})

_ORDER_INSENSITIVE = frozenset({
    "sorted", "len", "min", "max", "sum", "any", "all", "set",
    "frozenset",
})


def _is_set_expr(expr: ast.AST, set_locals: set[str]) -> bool:
    """Shallow evidence that ``expr`` is a set: literal, comprehension,
    ``set()``/``frozenset()`` call, a local known to hold one, or a
    union/intersection of such."""
    if isinstance(expr, (ast.Set, ast.SetComp)):
        return True
    if isinstance(expr, ast.Call):
        d = dotted_name(expr.func)
        if d in ("set", "frozenset"):
            return True
        if isinstance(expr.func, ast.Attribute) \
                and expr.func.attr in ("union", "intersection",
                                       "difference",
                                       "symmetric_difference"):
            return _is_set_expr(expr.func.value, set_locals)
        return False
    if isinstance(expr, ast.Name):
        return expr.id in set_locals
    if isinstance(expr, ast.BinOp) \
            and isinstance(expr.op, (ast.BitOr, ast.BitAnd, ast.Sub,
                                     ast.BitXor)):
        return (_is_set_expr(expr.left, set_locals)
                or _is_set_expr(expr.right, set_locals))
    return False


def _set_locals(fn_node: ast.AST) -> set[str]:
    out: set[str] = set()
    assigns: list[ast.Assign] = []
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            assigns.append(node)
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name):
            ann = node.annotation
            d = dotted_name(ann.value if isinstance(ann, ast.Subscript)
                            else ann)
            if d in ("set", "frozenset", "Set", "FrozenSet",
                     "typing.Set", "typing.FrozenSet"):
                out.add(node.target.id)
    # Fixpoint over the assignment chain: ast.walk is breadth-first, so
    # `t = s | extra` at function top level is visited BEFORE the
    # `s = set()` sitting one block deeper (`if cond: s = set()`) — a
    # single pass would miss t and the downstream order-sensitive fold.
    changed = True
    while changed:
        changed = False
        for node in assigns:
            name = node.targets[0].id  # type: ignore[union-attr]
            if name not in out and _is_set_expr(node.value, out):
                out.add(name)
                changed = True
    # Kill on rebinding: a name whose LAST assignment is not
    # set-valued was rebound away from a set (`s = sorted(s)` yields a
    # list) — iterating it afterwards is deterministic, so flagging it
    # would force a bogus waiver on the canonical TAD904 fix itself.
    last: dict[str, ast.Assign] = {}
    for node in assigns:
        name = node.targets[0].id  # type: ignore[union-attr]
        if name not in last or node.lineno > last[name].lineno:
            last[name] = node
    for name, node in last.items():
        if name in out and not _is_set_expr(node.value, out):
            out.discard(name)
    return out


def _order_free_body(body: list[ast.stmt]) -> bool:
    """True when the loop body is commutative over iteration order:
    XOR folds (``^=``, the bucket-digest idiom), ``.add``/``.discard``
    into sets, and conditionals over only such statements."""
    ok = False
    for stmt in body:
        if isinstance(stmt, ast.AugAssign) \
                and isinstance(stmt.op, ast.BitXor):
            ok = True
            continue
        if isinstance(stmt, ast.Expr) \
                and isinstance(stmt.value, ast.Call) \
                and isinstance(stmt.value.func, ast.Attribute) \
                and stmt.value.func.attr in ("add", "discard"):
            ok = True
            continue
        if isinstance(stmt, ast.If) \
                and _order_free_body(stmt.body) \
                and (not stmt.orelse or _order_free_body(stmt.orelse)):
            ok = True
            continue
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        return False
    return ok


class _FnScan(ast.NodeVisitor):
    """One function body's determinism findings."""

    def __init__(self, fn: FuncInfo, tag: str, graph: PackageGraph):
        self.fn = fn
        self.tag = tag
        self.graph = graph
        self.set_locals = _set_locals(fn.node)
        self.findings: list[Finding] = []
        #: set-iteration nodes blessed by an order-insensitive consumer.
        self._exempt: set[int] = set()
        #: wall-clock calls blessed by the virtual-clock-default idiom
        #: (``now = time.time() if now is None else now``): the clock
        #: is only the PRODUCTION default — replay always injects.
        self._clock_default: set[int] = set()

    def _emit(self, line: int, code: str, msg: str) -> None:
        where = _short_fn(self.fn.qname)
        self.findings.append(Finding(
            self.fn.rel_path, line, code,
            f"{where} {msg} (under the '{self.tag}' replay contract)"))

    # -- calls ------------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        d = canonical_call_name(node.func, self.fn, self.graph)
        if d is not None:
            if d in _WALL_CLOCK:
                if id(node) not in self._clock_default:
                    self._emit(node.lineno, "TAD901",
                               f"reads the wall clock via '{d}' — "
                               f"replay must take 'now' as an input")
            elif d in _SEEDED_CTORS:
                if not node.args and not node.keywords:
                    self._emit(
                        node.lineno, "TAD902",
                        f"'{d}()' with no seed draws from OS entropy — "
                        f"pass an explicit seed")
            else:
                root = d.split(".")[0]
                if (root in _RANDOM_ROOTS and "." in d) \
                        or d in _UUID_ENTROPY:
                    self._emit(
                        node.lineno, "TAD902",
                        f"draws process-global randomness via '{d}' — "
                        f"thread a seeded Random through instead")
                elif root in ("np", "numpy") \
                        and d.split(".")[1:2] == ["random"]:
                    self._emit(
                        node.lineno, "TAD902",
                        f"draws numpy global randomness via '{d}' — "
                        f"use a seeded Generator instead")
                elif d == "os.urandom":
                    self._emit(node.lineno, "TAD902",
                               "draws entropy via 'os.urandom'")
        if d in _ORDER_INSENSITIVE:
            for arg in node.args:
                self._bless(arg)
        self.generic_visit(node)

    # -- virtual-clock defaults -------------------------------------------

    @staticmethod
    def _none_test(
            test: ast.AST) -> "tuple[type[ast.cmpop], ast.AST] | None":
        """(``Is``/``IsNot``, the tested expr) for a ``<x> is [not]
        None`` comparison, else None."""
        if (isinstance(test, ast.Compare)
                and len(test.ops) == 1
                and isinstance(test.ops[0], (ast.Is, ast.IsNot))):
            sides = [test.left, *test.comparators]
            tested = [c for c in sides
                      if not (isinstance(c, ast.Constant)
                              and c.value is None)]
            if len(tested) == 1:
                return type(test.ops[0]), tested[0]
        return None

    @staticmethod
    def _target_key(expr: ast.AST) -> str | None:
        """A ctx-insensitive spelling of a name/attribute chain (the
        tested ``now`` / ``self._now`` vs its Store-ctx twin)."""
        if isinstance(expr, ast.Name):
            return expr.id
        if isinstance(expr, ast.Attribute):
            inner = _FnScan._target_key(expr.value)
            return f"{inner}.{expr.attr}" if inner else None
        return None

    def _bless_clock(self, *nodes: ast.AST) -> None:
        for n in nodes:
            for sub in ast.walk(n):
                if isinstance(sub, ast.Call):
                    self._clock_default.add(id(sub))

    def _bless_default_stmts(self, tested: ast.AST,
                             stmts: list[ast.stmt]) -> None:
        """Bless clock calls in the not-injected branch ONLY where the
        clock value flows back into the None-tested name: ``if now is
        None: now = time.time()`` is the injection default, while a
        lazy-init guard on an UNRELATED attribute (``if self._cache is
        None: ... self._stamp = time.time()``) leaks a clock value
        replay never injects and stays a finding."""
        key = self._target_key(tested)
        if key is None:
            return
        for stmt in stmts:
            targets: list[ast.expr] = []
            value: ast.expr | None = None
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)) \
                    and stmt.value is not None:
                targets, value = [stmt.target], stmt.value
            if value is not None and any(
                    self._target_key(t) == key for t in targets):
                self._bless_clock(value)

    def visit_IfExp(self, node: ast.IfExp) -> None:
        # Only the branch taken when the value was NOT injected is the
        # production default: ``time.time() if now is None else now``
        # blesses the body, ``now if now is not None else time.time()``
        # blesses the orelse.  The other branch runs precisely when the
        # caller DID pass a value and gets no exemption.  The whole
        # branch is the default VALUE here — wherever the expression's
        # result flows, it only carries the clock when nothing was
        # injected — so no assignment-target check applies.
        nt = self._none_test(node.test)
        if nt is not None:
            op, _ = nt
            self._bless_clock(node.body if op is ast.Is
                              else node.orelse)
        self.generic_visit(node)

    def visit_If(self, node: ast.If) -> None:
        nt = self._none_test(node.test)
        if nt is not None:
            op, tested = nt
            self._bless_default_stmts(
                tested, node.body if op is ast.Is else node.orelse)
        self.generic_visit(node)

    def _bless(self, expr: ast.AST) -> None:
        for sub in ast.walk(expr):
            if isinstance(sub, (ast.GeneratorExp, ast.ListComp,
                                ast.SetComp, ast.DictComp)):
                self._exempt.add(id(sub))
            elif _is_set_expr(sub, self.set_locals):
                self._exempt.add(id(sub))

    # -- id()-keyed maps --------------------------------------------------

    @staticmethod
    def _contains_id_call(expr: ast.AST) -> bool:
        return any(
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Name) and sub.func.id == "id"
            for sub in ast.walk(expr))

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if self._contains_id_call(node.slice):
            self._emit(node.lineno, "TAD903",
                       "keys a map by 'id(...)' — ids are allocation "
                       "order, different every run")
        self.generic_visit(node)

    def visit_Dict(self, node: ast.Dict) -> None:
        for key in node.keys:
            if key is not None and self._contains_id_call(key):
                self._emit(key.lineno, "TAD903",
                           "keys a dict literal by 'id(...)'")
        self.generic_visit(node)

    # -- set iteration ----------------------------------------------------

    def visit_For(self, node: ast.For) -> None:
        if _is_set_expr(node.iter, self.set_locals) \
                and id(node.iter) not in self._exempt \
                and not _order_free_body(node.body):
            self._emit(node.iter.lineno, "TAD904",
                       "iterates a set in hash order feeding an "
                       "order-sensitive fold — wrap it in sorted() "
                       "(XOR-only folds are exempt)")
        self.generic_visit(node)

    def _visit_comp(self, node: ast.AST,
                    generators: list[ast.comprehension]) -> None:
        if id(node) not in self._exempt:
            for gen in generators:
                if _is_set_expr(gen.iter, self.set_locals) \
                        and id(gen.iter) not in self._exempt:
                    self._emit(gen.iter.lineno, "TAD904",
                               "iterates a set in hash order inside a "
                               "comprehension — wrap it in sorted()")
        self.generic_visit(node)  # type: ignore[arg-type]

    def visit_ListComp(self, node: ast.ListComp) -> None:
        self._visit_comp(node, node.generators)

    def visit_GeneratorExp(self, node: ast.GeneratorExp) -> None:
        self._visit_comp(node, node.generators)

    def visit_DictComp(self, node: ast.DictComp) -> None:
        self._visit_comp(node, node.generators)

    def visit_SetComp(self, node: ast.SetComp) -> None:
        # Building a SET from a set is order-free by construction.
        self.generic_visit(node)


class DeterminismChecker(ProgramChecker):
    name = "determinism"
    codes = {
        "TAD901": "wall-clock read under the replay contract",
        "TAD902": "unseeded randomness under the replay contract",
        "TAD903": "id()-keyed map under the replay contract",
        "TAD904": "unsorted set iteration feeding an order-sensitive "
                  "fold",
    }

    def applies_to(self, rel_path: str) -> bool:
        return "tpu_autoscaler/testing/" not in rel_path

    def check_program(self, files: list[SourceFile]) -> list[Finding]:
        graph = shared_graph(files)

        # Roots: contract-module functions + digest builders, each
        # carrying its contract tag through the closure.
        tags: dict[str, str] = {}
        worklist: list[str] = []
        for fn in graph.funcs.values():
            tag = CONTRACT_MODULES.get(fn.rel_path)
            if tag is None and "digest" in fn.node.name.lower():
                tag = "digest"
            if tag is not None and fn.qname not in tags:
                tags[fn.qname] = tag
                worklist.append(fn.qname)
        while worklist:
            q = worklist.pop()
            for callee in graph.edges.get(q, ()):
                if callee not in tags and callee in graph.funcs:
                    tags[callee] = tags[q]
                    worklist.append(callee)

        findings: list[Finding] = []
        for qname, tag in sorted(tags.items()):
            fn = graph.funcs[qname]
            scan = _FnScan(fn, tag, graph)
            scan.visit(fn.node)
            findings.extend(scan.findings)
        findings.sort(key=lambda f: (f.file, f.line, f.code))
        return findings

