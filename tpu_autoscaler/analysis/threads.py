"""Thread-discipline checker (TAT2xx).

The codebase's threading contract (controller/watch.py docstring): a
background thread (``WatchTrigger``) shares state with the reconcile
loop only through ``threading.Event``/``Lock`` primitives — everything
else a thread object mutates after ``__init__`` must be owned by the
thread (touched only from ``run()`` and its private helpers), and
classes that hold a ``Lock`` must take it around every shared write.
This checker turns that contract into findings:

- a class is IN SCOPE when it subclasses ``threading.Thread`` or
  assigns a ``threading.Lock()``/``RLock()`` to ``self`` in
  ``__init__``;
- attribute writes in ``__init__`` are construction, always fine;
- calls on synchronization primitives themselves (``self._stop.set()``)
  are the sanctioned cross-thread channel, always fine;
- for lock-holding classes, every other ``self.X`` write must sit
  lexically inside ``with self.<lock>:`` (TAT201);
- for Thread subclasses, ``self.X`` writes are additionally fine in
  methods reachable ONLY from ``run()`` (thread-owned state); a write
  in a method callable from outside the thread is a cross-thread race
  unless lock-guarded (TAT202).

Codes:

- TAT201 — unguarded attribute write in a lock-holding class;
- TAT202 — cross-thread attribute write in a Thread subclass.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tpu_autoscaler.analysis.core import (
    Checker,
    Finding,
    SourceFile,
    dotted_name,
)
from tpu_autoscaler.analysis.purity import MUTATING_METHODS

_LOCK_CTORS = frozenset({"Lock", "RLock"})
_SYNC_CTORS = frozenset({"Lock", "RLock", "Event", "Condition",
                         "Semaphore", "BoundedSemaphore", "Barrier"})


def _ctor_name(value: ast.AST) -> str | None:
    """'Lock' for ``threading.Lock()`` / ``Lock()``, else None."""
    if isinstance(value, ast.Call):
        d = dotted_name(value.func)
        if d is not None:
            return d.split(".")[-1]
    return None


def _self_attr(node: ast.AST) -> str | None:
    """'x' for a bare ``self.x`` expression."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _self_attr_root(node: ast.AST) -> str | None:
    """'x' for ``self.x``, ``self.x[...]``, ``self.x.y`` chains."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        found = _self_attr(node)
        if found is not None:
            return found
        node = node.value
    return None


def _walk_method(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a method body WITHOUT descending into nested classes (their
    ``self`` is a different object) or nested functions that rebind
    ``self`` as a parameter; plain closures keep the outer ``self`` and
    are walked."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, ast.ClassDef):
            continue
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(a.arg == "self" for a in node.args.args):
                continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class _ClassInfo:
    def __init__(self, node: ast.ClassDef) -> None:
        self.node = node
        self.is_thread = any(
            (dotted_name(b) or "").split(".")[-1] == "Thread"
            for b in node.bases)
        self.lock_attrs: set[str] = set()
        self.sync_attrs: set[str] = set()
        self.methods: dict[str, ast.FunctionDef] = {
            n.name: n for n in node.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        init = self.methods.get("__init__")
        if init is not None:
            for sub in ast.walk(init):
                # Plain and annotated assignment both bind primitives:
                # ``self._lock = Lock()`` and
                # ``self._lock: threading.Lock = Lock()``.
                if isinstance(sub, ast.Assign):
                    targets, value = sub.targets, sub.value
                elif isinstance(sub, ast.AnnAssign) and sub.value is not None:
                    targets, value = [sub.target], sub.value
                else:
                    continue
                ctor = _ctor_name(value)
                for t in targets:
                    attr = _self_attr(t)
                    if attr and ctor in _SYNC_CTORS:
                        self.sync_attrs.add(attr)
                        if ctor in _LOCK_CTORS:
                            self.lock_attrs.add(attr)

    def thread_owned_methods(self) -> set[str]:
        """Methods reachable from ``run()`` and from NOWHERE else in the
        class — the thread's private call graph.  A method also called
        by an externally-callable method is shared, hence not owned."""
        calls: dict[str, set[str]] = {}
        for name, fn in self.methods.items():
            called: set[str] = set()
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Call):
                    attr = _self_attr(sub.func)
                    if attr in self.methods:
                        called.add(attr)
            calls[name] = called

        def closure(roots: set[str]) -> set[str]:
            seen = set(roots)
            frontier = list(roots)
            while frontier:
                for nxt in calls.get(frontier.pop(), ()):
                    if nxt not in seen:
                        seen.add(nxt)
                        frontier.append(nxt)
            return seen

        if "run" not in self.methods:
            return set()
        from_run = closure({"run"})
        external_roots = {n for n in self.methods
                          if n not in from_run and n != "__init__"}
        from_external = closure(external_roots)
        return from_run - from_external


class ThreadDisciplineChecker(Checker):
    """Self-scoping: runs on every file, reports only on classes that
    subclass Thread or hold locks."""

    name = "thread-discipline"
    codes = {
        "TAT201": "unguarded attribute write in a lock-holding class",
        "TAT202": "cross-thread attribute write in a Thread subclass",
    }

    def applies_to(self, rel_path: str) -> bool:
        return True

    def check(self, src: SourceFile) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                info = _ClassInfo(node)
                if info.is_thread or info.lock_attrs:
                    findings.extend(self._check_class(src, info))
        return findings

    def _check_class(self, src: SourceFile,
                     info: _ClassInfo) -> list[Finding]:
        findings: list[Finding] = []
        owned = info.thread_owned_methods() if info.is_thread else set()
        for name, fn in info.methods.items():
            if name == "__init__" or name in owned:
                continue
            findings.extend(self._check_method(src, info, name, fn))
        return findings

    def _check_method(self, src: SourceFile, info: _ClassInfo,
                      method: str, fn: ast.FunctionDef) -> list[Finding]:
        findings: list[Finding] = []
        guarded: set[int] = set()  # line numbers under a lock guard

        for sub in _walk_method(fn):
            if isinstance(sub, ast.With):
                if any(_self_attr(item.context_expr) in info.lock_attrs
                       for item in sub.items):
                    guarded.update(range(sub.lineno,
                                         (sub.end_lineno or sub.lineno) + 1))

        def emit(node: ast.AST, attr: str, how: str) -> None:
            if node.lineno in guarded:
                return
            if info.lock_attrs:
                findings.append(Finding(
                    src.rel_path, node.lineno, "TAT201",
                    f"{info.node.name}.{method} {how} 'self.{attr}' "
                    f"outside 'with self.{sorted(info.lock_attrs)[0]}:'"))
            else:
                findings.append(Finding(
                    src.rel_path, node.lineno, "TAT202",
                    f"{info.node.name}.{method} {how} 'self.{attr}' but "
                    f"is callable from outside the thread (only run()'s "
                    f"private call graph may touch thread-owned state)"))

        for sub in _walk_method(fn):
            targets: list[ast.AST] = []
            if isinstance(sub, ast.Assign):
                targets = list(sub.targets)
            elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                targets = [sub.target]
            elif isinstance(sub, ast.Delete):
                targets = list(sub.targets)
            elif isinstance(sub, ast.Call):
                f = sub.func
                if isinstance(f, ast.Attribute) \
                        and f.attr in MUTATING_METHODS:
                    # Calls ON a sync primitive (Event.clear etc.) are
                    # the sanctioned channel; reassigning the primitive
                    # itself (handled below) is not.
                    attr = _self_attr_root(f.value)
                    if attr is not None and attr not in info.sync_attrs:
                        emit(sub, attr, f"mutates (.{f.attr})")
                continue
            for t in targets:
                attr = _self_attr_root(t)
                if attr is not None:
                    emit(t, attr, "writes")
        return findings
