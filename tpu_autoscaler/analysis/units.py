"""Units-of-measure checker for the cost algebra (TAU10xx).

The cost spine (cost/, repack/, policy/slo.py, policy/engine.py,
serving/scaler.py) is an algebra over FOUR incompatible quantities —
chips, seconds, chip-seconds and dollars — plus one rate
($/chip-hour) whose timebase differs from every accumulator by a
factor of 3600.  ``tpu_autoscaler/units.py`` gives each quantity a
zero-runtime-cost ``Annotated`` alias and two blessed constructors
(``chip_seconds``, ``usd``) as the only sanctioned dimension
crossings.  This checker makes the discipline machine-checked: it
seeds dimensions from the alias annotations and propagates them
through assignments, attribute tables, container elements, tuple
returns and resolved call edges on the shared :class:`PackageGraph`.

The dimension lattice is an exponent vector over four base units —
``chip``, ``second``, ``hour``, ``usd`` — so ``ChipSeconds`` is
``chip*s``, ``UsdPerChipHour`` is ``usd/(chip*hour)`` and
``Fraction`` is the PROVEN-dimensionless point (distinct from
unknown).  Multiplication adds vectors, division subtracts; the
literal ``3600``/``3600.0`` and the name ``SECONDS_PER_HOUR`` carry
``s/hour`` as direct multiply/divide operands (elsewhere a numeric
literal is polymorphic), which is what makes ``rate * cs / 3600.0``
come out as clean dollars while ``rate * cs`` leaves the
mixed-timebase residue TAU1002 exists to catch.

| code | meaning |
| --- | --- |
| TAU1001 | mixed-dimension add/sub, or a value bound against a declaration of another dimension |
| TAU1002 | a flow boundary carries a mixed-timebase dimension (per-hour x seconds without the /3600) |
| TAU1003 | dimensioned value exported to a metric whose name lacks the matching unit suffix |
| TAU1004 | budget-guard comparison or budget-function argument across dimensions |

Evidence-only, like TAR5xx/TAD9xx: an unresolved name, an unannotated
``float``, a dict read or an unresolvable callee is UNKNOWN and
produces no finding — only flow the checker actually proved
dimensioned can be flagged, so the pass runs with no baseline
(scripts/ci_gate.sh re-runs the family with ``--no-baseline``).
"""

from __future__ import annotations

import ast
import dataclasses

from tpu_autoscaler.analysis.callgraph import (
    FuncInfo,
    ModuleInfo,
    PackageGraph,
    _module_name,
    _short as _short_fn,
    shared_graph,
)
from tpu_autoscaler.analysis.core import (
    Finding,
    ProgramChecker,
    SourceFile,
    dotted_name,
)
from tpu_autoscaler.analysis.metricsdoc import (
    _METRIC_METHODS,
    _joinedstr_prefix,
)

#: Exponent vector over (chip, second, hour, usd).
Dim = tuple[int, int, int, int]

UNITS_MODULE = "tpu_autoscaler.units"

#: The alias lattice.  ``Fraction`` is PROVEN dimensionless — the
#: distinction from unknown matters: Fraction + ChipSeconds is a
#: finding, float + ChipSeconds is not (no evidence).
ALIAS_DIMS: dict[str, Dim] = {
    "Chips": (1, 0, 0, 0),
    "Seconds": (0, 1, 0, 0),
    "ChipSeconds": (1, 1, 0, 0),
    "UsdPerChipHour": (-1, 0, -1, 1),
    "Usd": (0, 0, 0, 1),
    "Fraction": (0, 0, 0, 0),
}

DIMLESS: Dim = (0, 0, 0, 0)

#: The conversion factor's dimension: multiplying by 3600 (or
#: SECONDS_PER_HOUR) turns hours into seconds; dividing turns
#: seconds into hours.  Carried ONLY as a direct mul/div operand —
#: anywhere else ``3600.0`` is just a number (a compare against it
#: must stay polymorphic, or every ``cs >= 3600.0`` would lie).
_SEC_PER_HOUR: Dim = (0, 1, -1, 0)

#: The one window algebra (policy/slo.py): mismatched dimensions fed
#: to or compared around these are budget-guard bugs (TAU1004), the
#: class of error where a dollar total silently gates a chip-seconds
#: budget.
_BUDGET_FUNCS = frozenset({"budget_remaining", "rolling_waste"})

#: Builtin pass-throughs: the result carries its argument's dimension.
_PASSTHROUGH = frozenset({"round", "abs", "float", "int", "min", "max"})

_SEQ_CONTAINERS = frozenset({
    "list", "List", "set", "Set", "frozenset", "FrozenSet", "tuple",
    "Sequence", "Iterable", "Iterator", "Collection", "deque", "Deque",
})
_DICTS = frozenset({
    "dict", "Dict", "Mapping", "MutableMapping", "OrderedDict",
    "defaultdict",
})

#: Metric-name suffix contract (docs/OPERATIONS.md): a series fed an
#: alias-dimensioned value must carry the unit in its name.  Keyed by
#: exact alias dimension — derived dimensions (a $/hour gauge) are
#: out of contract and skipped.
_SUFFIX_RULES: list[tuple[Dim, str, tuple[str, ...]]] = [
    (ALIAS_DIMS["ChipSeconds"], "ChipSeconds", ("chip_seconds",)),
    (ALIAS_DIMS["Usd"], "Usd", ("usd", "dollar")),
    (ALIAS_DIMS["Seconds"], "Seconds", ("seconds",)),
    (ALIAS_DIMS["Chips"], "Chips", ("chips",)),
    (ALIAS_DIMS["UsdPerChipHour"], "UsdPerChipHour", ("per_hour",)),
]

_BASE_SYMBOLS = ("chip", "s", "hour", "usd")


def _dim_str(dim: Dim) -> str:
    """Human spelling: the alias name when one matches, else the
    exponent product (``usd*s/hour`` for the classic residue)."""
    for name, d in ALIAS_DIMS.items():
        if d == dim:
            return "dimensionless (Fraction)" if dim == DIMLESS else name
    num = [sym if e == 1 else f"{sym}^{e}"
           for sym, e in zip(_BASE_SYMBOLS, dim) if e > 0]
    den = [sym if e == -1 else f"{sym}^{-e}"
           for sym, e in zip(_BASE_SYMBOLS, dim) if e < 0]
    if not num and not den:
        return "dimensionless"
    out = "*".join(num) or "1"
    if den:
        out += "/" + "*".join(den)
    return out


def _parse_str_ann(ann: ast.AST | None) -> ast.AST | None:
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            return ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return None
    return ann


def _ann_leaf(ann: ast.AST) -> str:
    d = dotted_name(ann)
    return d.split(".")[-1] if d else ""


def _is_numeric_literal(expr: ast.AST) -> bool:
    return (isinstance(expr, ast.Constant)
            and isinstance(expr.value, (int, float))
            and not isinstance(expr.value, bool))


@dataclasses.dataclass
class _Env:
    """One function's dimension environment (flow-insensitive)."""

    dims: dict[str, Dim]
    #: annotation-derived only — the contract TAU1001's
    #: assigned-against-declaration check holds values to.
    declared: dict[str, Dim]
    #: name -> (annotation node, module it reads in); feeds container
    #: element, tuple-part and dict-value queries.
    anns: dict[str, tuple[ast.AST, ModuleInfo]]
    #: class types, seeded from the graph and extended with loop
    #: bindings over annotated containers.
    types: dict[str, str]


class UnitsChecker(ProgramChecker):
    """Dimension discipline over the cost algebra (docs/ANALYSIS.md)."""

    name = "units"
    codes = {
        "TAU1001": "mixed-dimension add/sub or assignment against a "
                   "declaration of another dimension",
        "TAU1002": "mixed-timebase residue at a flow boundary (per-hour "
                   "rate crossed seconds without /3600)",
        "TAU1003": "dimensioned value exported to a metric whose name "
                   "lacks the matching unit suffix",
        "TAU1004": "budget-guard comparison or budget-function argument "
                   "across dimensions",
    }

    def applies_to(self, rel_path: str) -> bool:
        return "tpu_autoscaler/testing/" not in rel_path

    # -- program tables ----------------------------------------------------

    def _build_tables(self, files: list[SourceFile]) -> None:
        g = self.graph
        # Class attribute annotations: dataclass fields (class-body
        # AnnAssign — the graph's method-body inference never sees
        # them) plus ``self.x: T`` method-body declarations.
        self._attr_anns: dict[str, dict[str, tuple[ast.AST,
                                                   ModuleInfo]]] = {}
        for ci in g.classes.values():
            mod = g.modules[_module_name(ci.rel_path)]
            table: dict[str, tuple[ast.AST, ModuleInfo]] = {}
            for stmt in ci.node.body:
                if isinstance(stmt, ast.AnnAssign) \
                        and isinstance(stmt.target, ast.Name):
                    table.setdefault(stmt.target.id,
                                     (stmt.annotation, mod))
            for fn in ci.methods.values():
                for node in ast.walk(fn.node):
                    if isinstance(node, ast.AnnAssign) \
                            and isinstance(node.target, ast.Attribute) \
                            and isinstance(node.target.value, ast.Name) \
                            and node.target.value.id == "self":
                        table.setdefault(node.target.attr,
                                         (node.annotation, mod))
            self._attr_anns[ci.qname] = table
        # Module-level annotated globals.
        self._global_anns: dict[str, dict[str, tuple[ast.AST,
                                                     ModuleInfo]]] = {}
        for mod in g.modules.values():
            table = {}
            for stmt in mod.src.tree.body:
                if isinstance(stmt, ast.AnnAssign) \
                        and isinstance(stmt.target, ast.Name):
                    table[stmt.target.id] = (stmt.annotation, mod)
            self._global_anns[mod.modname] = table
        # Return dimensions: annotations first, then a two-iteration
        # inference pass so an unannotated helper returning ``x * y``
        # of known dims still propagates to its callers.
        self._ret_dim: dict[str, Dim] = {}
        self._ret_ann: dict[str, tuple[ast.AST, ModuleInfo]] = {}
        for q, fn in g.funcs.items():
            if fn.node.returns is not None:
                mod = g.modules[_module_name(fn.rel_path)]
                self._ret_ann[q] = (fn.node.returns, mod)
                dim = self._ann_dim(fn.node.returns, mod)
                if dim is not None:
                    self._ret_dim[q] = dim
        for _ in range(2):
            self._env_cache: dict[str, _Env] = {}
            for q, fn in g.funcs.items():
                if q in self._ret_dim or fn.node.returns is not None:
                    continue
                env = self._env(fn)
                dims = {self._expr_dim(node.value, fn, env)
                        for node in ast.walk(fn.node)
                        if isinstance(node, ast.Return)
                        and node.value is not None}
                if len(dims) == 1:
                    dim = dims.pop()
                    if dim is not None:
                        self._ret_dim[q] = dim
        self._env_cache = {}

    # -- annotation interpretation ----------------------------------------

    def _alias_name(self, ann: ast.AST, mod: ModuleInfo) -> str | None:
        """The units alias a Name/Attribute annotation denotes, chased
        through the import table (never the filesystem — fixtures that
        merely ``from tpu_autoscaler.units import ...`` resolve too)."""
        d = dotted_name(ann)
        if d is None:
            return None
        if "." in d:
            head, _, rest = d.partition(".")
            target = mod.imports.get(head)
            full = f"{target}.{rest}" if target else f"{mod.modname}.{d}"
        else:
            full = mod.imports.get(d) or f"{mod.modname}.{d}"
        if full.startswith(UNITS_MODULE + "."):
            leaf = full.rsplit(".", 1)[1]
            if leaf in ALIAS_DIMS:
                return leaf
        return None

    def _ann_dim(self, ann: ast.AST | None,
                 mod: ModuleInfo) -> Dim | None:
        """Scalar dimension of an annotation.  Plain ``float``/``int``
        is UNKNOWN, not dimensionless — only ``Fraction`` proves."""
        ann = _parse_str_ann(ann)
        if ann is None:
            return None
        if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
            return (self._ann_dim(ann.left, mod)
                    or self._ann_dim(ann.right, mod))
        if isinstance(ann, ast.Subscript):
            leaf = _ann_leaf(ann.value)
            if leaf in ("Optional", "Final", "Annotated"):
                sl = ann.slice
                if leaf == "Annotated" and isinstance(sl, ast.Tuple) \
                        and sl.elts:
                    sl = sl.elts[0]
                return self._ann_dim(sl, mod)
            return None                       # containers: no scalar dim
        name = self._alias_name(ann, mod)
        return ALIAS_DIMS.get(name) if name else None

    def _elem_ann(self, ann: ast.AST | None, mod: ModuleInfo
                  ) -> tuple[ast.AST, ModuleInfo] | None:
        """Element annotation of a homogeneous container annotation."""
        ann = _parse_str_ann(ann)
        if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
            return (self._elem_ann(ann.left, mod)
                    or self._elem_ann(ann.right, mod))
        if isinstance(ann, ast.Subscript):
            leaf = _ann_leaf(ann.value)
            if leaf == "Optional":
                return self._elem_ann(ann.slice, mod)
            if leaf in _SEQ_CONTAINERS and leaf not in ("tuple", "Tuple") \
                    and not isinstance(ann.slice, ast.Tuple):
                return (ann.slice, mod)
        return None

    def _tuple_anns(self, ann: ast.AST | None, mod: ModuleInfo
                    ) -> list[ast.AST] | None:
        ann = _parse_str_ann(ann)
        if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
            return (self._tuple_anns(ann.left, mod)
                    or self._tuple_anns(ann.right, mod))
        if isinstance(ann, ast.Subscript):
            leaf = _ann_leaf(ann.value)
            if leaf == "Optional":
                return self._tuple_anns(ann.slice, mod)
            if leaf in ("tuple", "Tuple") \
                    and isinstance(ann.slice, ast.Tuple):
                return list(ann.slice.elts)
        return None

    def _dict_kv_anns(self, ann: ast.AST | None, mod: ModuleInfo
                      ) -> tuple[ast.AST, ast.AST] | None:
        ann = _parse_str_ann(ann)
        if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
            return (self._dict_kv_anns(ann.left, mod)
                    or self._dict_kv_anns(ann.right, mod))
        if isinstance(ann, ast.Subscript):
            leaf = _ann_leaf(ann.value)
            if leaf == "Optional":
                return self._dict_kv_anns(ann.slice, mod)
            if leaf in _DICTS and isinstance(ann.slice, ast.Tuple) \
                    and len(ann.slice.elts) == 2:
                return (ann.slice.elts[0], ann.slice.elts[1])
        return None

    # -- class/attr resolution --------------------------------------------

    def _attr_ann(self, cls_qname: str, attr: str, depth: int = 0
                  ) -> tuple[ast.AST, ModuleInfo] | None:
        table = self._attr_anns.get(cls_qname)
        if table is not None and attr in table:
            return table[attr]
        ci = self.graph.classes.get(cls_qname)
        if ci is not None and depth < 4:
            for base in self.graph._package_bases(ci):
                found = self._attr_ann(base.qname, attr, depth + 1)
                if found is not None:
                    return found
        return None

    def _expr_cls(self, expr: ast.AST, fn: FuncInfo,
                  env: _Env) -> str | None:
        """Class qname of an expression: the graph's resolution plus
        this checker's dataclass-field annotations."""
        t = self.graph.expr_type(expr, fn, env.types)
        if t is not None:
            return t
        if isinstance(expr, ast.Attribute):
            base = self._expr_cls(expr.value, fn, env)
            if base is not None:
                aa = self._attr_ann(base, expr.attr)
                if aa is not None:
                    return self.graph._annotation_type(aa[0], aa[1])
        return None

    # -- expression annotations (for container queries) --------------------

    def _call_ret_ann(self, expr: ast.AST, fn: FuncInfo, env: _Env
                      ) -> tuple[ast.AST, ModuleInfo] | None:
        if not isinstance(expr, ast.Call):
            return None
        target = self.graph.resolve_callable(expr.func, fn, env.types)
        if target is None:
            return None
        return self._ret_ann.get(target.qname)

    def _expr_ann(self, expr: ast.AST, fn: FuncInfo, env: _Env
                  ) -> tuple[ast.AST, ModuleInfo] | None:
        if isinstance(expr, ast.Name):
            if expr.id in env.anns:
                return env.anns[expr.id]
            mod = self.graph.modules[_module_name(fn.rel_path)]
            return self._global_anns.get(mod.modname, {}).get(expr.id)
        if isinstance(expr, ast.Attribute):
            base = self._expr_cls(expr.value, fn, env)
            if base is not None:
                return self._attr_ann(base, expr.attr)
            d = dotted_name(expr)
            if d is not None and "." in d:
                head, _, rest = d.partition(".")
                mod = self.graph.modules[_module_name(fn.rel_path)]
                target = mod.imports.get(head)
                if target is not None and "." not in rest:
                    return self._global_anns.get(target, {}).get(rest)
            return None
        if isinstance(expr, ast.Call):
            return self._call_ret_ann(expr, fn, env)
        return None

    # -- dimension evaluation ---------------------------------------------

    @staticmethod
    def _is_conv_factor(expr: ast.AST) -> bool:
        if _is_numeric_literal(expr) and expr.value in (3600, 3600.0):
            return True
        d = dotted_name(expr)
        return d is not None and d.split(".")[-1] == "SECONDS_PER_HOUR"

    def _factor_dim(self, expr: ast.AST, fn: FuncInfo,
                    env: _Env) -> Dim | None:
        """Operand dimension inside a multiply/divide: numeric
        literals are dimensionless here (``chips * 2`` is chips), and
        the 3600 conversion factor carries s/hour."""
        if self._is_conv_factor(expr):
            return _SEC_PER_HOUR
        if _is_numeric_literal(expr):
            return DIMLESS
        if isinstance(expr, ast.UnaryOp):
            return self._factor_dim(expr.operand, fn, env)
        return self._expr_dim(expr, fn, env)

    def _expr_dim(self, expr: ast.AST, fn: FuncInfo,
                  env: _Env) -> Dim | None:
        if isinstance(expr, ast.Constant):
            return None                        # polymorphic literal
        if isinstance(expr, ast.Name):
            if expr.id in env.dims:
                return env.dims[expr.id]
            mod = self.graph.modules[_module_name(fn.rel_path)]
            ga = self._global_anns.get(mod.modname, {}).get(expr.id)
            return self._ann_dim(*ga) if ga else None
        if isinstance(expr, ast.Attribute):
            aa = self._expr_ann(expr, fn, env)
            return self._ann_dim(*aa) if aa else None
        if isinstance(expr, ast.UnaryOp):
            return self._expr_dim(expr.operand, fn, env)
        if isinstance(expr, ast.BinOp):
            op = expr.op
            if isinstance(op, (ast.Mult, ast.Div, ast.FloorDiv)):
                # The 3600 factor needs a DIMENSIONED partner: between
                # two bare literals (``threshold=500.0 / 3600.0``) it
                # is plain arithmetic, not a timebase crossing.
                if (self._is_conv_factor(expr.left)
                        and _is_numeric_literal(expr.right)) \
                        or (self._is_conv_factor(expr.right)
                            and _is_numeric_literal(expr.left)):
                    return None
                left = self._factor_dim(expr.left, fn, env)
                right = self._factor_dim(expr.right, fn, env)
                if left is None or right is None:
                    return None                # dim x unknown: no evidence
                if isinstance(op, ast.Mult):
                    return (left[0] + right[0], left[1] + right[1],
                            left[2] + right[2], left[3] + right[3])
                return (left[0] - right[0], left[1] - right[1],
                        left[2] - right[2], left[3] - right[3])
            if isinstance(op, (ast.Add, ast.Sub)):
                left = self._expr_dim(expr.left, fn, env)
                right = self._expr_dim(expr.right, fn, env)
                if left is not None and right is not None:
                    return left if left == right else None
                return left if left is not None else right
            if isinstance(op, ast.Mod):
                return self._expr_dim(expr.left, fn, env)
            return None
        if isinstance(expr, ast.Call):
            return self._call_dim(expr, fn, env)
        if isinstance(expr, ast.IfExp):
            return (self._expr_dim(expr.body, fn, env)
                    or self._expr_dim(expr.orelse, fn, env))
        if isinstance(expr, ast.BoolOp):
            for v in expr.values:
                dim = self._expr_dim(v, fn, env)
                if dim is not None:
                    return dim
            return None
        if isinstance(expr, ast.Subscript):
            if isinstance(expr.slice, ast.Constant) \
                    and isinstance(expr.slice.value, int):
                src_ann = self._expr_ann(expr.value, fn, env)
                if src_ann is not None:
                    parts = self._tuple_anns(*src_ann)
                    idx = expr.slice.value
                    if parts and 0 <= idx < len(parts):
                        return self._ann_dim(parts[idx], src_ann[1])
            return None                        # dict/list reads: unknown
        return None

    def _call_dim(self, call: ast.Call, fn: FuncInfo,
                  env: _Env) -> Dim | None:
        d = dotted_name(call.func)
        leaf = d.split(".")[-1] if d else None
        if leaf in _PASSTHROUGH:
            for arg in call.args:
                dim = self._expr_dim(arg, fn, env)
                if dim is not None:
                    return dim
            return None
        if leaf == "sum" and call.args:
            arg0 = call.args[0]
            if isinstance(arg0, (ast.GeneratorExp, ast.ListComp)):
                # comprehension targets were bound during env build
                return self._expr_dim(arg0.elt, fn, env)
            src_ann = self._expr_ann(arg0, fn, env)
            if src_ann is not None:
                ea = self._elem_ann(*src_ann)
                if ea is not None:
                    return self._ann_dim(*ea)
            return None
        target = self.graph.resolve_callable(call.func, fn, env.types)
        if target is not None:
            return self._ret_dim.get(target.qname)
        return None

    # -- per-function environment -----------------------------------------

    def _bind_name(self, node: ast.AST, ann: ast.AST,
                   mod: ModuleInfo, env: _Env) -> None:
        if not isinstance(node, ast.Name):
            return
        dim = self._ann_dim(ann, mod)
        if dim is not None:
            env.dims.setdefault(node.id, dim)
        env.anns.setdefault(node.id, (ann, mod))
        cls = self.graph._annotation_type(ann, mod)
        if cls is not None:
            env.types.setdefault(node.id, cls)

    def _bind_loop(self, target: ast.AST, it: ast.AST,
                   fn: FuncInfo, env: _Env) -> None:
        # dict.items()/.values()/.keys() over an annotated mapping.
        if isinstance(it, ast.Call) and not it.args \
                and isinstance(it.func, ast.Attribute) \
                and it.func.attr in ("items", "values", "keys"):
            base_ann = self._expr_ann(it.func.value, fn, env)
            if base_ann is None:
                return
            kv = self._dict_kv_anns(*base_ann)
            if kv is None:
                return
            key_ann, val_ann = kv
            mod = base_ann[1]
            if it.func.attr == "values":
                self._bind_name(target, val_ann, mod, env)
            elif it.func.attr == "keys":
                self._bind_name(target, key_ann, mod, env)
            elif isinstance(target, ast.Tuple) \
                    and len(target.elts) == 2:
                self._bind_name(target.elts[0], key_ann, mod, env)
                self._bind_name(target.elts[1], val_ann, mod, env)
            return
        src_ann = self._expr_ann(it, fn, env)
        if src_ann is None:
            return
        ea = self._elem_ann(*src_ann)
        if ea is None:
            return
        elem, mod = ea
        if isinstance(target, ast.Name):
            self._bind_name(target, elem, mod, env)
        elif isinstance(target, ast.Tuple):
            parts = self._tuple_anns(elem, mod)
            if parts and len(parts) == len(target.elts):
                for tgt, part in zip(target.elts, parts):
                    self._bind_name(tgt, part, mod, env)

    def _env(self, fn: FuncInfo) -> _Env:
        cached = self._env_cache.get(fn.qname)
        if cached is not None:
            return cached
        mod = self.graph.modules[_module_name(fn.rel_path)]
        env = _Env({}, {}, {}, self.graph.local_types(fn))
        args = fn.node.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            if a.annotation is not None:
                dim = self._ann_dim(a.annotation, mod)
                if dim is not None:
                    env.dims[a.arg] = dim
                    env.declared[a.arg] = dim
                env.anns.setdefault(a.arg, (a.annotation, mod))
        for _ in range(2):                     # aliases chain one hop
            for node in ast.walk(fn.node):
                if isinstance(node, ast.AnnAssign) \
                        and isinstance(node.target, ast.Name):
                    dim = self._ann_dim(node.annotation, mod)
                    if dim is not None:
                        env.dims.setdefault(node.target.id, dim)
                        env.declared.setdefault(node.target.id, dim)
                    env.anns.setdefault(node.target.id,
                                        (node.annotation, mod))
                elif isinstance(node, ast.Assign) \
                        and len(node.targets) == 1:
                    tgt = node.targets[0]
                    if isinstance(tgt, ast.Name):
                        dim = self._expr_dim(node.value, fn, env)
                        if dim is not None:
                            env.dims.setdefault(tgt.id, dim)
                        ra = self._call_ret_ann(node.value, fn, env)
                        if ra is not None:
                            env.anns.setdefault(tgt.id, ra)
                    elif isinstance(tgt, ast.Tuple):
                        ra = self._call_ret_ann(node.value, fn, env)
                        if ra is None:
                            continue
                        parts = self._tuple_anns(*ra)
                        if parts and len(parts) == len(tgt.elts):
                            for t, part in zip(tgt.elts, parts):
                                self._bind_name(t, part, ra[1], env)
                elif isinstance(node, ast.For):
                    self._bind_loop(node.target, node.iter, fn, env)
                elif isinstance(node, (ast.ListComp, ast.SetComp,
                                       ast.GeneratorExp, ast.DictComp)):
                    for gen in node.generators:
                        self._bind_loop(gen.target, gen.iter, fn, env)
        self._env_cache[fn.qname] = env
        return env

    # -- the check ---------------------------------------------------------

    def check_program(self, files: list[SourceFile]) -> list[Finding]:
        if not files:
            return []
        self.graph = shared_graph(files)
        self._build_tables(files)
        findings: list[Finding] = []
        for qname in sorted(self.graph.funcs):
            fn = self.graph.funcs[qname]
            scan = _FnScan(self, fn, self._env(fn))
            scan.visit(fn.node)
            findings.extend(scan.findings)
        findings.sort(key=lambda f: (f.file, f.line, f.code))
        return findings


class _FnScan(ast.NodeVisitor):
    """One function body's dimension findings."""

    def __init__(self, checker: UnitsChecker, fn: FuncInfo,
                 env: _Env) -> None:
        self.c = checker
        self.fn = fn
        self.env = env
        self.mod = checker.graph.modules[_module_name(fn.rel_path)]
        self.findings: list[Finding] = []

    def _emit(self, line: int, code: str, msg: str) -> None:
        where = _short_fn(self.fn.qname)
        self.findings.append(Finding(self.fn.rel_path, line, code,
                                     f"{where} {msg}"))

    def _dim(self, expr: ast.AST) -> Dim | None:
        return self.c._expr_dim(expr, self.fn, self.env)

    # -- TAU1002: mixed-timebase residue at flow boundaries ----------------

    def _check_residue(self, expr: ast.AST) -> None:
        dim = self._dim(expr)
        if dim is not None and dim[1] != 0 and dim[2] != 0:
            self._emit(
                expr.lineno, "TAU1002",
                f"carries the mixed-timebase dimension {_dim_str(dim)} "
                f"— a per-hour rate crossed a seconds quantity without "
                f"the /3600 conversion; use the blessed constructors "
                f"(units.chip_seconds / units.usd)")

    # -- statements --------------------------------------------------------

    def _target_declared(self, tgt: ast.AST) -> Dim | None:
        if isinstance(tgt, ast.Name):
            return self.env.declared.get(tgt.id)
        if isinstance(tgt, ast.Attribute):
            base = self.c._expr_cls(tgt.value, self.fn, self.env)
            if base is not None:
                aa = self.c._attr_ann(base, tgt.attr)
                if aa is not None:
                    return self.c._ann_dim(*aa)
        return None

    def _check_binding(self, tgt: ast.AST, tdim: Dim | None,
                       rhs: Dim | None, line: int,
                       what: str = "assigns") -> None:
        if tdim is not None and rhs is not None and tdim != rhs:
            self._emit(line, "TAU1001",
                       f"{what} a {_dim_str(rhs)} value to a target "
                       f"declared {_dim_str(tdim)}")

    def visit_Assign(self, node: ast.Assign) -> None:
        self._check_residue(node.value)
        rhs = self._dim(node.value)
        for tgt in node.targets:
            if isinstance(tgt, ast.Tuple):
                ra = self.c._call_ret_ann(node.value, self.fn, self.env)
                if ra is not None:
                    parts = self.c._tuple_anns(*ra)
                    if parts and len(parts) == len(tgt.elts):
                        for t, part in zip(tgt.elts, parts):
                            self._check_binding(
                                t, self._target_declared(t),
                                self.c._ann_dim(part, ra[1]),
                                node.value.lineno)
                continue
            self._check_binding(tgt, self._target_declared(tgt), rhs,
                                node.value.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._check_residue(node.value)
            tdim = self.c._ann_dim(node.annotation, self.mod)
            self._check_binding(node.target, tdim,
                                self._dim(node.value),
                                node.value.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_residue(node.value)
        if isinstance(node.op, (ast.Add, ast.Sub)):
            tdim = self._target_declared(node.target)
            rhs = self._dim(node.value)
            if tdim is not None and rhs is not None and tdim != rhs:
                self._emit(node.value.lineno, "TAU1001",
                           f"accumulates {_dim_str(rhs)} into a target "
                           f"declared {_dim_str(tdim)}")
        self.generic_visit(node)

    def visit_Return(self, node: ast.Return) -> None:
        if node.value is not None:
            self._check_residue(node.value)
            ra = self.c._ret_ann.get(self.fn.qname)
            if ra is not None:
                self._check_binding(node.value,
                                    self.c._ann_dim(*ra),
                                    self._dim(node.value),
                                    node.value.lineno, what="returns")
        self.generic_visit(node)

    # -- expressions -------------------------------------------------------

    def visit_BinOp(self, node: ast.BinOp) -> None:
        if isinstance(node.op, (ast.Add, ast.Sub)):
            left = self._dim(node.left)
            right = self._dim(node.right)
            if left is not None and right is not None and left != right:
                verb = "adds" if isinstance(node.op, ast.Add) \
                    else "subtracts"
                self._emit(node.lineno, "TAU1001",
                           f"{verb} {_dim_str(right)} "
                           f"{'to' if verb == 'adds' else 'from'} "
                           f"{_dim_str(left)} — incompatible dimensions")
        self.generic_visit(node)

    @staticmethod
    def _budgetish(expr: ast.AST) -> bool:
        if isinstance(expr, ast.Call):
            d = dotted_name(expr.func)
            return d is not None \
                and d.split(".")[-1] in _BUDGET_FUNCS
        d = dotted_name(expr)
        return d is not None and "budget" in d.split(".")[-1]

    def visit_Compare(self, node: ast.Compare) -> None:
        operands = [node.left, *node.comparators]
        for op in operands:
            self._check_residue(op)
        known = [(op, dim) for op in operands
                 if (dim := self._dim(op)) is not None]
        dims = {dim for _op, dim in known}
        if len(dims) > 1:
            rendered = " vs ".join(sorted(_dim_str(d) for d in dims))
            if any(self._budgetish(op) for op in operands):
                self._emit(node.lineno, "TAU1004",
                           f"budget guard compares across dimensions "
                           f"({rendered}) — a budget and its spend "
                           f"must share one currency")
            else:
                self._emit(node.lineno, "TAU1001",
                           f"compares incompatible dimensions "
                           f"({rendered})")
        self.generic_visit(node)

    # -- calls: metric escapes + argument contracts ------------------------

    def _metric_name(self, arg: ast.AST) -> str | None:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value
        if isinstance(arg, ast.JoinedStr):
            return _joinedstr_prefix(arg) or None
        return None

    def _check_metric(self, node: ast.Call) -> None:
        if not (isinstance(node.func, ast.Attribute)
                and node.func.attr in _METRIC_METHODS and node.args):
            return
        name = self._metric_name(node.args[0])
        if name is None:
            return
        value = node.args[1] if len(node.args) > 1 else next(
            (kw.value for kw in node.keywords
             if kw.arg in ("by", "value")), None)
        if value is None:
            return
        dim = self._dim(value)
        if dim is None:
            return
        for rule_dim, alias, needles in _SUFFIX_RULES:
            if dim != rule_dim:
                continue
            ok = any(n in name for n in needles)
            if alias == "Seconds" and "chip_seconds" in name:
                ok = False                     # plain seconds fed to a
            if not ok:                         # chip-seconds series
                want = "/".join(f"'{n}'" for n in needles)
                self._emit(
                    value.lineno, "TAU1003",
                    f"feeds a {alias}-dimensioned value to metric "
                    f"'{name}', whose name lacks the {want} unit "
                    f"suffix — rename the series or convert the value")
            return                             # alias dims are disjoint

    def _check_call_args(self, node: ast.Call) -> None:
        target = self.c.graph.resolve_callable(node.func, self.fn,
                                               self.env.types)
        if target is None:
            return
        tmod = self.c.graph.modules[_module_name(target.rel_path)]
        args = target.node.args
        params = list(args.posonlyargs) + list(args.args)
        if target.cls is not None and params \
                and params[0].arg in ("self", "cls") \
                and not isinstance(node.func, ast.Name):
            params = params[1:]
        by_name = {p.arg: p for p in params + list(args.kwonlyargs)}
        is_budget = target.node.name in _BUDGET_FUNCS

        def check(param: ast.arg, arg: ast.AST) -> None:
            pdim = self.c._ann_dim(param.annotation, tmod)
            adim = self._dim(arg)
            if pdim is None or adim is None or pdim == adim:
                return
            budget = is_budget or "budget" in param.arg
            self._emit(
                arg.lineno,
                "TAU1004" if budget else "TAU1001",
                f"passes a {_dim_str(adim)} value for parameter "
                f"'{param.arg}' of {_short_fn(target.qname)}, declared "
                f"{_dim_str(pdim)}"
                + (" — budget algebra must not mix currencies"
                   if budget else ""))

        for i, arg in enumerate(node.args):
            if isinstance(arg, ast.Starred) or i >= len(params):
                break
            check(params[i], arg)
        for kw in node.keywords:
            if kw.arg is not None and kw.arg in by_name:
                check(by_name[kw.arg], kw.value)

    def visit_Call(self, node: ast.Call) -> None:
        for arg in node.args:
            if not isinstance(arg, ast.Starred):
                self._check_residue(arg)
        for kw in node.keywords:
            self._check_residue(kw.value)
        self._check_metric(node)
        self._check_call_args(node)
        self.generic_visit(node)
