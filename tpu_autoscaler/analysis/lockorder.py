"""Whole-program lock-order analysis (TAL7xx).

The escape pass (TAR5xx) proves accesses are *guarded*; nothing proved
the guards themselves compose.  Two locks taken in opposite orders on
two threads deadlock with every access correctly guarded — invisible to
a lockset model, fatal in production.  This pass builds the global
lock-ACQUISITION-ORDER graph and checks it:

1. **Held-set propagation.**  For every function the pass computes the
   set of locks that may be held on entry: lexical ``with <lock>:``
   blocks enclosing each call site, propagated transitively over the
   package call graph (``callgraph.PackageGraph`` — the same resolved
   edges, Thread-``run()`` roots, pool-submit thunks and property edges
   the race pass trusts).  Thread roots and pool thunks start with an
   EMPTY held set: locks do not follow a ``submit()`` across threads —
   and for the same reason a nested def/lambda body is its own scope:
   it runs when CALLED, inheriting neither the definition site's
   ``with`` blocks nor the enclosing function's entry set.
2. **Order edges.**  Acquiring lock B at a point where lock A may be
   held adds the directed edge A → B, tagged with the acquisition site.
3. **Findings.**

   - TAL701 — a cycle in the order graph: two call chains acquire the
     same locks in opposite orders; under the wrong interleaving each
     thread holds what the other wants (potential deadlock);
   - TAL702 — ``Condition.wait()`` while holding a second lock: the
     wait releases only the condition's own lock, so the notifier can
     block forever on the one still held;
   - TAL703 — acquiring a NON-reentrant ``Lock`` that may already be
     held on the same call chain: self-deadlock (``RLock``/
     ``Condition`` re-entry is what those types are for and is not
     flagged).

Lock identity is ``callgraph.lock_id`` — the same naming the TAR5xx
locksets use — and every node carries its construction site
(``ClassInfo.attr_sites`` / ``ModuleInfo.global_sites``), which is the
join key for the runtime lock-order witness
(``tpu_autoscaler/concurrency.LockOrderWitness``): a witnessed edge
whose sites resolve to package locks but which is absent from this
graph is a checker gap and fails the race tier
(``tests/test_lockwitness.py``).

Precision notes, deliberately asymmetric like the race pass: an
unresolvable callee produces no edge, so a reported cycle rests
entirely on resolved evidence; held sets union over ALL call sites
(context-insensitive), so a lock can appear held at a callee one
caller never reaches — that over-approximation can only ADD edges,
never hide one, which is the right bias for a deadlock detector.
"""

from __future__ import annotations

import ast
import dataclasses

from tpu_autoscaler.analysis.callgraph import (
    SYNC_CONDITION,
    SYNC_LOCK,
    FuncInfo,
    PackageGraph,
    _is_property,
    _module_name,
    _short as _short_fn,
    lock_id,
    shared_graph,
)
from tpu_autoscaler.analysis.core import (
    Finding,
    ProgramChecker,
    SourceFile,
)


@dataclasses.dataclass(frozen=True)
class _Acquire:
    """One static lock acquisition: ``with <lock>:`` in ``fn``."""

    lid: str
    fn_qname: str
    rel_path: str
    line: int
    #: Locks held LEXICALLY at this with-statement (enclosing blocks
    #: of the SAME scope — a nested def's body is a separate scope).
    lexical: frozenset[str]
    #: True when the site lives inside a nested def/lambda: the body
    #: runs when CALLED (often on another thread via ``submit()``), so
    #: neither the enclosing with-blocks nor the function's propagated
    #: entry set is held there.
    deferred: bool = False


@dataclasses.dataclass(frozen=True)
class _Wait:
    """One ``<condition>.wait()`` call site."""

    lid: str                # the condition's own lock id
    fn_qname: str
    rel_path: str
    line: int
    lexical: frozenset[str]
    deferred: bool = False


def _split_scope(root: ast.AST) -> tuple[list[ast.AST], list[ast.AST]]:
    """Partition ``root``'s subtree into nodes of its OWN lexical scope
    and the nested def/lambda nodes whose bodies are separate (deferred)
    scopes — code inside them executes when called, not where defined,
    so definition-site lock context does not apply."""
    own: list[ast.AST] = []
    nested: list[ast.AST] = []
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            nested.append(node)
            continue
        own.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return own, nested


class LockOrderGraph:
    """The package's lock world: nodes, order edges, construction
    sites.  Built once per analyzed file set; consumed by the TAL7xx
    checker and by the runtime-witness cross-check."""

    def __init__(self, graph: PackageGraph) -> None:
        self.pkg = graph
        #: lock id -> synthetic type (@sync:Lock / RLock / Condition).
        self.lock_types: dict[str, str] = {}
        #: lock id -> (rel_path, line) of its constructing assignment.
        self.creation_sites: dict[str, tuple[str, int]] = {}
        #: (held, acquired) -> example acquisition (rel_path, line, fn).
        self.edges: dict[tuple[str, str], tuple[str, int, str]] = {}
        #: fn qname -> locks possibly held on entry (propagated).
        self.entry_held: dict[str, set[str]] = {}
        self._acquires: dict[str, list[_Acquire]] = {}
        self._waits: dict[str, list[_Wait]] = {}
        self._calls: dict[
            str, list[tuple[int, str, frozenset[str], bool]]] = {}
        #: fn qname -> with-lock ranges (lo, hi, lid, scope) — scope is
        #: None for the function body, the nested def's (lo, hi) span
        #: for deferred scopes; a range only holds at lines of its own
        #: scope.
        self._ranges: dict[
            str,
            list[tuple[int, int, str, tuple[int, int] | None]]] = {}
        #: fn qname -> nested def/lambda line spans (deferred scopes).
        self._deferred_spans: dict[str, list[tuple[int, int]]] = {}
        self._index()
        self._propagate()
        self._build_edges()

    # -- per-function extraction ------------------------------------------

    def _index(self) -> None:
        for fn in self.pkg.funcs.values():
            locals_ = self.pkg.local_types(fn)
            ranges: list[tuple[int, int, str,
                               tuple[int, int] | None]] = []
            acquires: list[_Acquire] = []
            calls: list[tuple[int, str, frozenset[str], bool]] = []
            waits: list[_Wait] = []
            spans: list[tuple[int, int]] = []

            # One pass per lexical scope: the function body first, then
            # every nested def/lambda as its own DEFERRED scope with an
            # empty starting lock context (the body runs when called —
            # a closure handed to ``submit()`` does not hold the
            # definition site's locks).
            pending: list[tuple[ast.AST, tuple[int, int] | None]] = [
                (fn.node, None)]
            while pending:
                scope_root, scope = pending.pop()
                own, nested = _split_scope(scope_root)
                for n in nested:
                    span = (n.lineno, n.end_lineno or n.lineno)
                    spans.append(span)
                    pending.append((n, span))
                deferred = scope is not None

                scope_ranges: list[tuple[int, int, str]] = []
                withs = sorted(
                    (n for n in own if isinstance(n, ast.With)),
                    key=lambda n: (n.lineno,
                                   -(n.end_lineno or n.lineno)))
                for node in withs:
                    for item in node.items:
                        lid = lock_id(item.context_expr, fn, locals_,
                                      self.pkg)
                        if lid is None:
                            continue
                        enclosing = frozenset(
                            r[2] for r in scope_ranges
                            if r[0] <= node.lineno <= r[1])
                        scope_ranges.append(
                            (node.lineno,
                             node.end_lineno or node.lineno, lid))
                        acquires.append(_Acquire(
                            lid, fn.qname, fn.rel_path, node.lineno,
                            enclosing, deferred))
                        t = self.pkg.expr_type(item.context_expr, fn,
                                               locals_)
                        if t is not None:
                            self.lock_types.setdefault(lid, t)
                            self._note_site(lid, item.context_expr, fn,
                                            locals_)
                ranges.extend((lo, hi, lid, scope)
                              for lo, hi, lid in scope_ranges)

                def lexical_at(line: int) -> frozenset[str]:
                    return frozenset(r[2] for r in scope_ranges
                                     if r[0] <= line <= r[1])

                for node in own:
                    if isinstance(node, ast.Call):
                        target = self.pkg.resolve_callable(node.func, fn,
                                                           locals_)
                        if target is not None:
                            calls.append((node.lineno, target.qname,
                                          lexical_at(node.lineno),
                                          deferred))
                        if isinstance(node.func, ast.Attribute) \
                                and node.func.attr == "wait":
                            t = self.pkg.expr_type(node.func.value, fn,
                                                   locals_)
                            if t == SYNC_CONDITION:
                                lid = lock_id(node.func.value, fn,
                                              locals_, self.pkg)
                                if lid is not None:
                                    waits.append(_Wait(
                                        lid, fn.qname, fn.rel_path,
                                        node.lineno,
                                        lexical_at(node.lineno),
                                        deferred))
                    elif isinstance(node, ast.Attribute) \
                            and isinstance(node.ctx, ast.Load):
                        base_t = self.pkg.expr_type(node.value, fn,
                                                    locals_)
                        ci = self.pkg.classes.get(base_t) \
                            if base_t else None
                        if ci is not None:
                            m = self.pkg._method(ci, node.attr)
                            if m is not None and _is_property(m.node):
                                calls.append((node.lineno, m.qname,
                                              lexical_at(node.lineno),
                                              deferred))
            self._acquires[fn.qname] = acquires
            self._waits[fn.qname] = waits
            self._calls[fn.qname] = calls
            self._ranges[fn.qname] = ranges
            self._deferred_spans[fn.qname] = spans

    def _note_site(self, lid: str, expr: ast.AST, fn: FuncInfo,
                   locals_: dict[str, str]) -> None:
        if lid in self.creation_sites:
            return
        if isinstance(expr, ast.Attribute):
            base_t = self.pkg.expr_type(expr.value, fn, locals_)
            ci = self.pkg.classes.get(base_t) if base_t else None
            # Breadth-first over ALL package bases (left-to-right MRO
            # preference — a lock created in a SECOND base must still
            # get its site or the witness join fails open), with a
            # visited set: statically cyclic inheritance is parseable
            # work-in-progress source the linter must survive.
            queue = [ci] if ci is not None else []
            seen: set[int] = set()
            while queue:
                ci = queue.pop(0)
                if id(ci) in seen:
                    continue
                seen.add(id(ci))
                site = ci.attr_sites.get(expr.attr)
                if site is not None:
                    self.creation_sites[lid] = site
                    return
                queue.extend(self.pkg._package_bases(ci))
        elif isinstance(expr, ast.Name):
            mod = self.pkg.modules.get(_module_name(fn.rel_path))
            if mod is not None and expr.id in mod.global_sites:
                self.creation_sites[lid] = (
                    mod.src.rel_path, mod.global_sites[expr.id])

    # -- interprocedural held-set propagation -----------------------------

    def _propagate(self) -> None:
        self.entry_held = {q: set() for q in self.pkg.funcs}
        worklist = list(self.pkg.funcs)
        in_list = set(worklist)
        while worklist:
            caller = worklist.pop()
            in_list.discard(caller)
            base = self.entry_held[caller]
            for line, callee, lexical, deferred in self._calls.get(
                    caller, ()):
                if callee not in self.entry_held:
                    continue
                # A call inside a nested def runs when the closure is
                # called (possibly on another thread): the enclosing
                # function's entry set is not held there.
                ctx = lexical if deferred else base | lexical
                tgt = self.entry_held[callee]
                if not ctx <= tgt:
                    tgt |= ctx
                    if callee not in in_list:
                        in_list.add(callee)
                        worklist.append(callee)

    # -- order edges ------------------------------------------------------

    def _build_edges(self) -> None:
        for qname, acquires in self._acquires.items():
            entry = self.entry_held.get(qname, set())
            for acq in acquires:
                held = acq.lexical if acq.deferred \
                    else entry | acq.lexical
                own = self.own_locks(acq.lid)
                for h in sorted(held):
                    if h in own or acq.lid in self.own_locks(h):
                        # Re-entry is TAL703's job; a Condition and the
                        # lock it wraps are ONE lock, not an ordering.
                        continue
                    self.edges.setdefault(
                        (h, acq.lid),
                        (acq.rel_path, acq.line, acq.fn_qname))

    def own_locks(self, lid: str) -> frozenset[str]:
        """``lid`` plus, for a Condition constructed over an explicit
        lock (``self._cond = Condition(self._lock)``), the wrapped
        lock's id: waiting on the condition releases THAT lock, so the
        two ids name one mutex for hold/order purposes."""
        head, _, attr = lid.rpartition(".")
        ci = self.pkg.classes.get(head)
        if ci is not None:
            target = ci.cond_aliases.get(attr)
            if target is not None:
                return frozenset((lid, f"{head}.{target}"))
        return frozenset((lid,))

    def _scope_of(self, fn_qname: str,
                  line: int) -> tuple[int, int] | None:
        """The innermost deferred (nested-def) span containing ``line``,
        or None for the function's own body."""
        best: tuple[int, int] | None = None
        for lo, hi in self._deferred_spans.get(fn_qname, ()):
            if lo <= line <= hi and (best is None or lo >= best[0]):
                best = (lo, hi)
        return best

    def in_deferred_scope(self, fn_qname: str, line: int) -> bool:
        """True when ``line`` sits inside a nested def/lambda of
        ``fn_qname`` — code that runs when the closure is called, not
        on the enclosing function's thread."""
        return self._scope_of(fn_qname, line) is not None

    def held_at(self, acq: "_Acquire | _Wait") -> frozenset[str]:
        if acq.deferred:
            return acq.lexical
        return frozenset(self.entry_held.get(acq.fn_qname, set())
                         | acq.lexical)

    def held_at_line(self, fn_qname: str, line: int) -> frozenset[str]:
        """Locks possibly held at an arbitrary line of ``fn_qname``:
        the propagated entry set plus lexically-enclosing with-blocks
        of the SAME scope (the TAB8xx blocking lint's query — a with
        spanning a nested def does not hold inside the def's body, and
        a deferred scope never inherits the entry set)."""
        scope = self._scope_of(fn_qname, line)
        lexical = frozenset(
            lid for lo, hi, lid, sc in self._ranges.get(fn_qname, ())
            if sc == scope and lo <= line <= hi)
        if scope is not None:
            return lexical
        return frozenset(self.entry_held.get(fn_qname, set())) | lexical

    def all_acquires(self) -> list[_Acquire]:
        return [a for accs in self._acquires.values() for a in accs]

    def all_waits(self) -> list[_Wait]:
        return [w for ws in self._waits.values() for w in ws]

    def cycles(self) -> list[list[str]]:
        """Elementary cycles via SCC decomposition: every SCC with more
        than one node yields one canonical cycle (smallest node first,
        following edges greedily) — enough to NAME the inversion without
        enumerating the combinatorial set."""
        index: dict[str, int] = {}
        low: dict[str, int] = {}
        on_stack: set[str] = set()
        stack: list[str] = []
        sccs: list[list[str]] = []
        counter = [0]
        adj: dict[str, list[str]] = {}
        for a, b in self.edges:
            adj.setdefault(a, []).append(b)
        for outs in adj.values():
            outs.sort()

        def strongconnect(v: str) -> None:
            work = [(v, iter(adj.get(v, ())))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack.add(w)
                        work.append((w, iter(adj.get(w, ()))))
                        advanced = True
                        break
                    if w in on_stack:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        scc.append(w)
                        if w == node:
                            break
                    if len(scc) > 1:
                        sccs.append(scc)

        for v in sorted(adj):
            if v not in index:
                strongconnect(v)

        cycles: list[list[str]] = []
        for scc in sccs:
            members = set(scc)
            start = min(scc)
            # DFS (not a greedy walk — a branching SCC can dead-end a
            # greedy path and silently drop the cycle) for a simple
            # path start -> ... -> start inside the SCC; one always
            # exists because the SCC is strongly connected.
            path = [start]
            on_path = {start}
            iters = [iter(adj.get(start, ()))]
            found = False
            while iters and not found:
                advanced = False
                for w in iters[-1]:
                    if w == start:
                        found = True
                        break
                    if w in members and w not in on_path:
                        path.append(w)
                        on_path.add(w)
                        iters.append(iter(adj.get(w, ())))
                        advanced = True
                        break
                if not advanced and not found:
                    on_path.discard(path.pop())
                    iters.pop()
            if found:
                cycles.append(path)
        return sorted(cycles)


def lock_order_graph(graph: PackageGraph) -> LockOrderGraph:
    """One LockOrderGraph per PackageGraph: TAL7xx and TAB8xx both
    consume it inside one run_analysis call.  Memoized on the graph
    itself — a 1:1 overlay needs no global cache, eviction policy, or
    identity guard of its own."""
    lg = graph.lock_order
    if not isinstance(lg, LockOrderGraph):
        lg = LockOrderGraph(graph)
        graph.lock_order = lg
    return lg



def _short_lock(lid: str) -> str:
    """'ObjectCache._lock' for 'tpu_autoscaler.k8s.informer.ObjectCache._lock'."""
    head, _, attr = lid.rpartition(".")
    leaf = head.split(".")[-1] if head else ""
    return f"{leaf}.{attr}" if leaf else lid


def witness_gaps(
    witnessed: "dict[tuple[tuple[str, int], tuple[str, int]], tuple[str, int]]",
    lg: LockOrderGraph,
) -> list[str]:
    """Cross-check runtime-witnessed lock-order edges against the
    static graph (the race tier's checker-gap gate, docs/ANALYSIS.md).

    ``witnessed`` is ``concurrency.LockOrderWitness.edges``: (held
    creation site, acquired creation site) -> acquisition file:line.
    Creation sites are joined to static lock ids through
    ``LockOrderGraph.creation_sites``; an edge BETWEEN TWO PACKAGE
    LOCKS that the static graph lacks means the static pass failed to
    resolve a call chain that nests acquisitions — a blind spot that
    would also hide a real inversion, so the race tier fails on it.
    Edges touching locks the static graph never indexed (test-fixture
    locals, harness plumbing) prove nothing about the checker and are
    ignored."""
    # A creation site can carry SEVERAL lids (an inherited lock attr is
    # noted under both Base._a and Sub._a): the join must try every
    # combination — keeping one arbitrary lid per site both invents
    # gaps (the witnessed nesting is modeled under the other lid) and
    # can mask real ones.  The site IS the runtime identity; any modeled
    # lid pair on it means the static pass saw the nesting.
    site_to_lids: dict[tuple[str, int], list[str]] = {}
    for lid, site in lg.creation_sites.items():
        site_to_lids.setdefault(site, []).append(lid)
    gaps: list[str] = []
    for (held_site, acq_site), at in sorted(witnessed.items()):
        held_lids = site_to_lids.get(held_site)
        acq_lids = site_to_lids.get(acq_site)
        if not held_lids or not acq_lids:
            continue
        if not any((h, a) in lg.edges
                   for h in held_lids for a in acq_lids):
            held_lid = min(held_lids)
            acq_lid = min(acq_lids)
            gaps.append(
                f"witnessed lock-order edge {_short_lock(held_lid)} -> "
                f"{_short_lock(acq_lid)} (acquired at {at[0]}:{at[1]}) "
                f"is ABSENT from the static TAL7xx graph — the static "
                f"pass has a blind spot")
    return gaps


class LockOrderChecker(ProgramChecker):
    name = "lock-order"
    codes = {
        "TAL701": "lock-order cycle (potential deadlock)",
        "TAL702": "Condition.wait while holding a second lock",
        "TAL703": "re-entrant acquisition of a non-reentrant Lock",
    }

    def applies_to(self, rel_path: str) -> bool:
        # Same carve-out as TAR5xx: the deterministic scheduler's mutual
        # exclusion is by construction (semaphore handoff), not locks.
        return "tpu_autoscaler/testing/" not in rel_path

    def check_program(self, files: list[SourceFile]) -> list[Finding]:
        lg = lock_order_graph(shared_graph(files))
        findings: list[Finding] = []

        for cycle in lg.cycles():
            ring = cycle + [cycle[0]]
            hops = []
            site = None
            for a, b in zip(ring, ring[1:]):
                edge = lg.edges.get((a, b))
                if edge is not None and site is None:
                    site = edge
                hops.append(f"{_short_lock(a)} -> {_short_lock(b)}"
                            + (f" (at {_short_fn(edge[2])})"
                               if edge is not None else ""))
            rel, line = (site[0], site[1]) if site is not None \
                else ("<unknown>", 0)
            findings.append(Finding(
                rel, line, "TAL701",
                "lock-order cycle (potential deadlock): "
                + "; ".join(hops)))

        for w in lg.all_waits():
            others = lg.held_at(w) - lg.own_locks(w.lid)
            if others:
                held = ", ".join(sorted(_short_lock(o) for o in others))
                findings.append(Finding(
                    w.rel_path, w.line, "TAL702",
                    f"{_short_fn(w.fn_qname)} waits on "
                    f"'{_short_lock(w.lid)}' while holding [{held}] — "
                    f"the wait releases only the condition's own lock, "
                    f"so the notifier can block forever on the one "
                    f"still held"))

        for acq in lg.all_acquires():
            if lg.lock_types.get(acq.lid) != SYNC_LOCK:
                continue                        # RLock/Condition re-enter
            if acq.lid in lg.held_at(acq):
                findings.append(Finding(
                    acq.rel_path, acq.line, "TAL703",
                    f"{_short_fn(acq.fn_qname)} acquires non-reentrant "
                    f"'{_short_lock(acq.lid)}' which may already be "
                    f"held on this call chain (self-deadlock)"))

        findings.sort(key=lambda f: (f.file, f.line, f.code))
        return findings
