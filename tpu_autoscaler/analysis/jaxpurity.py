"""JAX trace-purity checker (TAJ4xx).

Functions traced by ``jax.jit``/``pjit`` run as staged XLA programs:
host-sync primitives (``.item()``, ``float(traced)``, ``np.asarray`` on
a traced value) silently insert device→host transfers — under tracing
they either fail or, worse, constant-fold a value that should be
data-dependent — and Python side effects (print, logging, RNG, clock
reads) execute once at trace time, not per step.  The sanctioned escape
hatches are ``jax.debug.print``/``jax.debug.callback`` and
``jax.pure_callback``/``io_callback``; anything else is a latent
correctness bug that only manifests on real hardware.

Reachability is static and module-local: roots are functions decorated
with (or wrapped in) ``jax.jit``/``jit``/``pjit`` — including
``functools.partial(jax.jit, ...)`` — plus every module function or
same-class method a reachable function references by name (reference,
not just call: functions handed to ``lax.scan``/``lax.cond`` etc. are
traced too).  Names passed to the callback escape hatches are host
functions by design and are NOT marked reachable.

Codes:

- TAJ401 — host synchronization inside a jit-reachable function;
- TAJ402 — Python side effect inside a jit-reachable function.

Static-shape arithmetic (``int(x.shape[0])``, ``len(xs)``,
``math.prod(shape)``) is trace-safe and exempt.
"""

from __future__ import annotations

import ast

from tpu_autoscaler.analysis.core import (
    Checker,
    Finding,
    SourceFile,
    dotted_name,
)

DEFAULT_SCOPE = ("tpu_autoscaler/workloads/",)

#: attribute calls that force a device→host sync
_SYNC_METHODS = frozenset({"item", "tolist", "block_until_ready"})

#: jax APIs that pull values to host
_SYNC_CALLS = frozenset({"device_get", "copy_to_host_async"})

#: builtins that coerce a traced array on host
_COERCIONS = frozenset({"float", "int", "bool", "complex"})

#: obvious trace-time side effects
_EFFECT_BUILTINS = frozenset({"print", "input", "open"})

#: modules whose calls are trace-time side effects inside jit
_EFFECT_MODULES = frozenset({"time", "random", "logging"})

#: callback escape hatches: Names passed here are host-side by design
_CALLBACK_SINKS = frozenset({
    "pure_callback", "io_callback", "callback", "debug_callback",
})

_NUMPY_TOP = frozenset({"numpy"})


class _ModuleIndex(ast.NodeVisitor):
    """Functions/methods, import aliases, and jit roots of one module."""

    def __init__(self) -> None:
        #: (class_name | None, func_name) -> every def bound to that
        #: name, nested included.  A name clash (top-level def + a
        #: nested def of the same name, one of them jitted) is
        #: statically ambiguous — ALL defs under a rooted key are
        #: scanned, erring toward a visible (waivable) finding over a
        #: silent miss.
        self.functions: dict[tuple[str | None, str], list[ast.AST]] = {}
        #: module-level functions and class-body methods only — the set
        #: by-name references may resolve to (a def nested inside some
        #: OTHER function is a private closure; resolving a root's name
        #: reference to it would be a mere name collision)
        self.top_level: set[tuple[str | None, str]] = set()
        self.np_aliases: set[str] = set()     # numpy (NOT jax.numpy)
        #: names that are jax submodules (``from jax import random``,
        #: ``import jax.random as random``) — trace-pure, never side
        #: effects even when the local name shadows an effect module
        self.jax_aliases: set[str] = set()
        self.jit_names: set[str] = set()      # bare names bound to jit
        self.roots: set[tuple[str | None, str]] = set()
        self._class: str | None = None
        self._fn_depth = 0

    # -- imports -------------------------------------------------------- #

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            if alias.name.split(".")[0] in _NUMPY_TOP \
                    and alias.name != "jax.numpy":
                self.np_aliases.add(alias.asname or alias.name)
            if alias.name.startswith("jax.") and alias.asname:
                self.jax_aliases.add(alias.asname)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        mod = node.module or ""
        for alias in node.names:
            if mod == "jax" and alias.name in ("jit", "pjit"):
                self.jit_names.add(alias.asname or alias.name)
            if mod == "jax" or mod.startswith("jax."):
                self.jax_aliases.add(alias.asname or alias.name)
            if mod.startswith("jax.experimental.pjit") \
                    and alias.name == "pjit":
                self.jit_names.add(alias.asname or alias.name)

    # -- definitions ---------------------------------------------------- #

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        prev, self._class = self._class, node.name
        self.generic_visit(node)
        self._class = prev

    def _is_jit_expr(self, expr: ast.AST) -> bool:
        """Is ``expr`` jax.jit / jit / pjit / partial(jit, ...)?"""
        d = dotted_name(expr)
        if d is not None:
            last = d.split(".")[-1]
            if last in ("jit", "pjit") or d.split(".")[0] in self.jit_names:
                return True
        if isinstance(expr, ast.Call):
            # partial(jax.jit, static_argnums=...) or jax.jit(...) with
            # only keyword/config args (decorator-factory form).
            if self._is_jit_expr(expr.func):
                return True
            fd = dotted_name(expr.func)
            if fd is not None and fd.split(".")[-1] == "partial":
                return any(self._is_jit_expr(a) for a in expr.args[:1])
        return False

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        key = (self._class, node.name)
        self.functions.setdefault(key, []).append(node)
        if self._fn_depth == 0:
            self.top_level.add(key)
        if any(self._is_jit_expr(d) for d in node.decorator_list):
            self.roots.add(key)
        self._fn_depth += 1
        self.generic_visit(node)
        self._fn_depth -= 1

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]


def _collect_call_roots(index: _ModuleIndex, tree: ast.Module) -> None:
    """``jax.jit(f)`` / ``jit(f, ...)`` call forms: mark ``f``."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if not index._is_jit_expr(node.func):
            continue
        for arg in node.args[:1]:
            if isinstance(arg, ast.Name):
                for key in index.functions:
                    if key[1] == arg.id:
                        index.roots.add(key)


def _referenced_functions(index: _ModuleIndex, fn: ast.AST,
                          cls: str | None) -> set[tuple[str | None, str]]:
    """Module functions / same-class methods referenced by name inside
    ``fn`` — excluding names passed to callback escape hatches."""
    callback_args: set[int] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            d = dotted_name(node.func) or ""
            if d.split(".")[-1] in _CALLBACK_SINKS:
                for a in node.args:
                    if isinstance(a, ast.Name):
                        callback_args.add(id(a))
    refs: set[tuple[str | None, str]] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and id(node) not in callback_args:
            if (None, node.id) in index.top_level:
                refs.add((None, node.id))
        elif isinstance(node, ast.Attribute):
            if (isinstance(node.value, ast.Name)
                    and node.value.id == "self" and cls is not None
                    and (cls, node.attr) in index.top_level):
                refs.add((cls, node.attr))
    return refs


_META_ATTRS = frozenset({"shape", "ndim", "size", "dtype", "itemsize"})


def _static_shape_arith(node: ast.AST) -> bool:
    """int()/float() over static trace-time metadata is trace-safe:
    constants, ``.shape``/``.ndim``/``.size``/``.dtype`` access,
    ``len()``, ``math.*`` over those.  The whole expression must be
    built from safe parts — one ``.shape`` sub-term must not launder a
    sibling ``x.sum()`` host sync past the check."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Attribute):
        return node.attr in _META_ATTRS
    if isinstance(node, ast.Subscript):
        return _static_shape_arith(node.value)  # .shape[0] — index is
        # a Python int by construction or the jit trace itself fails
    if isinstance(node, ast.BinOp):
        return (_static_shape_arith(node.left)
                and _static_shape_arith(node.right))
    if isinstance(node, ast.UnaryOp):
        return _static_shape_arith(node.operand)
    if isinstance(node, ast.Call):
        d = dotted_name(node.func) or ""
        return d == "len" or d.startswith("math.")
    if isinstance(node, ast.Tuple):
        return all(_static_shape_arith(e) for e in node.elts)
    return False  # bare Name included: could be a traced array


class JaxPurityChecker(Checker):
    name = "jax-purity"
    codes = {
        "TAJ401": "host synchronization inside a jit-traced function",
        "TAJ402": "Python side effect inside a jit-traced function",
    }

    def __init__(self, scope: tuple[str, ...] = DEFAULT_SCOPE) -> None:
        self._scope = scope

    def applies_to(self, rel_path: str) -> bool:
        return any(s in rel_path for s in self._scope)

    def check(self, src: SourceFile) -> list[Finding]:
        index = _ModuleIndex()
        index.visit(src.tree)
        _collect_call_roots(index, src.tree)
        if not index.roots:
            return []

        # Transitive closure over by-name references; every def bound
        # to a reachable key is scanned (see _ModuleIndex.functions).
        reachable: set[tuple[str | None, str]] = set()
        frontier = list(index.roots)
        while frontier:
            key = frontier.pop()
            if key in reachable:
                continue
            reachable.add(key)
            for fn in index.functions[key]:
                for ref in _referenced_functions(index, fn, key[0]):
                    if ref not in reachable:
                        frontier.append(ref)

        findings: list[Finding] = []
        for key in sorted(reachable, key=lambda k: (k[0] or "", k[1])):
            for fn in index.functions[key]:
                findings.extend(self._check_function(src, index, fn, key))
        # A nested def sharing its encloser's name is walked both as a
        # list member and inside the encloser's body — report once.
        return list(dict.fromkeys(findings))

    def _check_function(self, src: SourceFile, index: _ModuleIndex,
                        fn: ast.AST, key: tuple[str | None, str]
                        ) -> list[Finding]:
        where = f"{key[0]}.{key[1]}" if key[0] else key[1]
        findings: list[Finding] = []

        def emit(node: ast.AST, code: str, msg: str) -> None:
            findings.append(Finding(
                src.rel_path, node.lineno, code,
                f"{msg} in jit-reachable '{where}'"))

        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            d = dotted_name(func)
            if isinstance(func, ast.Attribute):
                if func.attr in _SYNC_METHODS:
                    emit(node, "TAJ401",
                         f"host sync '.{func.attr}()'")
                    continue
                if d is not None:
                    top = d.split(".")[0]
                    last = d.split(".")[-1]
                    if top in index.np_aliases and last in (
                            "asarray", "array", "save", "load"):
                        emit(node, "TAJ401",
                             f"'{d}()' materializes on host (use "
                             f"jax.numpy inside traced code)")
                        continue
                    if (top in _EFFECT_MODULES
                            and top not in index.jax_aliases) or (
                            top in ("log", "logger", "logging")
                            and last in ("debug", "info", "warning",
                                         "error", "exception")):
                        emit(node, "TAJ402",
                             f"trace-time side effect '{d}()'")
                        continue
            elif isinstance(func, ast.Name):
                if func.id in _EFFECT_BUILTINS:
                    emit(node, "TAJ402",
                         f"trace-time side effect '{func.id}()'")
                elif func.id in _COERCIONS and node.args:
                    arg = node.args[0]
                    if not _static_shape_arith(arg):
                        emit(node, "TAJ401",
                             f"'{func.id}()' on a possibly-traced value "
                             f"forces a host sync (hint: trace-safe "
                             f"shape arithmetic is exempt)")
        return findings
