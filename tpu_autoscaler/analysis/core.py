"""Shared infrastructure for the invariant linter (docs/ANALYSIS.md).

The repo's architectural invariants — planner purity, thread discipline,
crash-only exception hygiene, jit-traced purity — live in docstrings
(engine/planner.py, controller/watch.py, SURVEY §6.3).  This package
makes them machine-checked: each checker walks a file's AST and emits
``Finding`` records; the runner filters them through inline waivers and
the grandfather baseline (``analysis/baseline.toml``) and the CLI exits
non-zero on anything left.

Design constraints:

- stdlib only (ast + tokenize); the container must not need new deps;
- Python 3.10 (no ``tomllib``), so the baseline file is read/written by
  a deliberately tiny TOML-subset codec (``[[finding]]`` tables of
  string scalars — exactly what the baseline needs, nothing more);
- waivers are explicit and greppable: a finding is silenced only by an
  ``# analysis: allow=CODE`` comment on its line, a checker-specific
  waiver (the exception checker's ``# crash-only: <reason>``), or a
  baseline entry carrying a ``reason``.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Iterable, Sequence

#: Inline waiver: a comment STARTING ``# analysis: allow=TAP104``
#: (comma-separate several codes).  Anything after the codes is the
#: human reason.  Anchored so prose QUOTING the syntax (like this
#: file's docstrings) is not itself a waiver.
_ALLOW_RE = re.compile(r"\A#\s*analysis:\s*allow=([A-Z0-9,]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One invariant violation.  ``key`` (file, code, message) identifies
    the finding across line drift — the baseline matches on it, never on
    line numbers."""

    file: str
    line: int
    code: str
    message: str

    @property
    def key(self) -> tuple[str, str, str]:
        return (self.file, self.code, self.message)

    def render(self) -> str:
        return f"{self.file}:{self.line}:{self.code} {self.message}"


class SourceFile:
    """A parsed module plus its comment map (line -> comment text)."""

    def __init__(self, path: str, rel_path: str, text: str) -> None:
        self.path = path
        self.rel_path = rel_path
        self.text = text
        self.tree = ast.parse(text, filename=path)
        self.comments: dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(io.StringIO(text).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
        except tokenize.TokenError:  # analysis must not die on odd input
            pass

    @classmethod
    def load(cls, path: str, root: str | None = None) -> "SourceFile":
        with open(path, encoding="utf-8") as f:
            text = f.read()
        rel = os.path.relpath(path, root or os.getcwd())
        return cls(path, rel.replace(os.sep, "/"), text)

    def allowed_codes(self, line: int) -> set[str]:
        """Codes inline-waived on ``line`` via ``# analysis: allow=``."""
        m = _ALLOW_RE.search(self.comments.get(line, ""))
        return set(m.group(1).split(",")) if m else set()

    def waiver_lines(self) -> dict[int, set[str]]:
        """Every ``# analysis: allow=`` comment: line -> waived codes.
        Feeds the unused-waiver audit (a waiver matching no finding is
        itself a finding, so waivers shrink as debt is paid)."""
        out: dict[int, set[str]] = {}
        for line in self.comments:
            codes = self.allowed_codes(line)
            if codes:
                out[line] = codes
        return out

    def comment_in_range(self, first: int, last: int,
                         needle: str) -> bool:
        return any(needle in self.comments.get(n, "")
                   for n in range(first, last + 1))


class Checker:
    """Interface: subclasses set ``name``/``codes`` and implement both
    ``applies_to`` (path scoping) and ``check``."""

    name: str = ""
    codes: dict[str, str] = {}

    def applies_to(self, rel_path: str) -> bool:
        raise NotImplementedError

    def check(self, src: SourceFile) -> list[Finding]:
        raise NotImplementedError


class ProgramChecker(Checker):
    """A checker that needs the WHOLE program at once (the
    interprocedural escape/race pass): the runner hands it every
    in-scope SourceFile in one call instead of one file at a time.
    Findings flow through the same waiver/baseline machinery."""

    def check(self, src: SourceFile) -> list[Finding]:
        return self.check_program([src])

    def check_program(self, files: list[SourceFile]) -> list[Finding]:
        raise NotImplementedError


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def root_name(node: ast.AST) -> str | None:
    """The leftmost Name of a Name/Attribute/Subscript chain."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in ("__pycache__", ".git"))
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)
        elif p.endswith(".py"):
            yield p


# ---------------------------------------------------------------------- #
# Baseline: the grandfather list.  TOML subset: ``[[finding]]`` tables
# with ``key = "string"`` pairs; comments and blank lines.
# ---------------------------------------------------------------------- #

BASELINE_KEYS = ("file", "code", "message", "reason")


def parse_baseline(text: str, path: str = "baseline.toml",
                   require_reasons: bool = True) -> list[dict[str, str]]:
    """``require_reasons=False`` is for ``--write-baseline``: it must be
    able to HARVEST reasons from a baseline that still has empty ones
    (its own freshly-written entries), or regeneration would deadlock on
    the very file it produced."""
    entries: list[dict[str, str]] = []
    current: dict[str, str] | None = None
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line == "[[finding]]":
            current = {}
            entries.append(current)
            continue
        m = re.match(r'^(\w+)\s*=\s*(".*")\s*$', line)
        if m is None or current is None:
            raise ValueError(
                f"{path}:{lineno}: cannot parse {line!r} (expected "
                f"'[[finding]]' or 'key = \"value\"')")
        key, value = m.group(1), m.group(2)
        try:
            current[key] = ast.literal_eval(value)
        except (ValueError, SyntaxError):
            raise ValueError(
                f"{path}:{lineno}: bad string literal {value!r}") from None
    for i, e in enumerate(entries):
        missing = [k for k in ("file", "code", "message") if k not in e]
        if missing:
            raise ValueError(
                f"{path}: finding #{i + 1} missing key(s): {missing}")
        if require_reasons and not e.get("reason"):
            raise ValueError(
                f"{path}: finding #{i + 1} ({e['code']} in {e['file']}) "
                f"has no 'reason' — every grandfathered finding must "
                f"say why it is acceptable")
    return entries


def _toml_str(s: str) -> str:
    return '"' + s.replace("\\", "\\\\").replace('"', '\\"') + '"'


def render_baseline(findings: Sequence[Finding],
                    reasons: dict[tuple[str, str, str], str] | None = None) -> str:
    """Serialize findings as a baseline file.  ``reasons`` maps finding
    keys to justification strings (existing entries keep theirs on
    regeneration; new ones get a TODO the parser will reject until a
    human fills it in — regeneration must not silently bless findings)."""
    reasons = reasons or {}
    out = [
        "# Grandfathered invariant-linter findings (docs/ANALYSIS.md).",
        "# Regenerate: python -m tpu_autoscaler.analysis --write-baseline"
        " tpu_autoscaler/",
        "# Every entry needs a human-written 'reason'.",
    ]
    for f in sorted(set(findings), key=lambda f: (f.file, f.code, f.line)):
        out += [
            "",
            "[[finding]]",
            f"file = {_toml_str(f.file)}",
            f"code = {_toml_str(f.code)}",
            f"message = {_toml_str(f.message)}",
            f"reason = {_toml_str(reasons.get(f.key, ''))}",
        ]
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------- #
# Runner
# ---------------------------------------------------------------------- #

@dataclasses.dataclass
class AnalysisResult:
    findings: list[Finding]          # live (unwaived) findings
    waived: list[Finding]            # silenced by baseline entries
    stale_baseline: list[dict[str, str]]  # entries matching nothing
    errors: list[str]                # unparseable files etc.
    # Waivers that silenced nothing (TAW001 inline allow=, TAW002
    # crash-only) — reported like findings so debt-paying shrinks them,
    # mirroring the stale-baseline rule.
    unused_waivers: list[Finding] = dataclasses.field(
        default_factory=list)


def run_analysis(paths: Sequence[str], checkers: Sequence[Checker],
                 baseline: Sequence[dict[str, str]] | None = None,
                 root: str | None = None) -> AnalysisResult:
    baseline = list(baseline or [])
    by_key = {(e["file"], e["code"], e["message"]): e for e in baseline}
    live: list[Finding] = []
    waived: list[Finding] = []
    matched: set[tuple[str, str, str]] = set()
    errors: list[str] = []
    sources: list[SourceFile] = []
    for path in iter_py_files(paths):
        try:
            sources.append(SourceFile.load(path, root=root))
        except (OSError, SyntaxError, ValueError) as e:
            errors.append(f"{path}: {e}")
    src_by_rel = {s.rel_path: s for s in sources}
    used_inline: set[tuple[str, int, str]] = set()

    def consume(findings: Iterable[Finding]) -> None:
        for f in findings:
            src = src_by_rel.get(f.file)
            if src is not None and f.code in src.allowed_codes(f.line):
                used_inline.add((f.file, f.line, f.code))
                continue
            if f.key in by_key:
                matched.add(f.key)
                waived.append(f)
            else:
                live.append(f)

    per_file = [c for c in checkers if not isinstance(c, ProgramChecker)]
    program = [c for c in checkers if isinstance(c, ProgramChecker)]
    for src in sources:
        for checker in per_file:
            if checker.applies_to(src.rel_path):
                consume(checker.check(src))
    for checker in program:
        consume(checker.check_program(
            [s for s in sources if checker.applies_to(s.rel_path)]))

    unused: list[Finding] = []
    for src in sources:
        for line, codes in src.waiver_lines().items():
            for code in sorted(codes):
                if (src.rel_path, line, code) not in used_inline:
                    unused.append(Finding(
                        src.rel_path, line, "TAW001",
                        f"unused waiver: allow={code} matches no "
                        f"finding on this line"))
        for checker in per_file:
            audit = getattr(checker, "waiver_audit", None)
            if audit is None or not checker.applies_to(src.rel_path):
                continue
            all_lines, used_lines = audit(src)
            for line in sorted(all_lines - used_lines):
                unused.append(Finding(
                    src.rel_path, line, "TAW002",
                    "unused waiver: 'crash-only:' comment on a handler "
                    "that passes without it (or on no handler at all)"))

    stale = [e for e in baseline
             if (e["file"], e["code"], e["message"]) not in matched]
    live.sort(key=lambda f: (f.file, f.line, f.code))
    unused.sort(key=lambda f: (f.file, f.line, f.code))
    return AnalysisResult(live, waived, stale, errors, unused)
