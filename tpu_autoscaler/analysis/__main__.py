"""CLI: ``python -m tpu_autoscaler.analysis [paths] [options]``.

Exit codes: 0 clean (or baseline-waived), 1 findings, 2 usage/parse
errors.  ``--write-baseline`` regenerates ``analysis/baseline.toml``
from the current findings, preserving existing reasons; new entries get
an empty reason the parser rejects, so a human must justify each one
before the baseline loads again.
"""

from __future__ import annotations

import argparse
import os
import sys

from tpu_autoscaler.analysis import (
    default_checkers,
    parse_baseline,
    render_baseline,
    run_analysis,
)

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.toml")

#: Baseline entries key on repo-root-relative paths, so findings must
#: be relativized against the tree the package lives in — NOT the cwd,
#: or the gate would spuriously fail when run from anywhere else.
REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpu_autoscaler.analysis",
        description="Invariant linter: planner purity, thread "
                    "discipline, exception hygiene, jax purity.")
    parser.add_argument("paths", nargs="*",
                        default=[os.path.join(REPO_ROOT, "tpu_autoscaler")],
                        help="files or directories (default: the "
                             "installed tpu_autoscaler tree)")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        help="grandfather allowlist (default: the "
                             "packaged analysis/baseline.toml)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, ignoring the "
                             "baseline")
    parser.add_argument("--write-baseline", action="store_true",
                        help="regenerate the baseline from current "
                             "findings (preserves existing reasons)")
    parser.add_argument("--select", default="",
                        help="comma-separated code prefixes to report "
                             "(e.g. TAP,TAE3)")
    parser.add_argument("--races", action="store_true",
                        help="report only the interprocedural race "
                             "pass (TAR5xx) — the static half of "
                             "scripts/race.sh")
    parser.add_argument("--format", default="text",
                        choices=("text", "github"),
                        help="'github' emits ::error workflow-command "
                             "annotations for CI")
    parser.add_argument("--list-codes", action="store_true",
                        help="print every checker's codes and exit")
    args = parser.parse_args(argv)
    if args.races:
        if args.select:
            # Refusing beats silently discarding the user's filter: a
            # gate invoked with --select TAT --races must not exit 0 on
            # live TAT findings.
            parser.error("--races and --select are mutually exclusive")
        args.select = "TAR"

    checkers = default_checkers()
    if args.list_codes:
        for checker in checkers:
            for code, desc in sorted(checker.codes.items()):
                print(f"{code}  [{checker.name}]  {desc}")
        return 0

    baseline: list[dict[str, str]] = []
    reasons: dict[tuple[str, str, str], str] = {}
    if not args.no_baseline and os.path.exists(args.baseline):
        try:
            with open(args.baseline, encoding="utf-8") as f:
                # Regeneration parses leniently: it only harvests
                # reasons, and must be able to read a baseline whose
                # fresh entries still have the empty reason it wrote.
                baseline = parse_baseline(
                    f.read(), args.baseline,
                    require_reasons=not args.write_baseline)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        reasons = {(e["file"], e["code"], e["message"]): e.get("reason", "")
                   for e in baseline}

    result = run_analysis(args.paths, checkers,
                          baseline=None if args.write_baseline
                          else baseline, root=REPO_ROOT)
    for err in result.errors:
        print(f"error: {err}", file=sys.stderr)

    if args.write_baseline:
        text = render_baseline(result.findings, reasons)
        with open(args.baseline, "w", encoding="utf-8") as f:
            f.write(text)
        print(f"wrote {len(result.findings)} finding(s) to "
              f"{args.baseline}; fill in empty 'reason' fields")
        return 0

    prefixes = tuple(p for p in args.select.split(",") if p)
    shown = [f for f in result.findings
             if not prefixes or f.code.startswith(prefixes)]
    # Unused waivers (TAW00x) are meta-findings: always reported, never
    # code-selectable away — a dead waiver is debt regardless of which
    # slice of the analysis is being gated.
    shown += result.unused_waivers
    for f in shown:
        if args.format == "github":
            print(f"::error file={f.file},line={f.line},"
                  f"title={f.code}::{f.message}")
        else:
            print(f.render())
    for entry in result.stale_baseline:
        print(f"stale baseline entry (no longer matches anything): "
              f"{entry['code']} {entry['file']}: {entry['message']}",
              file=sys.stderr)
    if shown:
        print(f"\n{len(shown)} finding(s) "
              f"({len(result.waived)} baseline-waived)", file=sys.stderr)
    if result.errors:
        return 2
    return 1 if shown else 0


if __name__ == "__main__":
    sys.exit(main())
