"""CLI: ``python -m tpu_autoscaler.analysis [paths] [options]``.

Exit codes: 0 clean (or baseline-waived), 1 findings, 2 usage/parse
errors.  ``--write-baseline`` regenerates ``analysis/baseline.toml``
from the current findings, preserving existing reasons; new entries get
an empty reason the parser rejects, so a human must justify each one
before the baseline loads again.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

from tpu_autoscaler.analysis import (
    ProgramChecker,
    default_checkers,
    parse_baseline,
    render_baseline,
    run_analysis,
)

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.toml")

#: Baseline entries key on repo-root-relative paths, so findings must
#: be relativized against the tree the package lives in — NOT the cwd,
#: or the gate would spuriously fail when run from anywhere else.
REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def _changed_files(root: str) -> set[str] | None:
    """Repo-root-relative paths the working tree changed vs HEAD plus
    untracked files, or None when git is unavailable/not a repo — the
    caller then falls back to FULL output (fail open: a broken git must
    widen the gate, never silently narrow it)."""
    out: set[str] = set()
    for args in (("git", "diff", "--name-only", "HEAD", "--"),
                 ("git", "ls-files", "--others", "--exclude-standard")):
        try:
            proc = subprocess.run(args, cwd=root, capture_output=True,
                                  text=True, timeout=30)
        except (OSError, subprocess.SubprocessError):
            return None
        if proc.returncode != 0:
            return None
        out.update(line.strip() for line in proc.stdout.splitlines()
                   if line.strip())
    return out


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpu_autoscaler.analysis",
        description="Invariant linter: planner purity, thread "
                    "discipline, exception hygiene, jax purity.")
    parser.add_argument("paths", nargs="*",
                        default=[os.path.join(REPO_ROOT, "tpu_autoscaler")],
                        help="files or directories (default: the "
                             "installed tpu_autoscaler tree)")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        help="grandfather allowlist (default: the "
                             "packaged analysis/baseline.toml)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, ignoring the "
                             "baseline")
    parser.add_argument("--write-baseline", action="store_true",
                        help="regenerate the baseline from current "
                             "findings (preserves existing reasons)")
    parser.add_argument("--select", default="",
                        help="comma-separated code prefixes to report "
                             "(e.g. TAP,TAE3)")
    parser.add_argument("--races", action="store_true",
                        help="report only the interprocedural race "
                             "pass (TAR5xx) — the static half of "
                             "scripts/race.sh")
    parser.add_argument("--units", action="store_true",
                        help="report only the units-of-measure pass "
                             "(TAU10xx) over the cost algebra — runs "
                             "with no baseline in scripts/ci_gate.sh")
    parser.add_argument("--format", default="text",
                        choices=("text", "github"),
                        help="'github' emits ::error workflow-command "
                             "annotations for CI")
    parser.add_argument("--changed-only", action="store_true",
                        help="report only findings in files the git "
                             "working tree changed vs HEAD (plus "
                             "untracked files); the whole-program "
                             "passes still analyze the FULL file set, "
                             "so interprocedural findings stay sound — "
                             "only the report is scoped.  Falls back "
                             "to full output when git is unavailable.")
    parser.add_argument("--list-codes", action="store_true",
                        help="print every checker's codes and exit")
    args = parser.parse_args(argv)
    if args.changed_only and args.write_baseline:
        # A baseline regenerated from a scoped report would silently
        # DROP every out-of-scope grandfathered finding.
        parser.error("--changed-only and --write-baseline are "
                     "mutually exclusive")
    if args.races and args.units:
        parser.error("--races and --units are mutually exclusive")
    if args.races:
        if args.select:
            # Refusing beats silently discarding the user's filter: a
            # gate invoked with --select TAT --races must not exit 0 on
            # live TAT findings.
            parser.error("--races and --select are mutually exclusive")
        args.select = "TAR"
    if args.units:
        if args.select:
            parser.error("--units and --select are mutually exclusive")
        args.select = "TAU"

    checkers = default_checkers()
    if args.list_codes:
        for checker in checkers:
            for code, desc in sorted(checker.codes.items()):
                print(f"{code}  [{checker.name}]  {desc}")
        return 0

    baseline: list[dict[str, str]] = []
    reasons: dict[tuple[str, str, str], str] = {}
    if not args.no_baseline and os.path.exists(args.baseline):
        try:
            with open(args.baseline, encoding="utf-8") as f:
                # Regeneration parses leniently: it only harvests
                # reasons, and must be able to read a baseline whose
                # fresh entries still have the empty reason it wrote.
                baseline = parse_baseline(
                    f.read(), args.baseline,
                    require_reasons=not args.write_baseline)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        reasons = {(e["file"], e["code"], e["message"]): e.get("reason", "")
                   for e in baseline}

    result = run_analysis(args.paths, checkers,
                          baseline=None if args.write_baseline
                          else baseline, root=REPO_ROOT)
    for err in result.errors:
        print(f"error: {err}", file=sys.stderr)

    if args.write_baseline:
        text = render_baseline(result.findings, reasons)
        with open(args.baseline, "w", encoding="utf-8") as f:
            f.write(text)
        print(f"wrote {len(result.findings)} finding(s) to "
              f"{args.baseline}; fill in empty 'reason' fields")
        return 0

    prefixes = tuple(p for p in args.select.split(",") if p)
    shown = [f for f in result.findings
             if not prefixes or f.code.startswith(prefixes)]
    if args.changed_only:
        changed = _changed_files(REPO_ROOT)
        if changed is None:
            print("warning: --changed-only requested but git is "
                  "unavailable; reporting everything", file=sys.stderr)
        else:
            # Whole-program families bypass the scope filter: the
            # interprocedural passes mean an edit in changed file A can
            # mint a finding ANCHORED in unchanged file B (a new lock
            # held into B's callee, a metric row removed from the docs),
            # and CI keeps the tree clean of these codes — so any such
            # finding present locally was caused by the local edits,
            # whichever file it anchors to.  Per-file checkers anchor
            # where they are caused and scope soundly.  Derived from
            # the registered checkers so a future ProgramChecker
            # family scopes correctly the day it lands.
            wp = tuple(code for c in checkers
                       if isinstance(c, ProgramChecker)
                       for code in c.codes)
            shown = [f for f in shown
                     if f.file in changed or f.code.startswith(wp)]
            print(f"(--changed-only: reporting {len(changed)} changed "
                  f"file(s); whole-program passes saw the full tree)",
                  file=sys.stderr)
    # Unused waivers (TAW00x) are meta-findings: always reported, never
    # code-selectable OR scope-able away — the interprocedural passes
    # mean an edit in file A can kill the finding a waiver in untouched
    # file B was silencing, and a --changed-only run that hid that dead
    # waiver would pass locally only to fail CI's full-tree stage.
    shown += result.unused_waivers
    for f in shown:
        if args.format == "github":
            print(f"::error file={f.file},line={f.line},"
                  f"title={f.code}::{f.message}")
        else:
            print(f.render())
    for entry in result.stale_baseline:
        print(f"stale baseline entry (no longer matches anything): "
              f"{entry['code']} {entry['file']}: {entry['message']}",
              file=sys.stderr)
    if shown:
        print(f"\n{len(shown)} finding(s) "
              f"({len(result.waived)} baseline-waived)", file=sys.stderr)
    if result.errors:
        return 2
    return 1 if shown else 0


if __name__ == "__main__":
    sys.exit(main())
