"""Whole-package call graph with class/attribute type resolution.

The substrate for the interprocedural race detector (escape.py, TAR5xx):
where the per-class TAT2xx heuristic sees one class at a time, this
module indexes every module under analysis and resolves

- classes (with transitive base chasing, so a ``concurrency.Thread``
  subclass is a thread class just like a ``threading.Thread`` one),
- attribute types, from ``__init__``/method assignments whose right-hand
  side is a resolvable constructor or an annotated parameter, from
  ``AnnAssign`` annotations, and from container ``append`` calls (the
  element type of ``self._watches``),
- call edges: ``self.m()``, ``obj.m()``/``obj.prop`` on objects whose
  type is known, module functions, cross-module imports (chasing
  ``__init__`` re-exports), and constructors,
- thread roots: ``run()`` of Thread subclasses, ``Thread(target=f)``
  targets, and thunks handed to a worker pool — either a raw
  ``ThreadPoolExecutor``/``concurrency.pool_executor`` ``submit`` or
  the ``submit`` of a class that owns a pool (the ActuationExecutor
  shape), where the first argument (unwrapped through
  ``functools.partial``) runs on a worker thread while the completion
  callback runs on the submitting thread per the drain contract.

Resolution is deliberately conservative: an unresolvable callee simply
produces no edge.  The consequences are asymmetric by design — a missed
edge can only HIDE sharing (handled by the TAT2xx fallback and the
dynamic schedule harness), never invent it, so everything the escape
pass reports rests on evidence the graph actually resolved.

Known holes (documented, covered by layer 2): lambdas and callables
stored through dataclass fields are not chased; module-global objects
are not modeled; instances of one class are conflated (class-level
granularity).
"""

from __future__ import annotations

import ast
import dataclasses

from tpu_autoscaler.analysis.core import SourceFile, dotted_name

#: Synthetic type markers (anything not a package-class qname).
SYNC_LOCK = "@sync:Lock"
SYNC_RLOCK = "@sync:RLock"
SYNC_EVENT = "@sync:Event"
SYNC_CONDITION = "@sync:Condition"
SYNC_OTHER = "@sync:Other"
POOL = "@pool"

_SYNC_CTORS: dict[str, str] = {
    "Lock": SYNC_LOCK,
    "RLock": SYNC_RLOCK,
    "Event": SYNC_EVENT,
    "Condition": SYNC_CONDITION,
    "Semaphore": SYNC_OTHER,
    "BoundedSemaphore": SYNC_OTHER,
    "Barrier": SYNC_OTHER,
}
_POOL_CTORS = frozenset({"ThreadPoolExecutor", "pool_executor"})
LOCK_TYPES = frozenset({SYNC_LOCK, SYNC_RLOCK, SYNC_CONDITION})
SYNC_TYPES = frozenset(_SYNC_CTORS.values())

#: Thread-safe queues get their own marker, deliberately OUTSIDE
#: SYNC_TYPES: the TAB8xx lint needs to recognize a ``.get()``
#: receiver as a queue, but a queue attribute must not become a
#: ``sync_attr`` (that would change TAT2xx/TAR5xx exemptions).
SYNC_QUEUE = "@sync:Queue"
_QUEUE_CTORS = frozenset({
    "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
})

#: The root every externally-callable function belongs to.
MAIN_ROOT = "main"


@dataclasses.dataclass
class FuncInfo:
    qname: str                      # module.Class.method / module.func
    rel_path: str
    node: ast.FunctionDef | ast.AsyncFunctionDef
    cls: "ClassInfo | None"
    src: SourceFile


class ClassInfo:
    def __init__(self, qname: str, name: str, rel_path: str,
                 node: ast.ClassDef, src: SourceFile) -> None:
        self.qname = qname
        self.name = name
        self.rel_path = rel_path
        self.node = node
        self.src = src
        self.base_names: list[str] = [
            d for b in node.bases if (d := dotted_name(b)) is not None]
        self.methods: dict[str, FuncInfo] = {}
        self.attr_types: dict[str, str] = {}
        self.elem_types: dict[str, str] = {}   # container attr -> element
        self.sync_attrs: set[str] = set()
        self.lock_attrs: set[str] = set()
        #: sync attr -> (rel_path, line) of its constructing assignment —
        #: the identity the runtime lock-order witness records, so the
        #: static and witnessed graphs can be joined on creation site.
        self.attr_sites: dict[str, tuple[str, int]] = {}
        #: condition attr -> the lock attr it was constructed OVER
        #: (``self._cond = Condition(self._lock)``): waiting on the
        #: condition releases THAT lock, so the two ids alias for
        #: lock-order purposes.
        self.cond_aliases: dict[str, str] = {}
        self.is_thread = False                 # set by PackageGraph


class ModuleInfo:
    def __init__(self, modname: str, src: SourceFile) -> None:
        self.modname = modname
        self.src = src
        self.imports: dict[str, str] = {}      # local name -> dotted target
        self.functions: dict[str, FuncInfo] = {}
        self.classes: dict[str, ClassInfo] = {}
        self.global_types: dict[str, str] = {}  # module-level name -> type
        self.global_sites: dict[str, int] = {}  # module-level name -> line


def _module_name(rel_path: str) -> str:
    parts = rel_path[:-3].split("/")           # strip .py
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class PackageGraph:
    """Index + resolver + reachability over a set of SourceFiles."""

    def __init__(self, files: list[SourceFile]) -> None:
        self.modules: dict[str, ModuleInfo] = {}
        self.funcs: dict[str, FuncInfo] = {}
        self.classes: dict[str, ClassInfo] = {}
        self.edges: dict[str, set[str]] = {}
        #: root id -> entry func qname ("main" handled separately)
        self.thread_roots: dict[str, str] = {}
        #: func qname -> set of root ids (incl. MAIN_ROOT)
        self.roots_of: dict[str, frozenset[str]] = {}
        #: lazily-built lock-order overlay (lockorder.lock_order_graph
        #: owns the type) — 1:1 with this graph, so it lives here
        #: instead of a second id-keyed global cache with its own
        #: eviction policy and staleness guard.
        self.lock_order: object | None = None
        for src in files:
            self._index_module(src)
        self._resolve_thread_classes()
        self._infer_attr_types()
        self._build_edges_and_roots()
        self._compute_reachability()

    # -- indexing ---------------------------------------------------------

    def _index_module(self, src: SourceFile) -> None:
        mod = ModuleInfo(_module_name(src.rel_path), src)
        self.modules[mod.modname] = mod
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    mod.imports[alias.asname or alias.name.split(".")[0]] \
                        = alias.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                base = node.module
                if node.level:                 # relative import
                    parts = mod.modname.split(".")
                    is_pkg = src.rel_path.endswith("__init__.py")
                    keep = len(parts) - node.level + (1 if is_pkg else 0)
                    base = ".".join(parts[:max(keep, 1)] + [node.module])
                for alias in node.names:
                    mod.imports[alias.asname or alias.name] \
                        = f"{base}.{alias.name}"
        for stmt in src.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qname = f"{mod.modname}.{stmt.name}"
                fi = FuncInfo(qname, src.rel_path, stmt, None, src)
                mod.functions[stmt.name] = fi
                self.funcs[qname] = fi
            elif isinstance(stmt, ast.ClassDef):
                qname = f"{mod.modname}.{stmt.name}"
                ci = ClassInfo(qname, stmt.name, src.rel_path, stmt, src)
                mod.classes[stmt.name] = ci
                self.classes[qname] = ci
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        mq = f"{qname}.{sub.name}"
                        mi = FuncInfo(mq, src.rel_path, sub, ci, src)
                        ci.methods[sub.name] = mi
                        self.funcs[mq] = mi
            elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                t = self._value_type_shallow(stmt.value)
                if t is not None:
                    mod.global_types[stmt.targets[0].id] = t
                    mod.global_sites[stmt.targets[0].id] = stmt.value.lineno

    @staticmethod
    def _value_type_shallow(value: ast.AST) -> str | None:
        """Sync/pool markers from a bare constructor call (no module
        context needed — the ctor NAME is the contract)."""
        if isinstance(value, ast.Call):
            d = dotted_name(value.func)
            if d is not None:
                leaf = d.split(".")[-1]
                if leaf in _SYNC_CTORS:
                    return _SYNC_CTORS[leaf]
                if leaf in _QUEUE_CTORS:
                    return SYNC_QUEUE
                if leaf in _POOL_CTORS:
                    return POOL
        return None

    # -- symbol resolution ------------------------------------------------

    def resolve_symbol(
            self, dotted: str) -> "ClassInfo | FuncInfo | None":
        """A dotted name -> ClassInfo | FuncInfo | None, chasing
        re-exports through package ``__init__`` modules."""
        for _ in range(8):                      # re-export chase bound
            if "." not in dotted:
                return None
            modname, leaf = dotted.rsplit(".", 1)
            mod = self.modules.get(modname)
            if mod is None:
                # maybe 'a.b.c' where 'a.b.c' is itself a module: no leaf
                return None
            if leaf in mod.classes:
                return mod.classes[leaf]
            if leaf in mod.functions:
                return mod.functions[leaf]
            if leaf in mod.imports:
                dotted = mod.imports[leaf]
                continue
            return None
        return None

    def _resolve_name(self, name: str,
                      mod: ModuleInfo) -> "ClassInfo | FuncInfo | None":
        """A bare name in module scope -> ClassInfo/FuncInfo/None."""
        if name in mod.classes:
            return mod.classes[name]
        if name in mod.functions:
            return mod.functions[name]
        if name in mod.imports:
            return self.resolve_symbol(mod.imports[name])
        return None

    def _resolve_thread_classes(self) -> None:
        def chase(ci: ClassInfo, depth: int = 0) -> bool:
            if depth > 8:
                return False
            for base in ci.base_names:
                if base.split(".")[-1] == "Thread":
                    return True
                mod = self.modules[_module_name(ci.rel_path)]
                target = self._resolve_name(base.split(".")[0], mod) \
                    if "." not in base else self.resolve_symbol(
                        self._qualify(base, mod))
                if isinstance(target, ClassInfo) \
                        and chase(target, depth + 1):
                    return True
            return False

        for ci in self.classes.values():
            ci.is_thread = chase(ci)

    def _qualify(self, dotted: str, mod: ModuleInfo) -> str:
        head, _, rest = dotted.partition(".")
        target = mod.imports.get(head)
        if target is None:
            return f"{mod.modname}.{dotted}"
        return f"{target}.{rest}" if rest else target

    # -- type inference ---------------------------------------------------

    def _annotation_type(self, ann: ast.AST | None,
                         mod: ModuleInfo) -> str | None:
        """'ObjectCache', 'Metrics | None', Optional[X], 'X' strings."""
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return None
        if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
            return (self._annotation_type(ann.left, mod)
                    or self._annotation_type(ann.right, mod))
        if isinstance(ann, ast.Subscript):
            d = dotted_name(ann.value)
            if d is not None and d.split(".")[-1] == "Optional":
                return self._annotation_type(ann.slice, mod)
            return None                         # list[X] etc: no instance
        d = dotted_name(ann)
        if d is None:
            return None
        leaf = d.split(".")[-1]
        if leaf in _SYNC_CTORS:
            return _SYNC_CTORS[leaf]
        if leaf in _QUEUE_CTORS:
            return SYNC_QUEUE
        target = self.resolve_symbol(self._qualify(d, mod)) \
            if "." in d else self._resolve_name(d, mod)
        if isinstance(target, ClassInfo):
            return target.qname
        return None

    def _param_types(self, fn: FuncInfo) -> dict[str, str]:
        mod = self.modules[_module_name(fn.rel_path)]
        out: dict[str, str] = {}
        args = fn.node.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            t = self._annotation_type(a.annotation, mod)
            if t is not None:
                out[a.arg] = t
        return out

    def expr_type(self, expr: ast.AST, fn: FuncInfo,
                  local_types: dict[str, str]) -> str | None:
        """Type of an expression: Name via locals/params/globals,
        Attribute via the owner class's attr table, Call via ctor."""
        if isinstance(expr, ast.Name):
            if expr.id == "self" and fn.cls is not None:
                return fn.cls.qname
            if expr.id in local_types:
                return local_types[expr.id]
            mod = self.modules[_module_name(fn.rel_path)]
            if expr.id in mod.global_types:
                return mod.global_types[expr.id]
            return None
        if isinstance(expr, ast.Attribute):
            base_t = self.expr_type(expr.value, fn, local_types)
            ci = self.classes.get(base_t) if base_t else None
            if ci is not None:
                return self._attr_type(ci, expr.attr)
            return None
        if isinstance(expr, ast.Call):
            return self._ctor_type(expr, fn)
        if isinstance(expr, ast.BoolOp):
            # ``rest or GcpRest(...)``: first resolvable operand wins.
            for v in expr.values:
                t = self.expr_type(v, fn, local_types)
                if t is not None:
                    return t
            return None
        if isinstance(expr, ast.IfExp):
            return (self.expr_type(expr.body, fn, local_types)
                    or self.expr_type(expr.orelse, fn, local_types))
        return None

    def _attr_type(self, ci: ClassInfo, attr: str,
                   depth: int = 0) -> str | None:
        if attr in ci.attr_types:
            return ci.attr_types[attr]
        if depth < 6:
            for base in self._package_bases(ci):
                t = self._attr_type(base, attr, depth + 1)
                if t is not None:
                    return t
        return None

    def _package_bases(self, ci: ClassInfo) -> list[ClassInfo]:
        mod = self.modules[_module_name(ci.rel_path)]
        out: list[ClassInfo] = []
        for base in ci.base_names:
            target = self.resolve_symbol(self._qualify(base, mod)) \
                if "." in base else self._resolve_name(base, mod)
            if isinstance(target, ClassInfo):
                out.append(target)
        return out

    def _ctor_type(self, call: ast.Call, fn: FuncInfo) -> str | None:
        d = dotted_name(call.func)
        if d is None:
            return None
        leaf = d.split(".")[-1]
        if leaf in _SYNC_CTORS:
            return _SYNC_CTORS[leaf]
        if leaf in _QUEUE_CTORS:
            return SYNC_QUEUE
        if leaf in _POOL_CTORS:
            return POOL
        mod = self.modules[_module_name(fn.rel_path)]
        target = self.resolve_symbol(self._qualify(d, mod)) \
            if "." in d else self._resolve_name(d, mod)
        if isinstance(target, ClassInfo):
            return target.qname
        return None

    def local_types(self, fn: FuncInfo) -> dict[str, str]:
        """Flow-insensitive local name types: annotated params, ctor
        assignments, aliases of typed attributes, typed loop vars."""
        out = self._param_types(fn)
        for _ in range(2):                      # two passes: aliases chain
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    t = self.expr_type(node.value, fn, out)
                    if t is not None:
                        out.setdefault(node.targets[0].id, t)
                elif isinstance(node, ast.AnnAssign) \
                        and isinstance(node.target, ast.Name):
                    mod = self.modules[_module_name(fn.rel_path)]
                    t = self._annotation_type(node.annotation, mod)
                    if t is not None:
                        out.setdefault(node.target.id, t)
                elif isinstance(node, ast.For) \
                        and isinstance(node.target, ast.Name):
                    t = self._elem_type(node.iter, fn, out)
                    if t is not None:
                        out.setdefault(node.target.id, t)
        return out

    def _elem_type(self, it: ast.AST, fn: FuncInfo,
                   local_types: dict[str, str]) -> str | None:
        """Element type of ``for x in self.attr`` via append inference."""
        if isinstance(it, ast.Attribute):
            base_t = self.expr_type(it.value, fn, local_types)
            ci = self.classes.get(base_t) if base_t else None
            if ci is not None:
                return ci.elem_types.get(it.attr)
        return None

    def _infer_attr_types(self) -> None:
        """attr -> type for every class, from every method body."""
        for ci in self.classes.values():
            for name, fn in ci.methods.items():
                locals_ = self._param_types(fn)
                for node in ast.walk(fn.node):
                    if isinstance(node, ast.Assign):
                        targets: list[ast.AST] = list(node.targets)
                        value: ast.AST | None = node.value
                    elif isinstance(node, ast.AnnAssign):
                        targets = [node.target]
                        value = node.value
                    else:
                        if isinstance(node, ast.Call) \
                                and isinstance(node.func, ast.Attribute) \
                                and node.func.attr == "append" \
                                and node.args:
                            holder = node.func.value
                            if self._is_self_attr(holder):
                                et = self.expr_type(node.args[0], fn,
                                                    locals_)
                                if et is not None and isinstance(
                                        holder, ast.Attribute):
                                    ci.elem_types.setdefault(
                                        holder.attr, et)
                        continue
                    t: str | None = None
                    if value is not None:
                        t = self.expr_type(value, fn, locals_)
                    if t is None and isinstance(node, ast.AnnAssign):
                        mod = self.modules[_module_name(fn.rel_path)]
                        t = self._annotation_type(node.annotation, mod)
                    if t is None:
                        continue
                    for tgt in targets:
                        if self._is_self_attr(tgt):
                            attr = tgt.attr  # type: ignore[union-attr]
                            ci.attr_types.setdefault(attr, t)
                            if t in SYNC_TYPES:
                                ci.sync_attrs.add(attr)
                                if value is not None:
                                    ci.attr_sites.setdefault(
                                        attr, (ci.rel_path, value.lineno))
                                if t == SYNC_CONDITION \
                                        and isinstance(value, ast.Call):
                                    lk = value.args[0] if value.args \
                                        else next(
                                            (kw.value
                                             for kw in value.keywords
                                             if kw.arg == "lock"), None)
                                    if lk is not None \
                                            and self._is_self_attr(lk):
                                        ci.cond_aliases.setdefault(
                                            attr,
                                            lk.attr)  # type: ignore[union-attr]
                            if t in LOCK_TYPES:
                                ci.lock_attrs.add(attr)

    @staticmethod
    def _is_self_attr(node: ast.AST) -> bool:
        return (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self")

    # -- call edges + thread roots ----------------------------------------

    def resolve_callable(self, expr: ast.AST, fn: FuncInfo,
                         local_types: dict[str, str]) -> FuncInfo | None:
        """A callable-valued expression -> its FuncInfo: ``self.m``,
        ``obj.m`` (typed), module functions, ``functools.partial(f,..)``."""
        if isinstance(expr, ast.Call):          # partial(f, ...)
            d = dotted_name(expr.func)
            if d is not None and d.split(".")[-1] == "partial" \
                    and expr.args:
                return self.resolve_callable(expr.args[0], fn, local_types)
            return None
        if isinstance(expr, ast.Name):
            mod = self.modules[_module_name(fn.rel_path)]
            target = self._resolve_name(expr.id, mod)
            return target if isinstance(target, FuncInfo) else None
        if isinstance(expr, ast.Attribute):
            base_t = self.expr_type(expr.value, fn, local_types)
            ci = self.classes.get(base_t) if base_t else None
            if ci is not None:
                return self._method(ci, expr.attr)
            mod = self.modules[_module_name(fn.rel_path)]
            d = dotted_name(expr)
            if d is not None:
                target = self.resolve_symbol(self._qualify(d, mod))
                if isinstance(target, FuncInfo):
                    return target
        return None

    def _method(self, ci: ClassInfo, name: str,
                depth: int = 0) -> FuncInfo | None:
        if name in ci.methods:
            return ci.methods[name]
        if depth < 6:
            for base in self._package_bases(ci):
                m = self._method(base, name, depth + 1)
                if m is not None:
                    return m
        return None

    def _owns_pool(self, ci: ClassInfo) -> bool:
        return POOL in ci.attr_types.values()

    def _build_edges_and_roots(self) -> None:
        for fn in list(self.funcs.values()):
            edges = self.edges.setdefault(fn.qname, set())
            locals_ = self.local_types(fn)
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Call):
                    self._edge_for_call(node, fn, locals_, edges)
                elif isinstance(node, ast.Attribute) \
                        and isinstance(node.ctx, ast.Load):
                    # Property access on a typed object is a call.
                    base_t = self.expr_type(node.value, fn, locals_)
                    ci = self.classes.get(base_t) if base_t else None
                    if ci is not None:
                        m = self._method(ci, node.attr)
                        if m is not None and _is_property(m.node):
                            edges.add(m.qname)
        # Thread-subclass run() roots.
        for ci in self.classes.values():
            if ci.is_thread:
                run = self._method(ci, "run")
                if run is not None:
                    self.thread_roots[f"{ci.name}.run"] = run.qname

    def _edge_for_call(self, node: ast.Call, fn: FuncInfo,
                       locals_: dict[str, str], edges: set[str]) -> None:
        target = self.resolve_callable(node.func, fn, locals_)
        if target is not None:
            edges.add(target.qname)
        # Constructor edge + thread target roots.
        d = dotted_name(node.func)
        mod = self.modules[_module_name(fn.rel_path)]
        ctor: ClassInfo | None = None
        if d is not None:
            leaf_target = self.resolve_symbol(self._qualify(d, mod)) \
                if "." in d else self._resolve_name(d, mod)
            if isinstance(leaf_target, ClassInfo):
                ctor = leaf_target
                init = self._method(ctor, "__init__")
                if init is not None:
                    edges.add(init.qname)
        is_thread_ctor = (
            (d is not None and d.split(".")[-1] == "Thread")
            or (ctor is not None and ctor.is_thread))
        if is_thread_ctor:
            for kw in node.keywords:
                if kw.arg == "target":
                    t = self.resolve_callable(kw.value, fn, locals_)
                    if t is not None:
                        self.thread_roots[f"thread:{_short(t.qname)}"] \
                            = t.qname
        # Pool thunks: <pool>.submit(fn, ...) or <pool-owner>.submit(...).
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "submit" and node.args:
            recv_t = self.expr_type(node.func.value, fn, locals_)
            recv_ci = self.classes.get(recv_t) if recv_t else None
            if recv_t == POOL or (recv_ci is not None
                                  and self._owns_pool(recv_ci)):
                t = self.resolve_callable(node.args[0], fn, locals_)
                if t is not None:
                    self.thread_roots[f"thunk:{_short(t.qname)}"] = t.qname

    # -- reachability -----------------------------------------------------

    def _closure(self, entries: set[str]) -> set[str]:
        seen = set(entries)
        frontier = list(entries)
        while frontier:
            for nxt in self.edges.get(frontier.pop(), ()):
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(nxt)
        return seen

    def _compute_reachability(self) -> None:
        per_root: dict[str, set[str]] = {
            rid: self._closure({entry})
            for rid, entry in self.thread_roots.items()}
        thread_closure: set[str] = set()
        for reach in per_root.values():
            thread_closure |= reach
        # Anything OUTSIDE the thread closure can be called by the main
        # thread (tests, CLI, the reconcile loop): those are the main
        # entries, and main additionally reaches into the closure
        # through resolved edges (e.g. the informer's pump()).
        main_entries = set(self.funcs) - thread_closure
        main_reach = self._closure(main_entries)
        roots: dict[str, set[str]] = {q: set() for q in self.funcs}
        for rid, reach in per_root.items():
            for q in reach:
                if q in roots:
                    roots[q].add(rid)
        for q in main_reach:
            if q in roots:
                roots[q].add(MAIN_ROOT)
        self.roots_of = {q: frozenset(r) for q, r in roots.items()}


def _is_property(node: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
    return any(isinstance(d, ast.Name) and d.id == "property"
               for d in node.decorator_list)


def _short(qname: str) -> str:
    parts = qname.split(".")
    return ".".join(parts[-2:]) if len(parts) >= 2 else qname


def lock_id(expr: ast.AST, fn: FuncInfo, locals_: dict[str, str],
            graph: PackageGraph) -> str | None:
    """Stable identity for the lock object in ``with <expr>:`` — the ONE
    naming scheme shared by the escape pass (TAR5xx locksets), the
    lock-order pass (TAL7xx graph nodes), and the runtime witness
    cross-check: ``<ClassQname>.<attr>`` for instance locks,
    ``<module>.<name>`` for module-level locks, ``<fn>:<name>`` for
    locals."""
    t = graph.expr_type(expr, fn, locals_)
    if t not in LOCK_TYPES:
        return None
    if isinstance(expr, ast.Attribute):
        base_t = graph.expr_type(expr.value, fn, locals_)
        if base_t is not None:
            return f"{base_t}.{expr.attr}"
        return f"{fn.qname}?.{expr.attr}"
    if isinstance(expr, ast.Name):
        mod = _module_name(fn.rel_path)
        if expr.id in graph.modules[mod].global_types:
            return f"{mod}.{expr.id}"
        return f"{fn.qname}:{expr.id}"         # local lock variable
    return None


def canonical_call_name(expr: ast.AST, fn: FuncInfo,
                        graph: PackageGraph) -> str | None:
    """``dotted_name`` with the leading import alias rewritten to its
    real target: ``import time as _time`` makes ``_time.sleep(...)``
    read as ``time.sleep``, and ``from time import sleep as snooze``
    makes ``snooze(...)`` read as ``time.sleep``.  The syntactic
    catalogs (TAB8xx blocking ops, TAD9xx clock/randomness) match on
    the canonical name — without this an alias silently disables the
    checker for the whole file: it fails OPEN, no finding and no
    waiver."""
    d = dotted_name(expr)
    if d is None:
        return None
    mod = graph.modules.get(_module_name(fn.rel_path))
    if mod is None:
        return d
    head, _, rest = d.partition(".")
    target = mod.imports.get(head)
    if target is None or target == head:
        return d
    return f"{target}.{rest}" if rest else target


#: One PackageGraph per (identical) file list per process: the four
#: whole-program passes run back-to-back over the same SourceFile
#: objects inside one run_analysis call, and indexing the package is
#: the dominant cost — share the graph instead of rebuilding it.  The
#: cache holds strong references to its SourceFiles (via the graph),
#: so id-reuse cannot alias a dead entry; bounded so long-lived
#: processes (pytest) cannot accumulate stale trees.
_GRAPH_CACHE: dict[tuple[int, ...], PackageGraph] = {}
_GRAPH_CACHE_MAX = 8


def shared_graph(files: list[SourceFile]) -> PackageGraph:
    key = tuple(id(s) for s in files)
    graph = _GRAPH_CACHE.get(key)
    if graph is None:
        graph = PackageGraph(files)
        if len(_GRAPH_CACHE) >= _GRAPH_CACHE_MAX:
            _GRAPH_CACHE.pop(next(iter(_GRAPH_CACHE)))
        _GRAPH_CACHE[key] = graph
    return graph
