"""Planner-purity checker (TAP1xx).

The reconcile design is crash-only: the planner must stay a pure
function of (gangs, nodes, pods, in-flight, policy) so desired state can
be recomputed from scratch every pass (engine/planner.py docstring,
SURVEY §6.3).  This checker enforces it mechanically on the decision
modules: no I/O, no clocks, no randomness, no environment reads, no
module-global mutation.

Explicitly ALLOWED: ``logging`` (telemetry never feeds back into the
decision) and ``functools`` memoization (``lru_cache`` over immutable
catalog data is referentially transparent — unlike a hand-rolled dict
cache, it mutates no inspectable module state).

Codes:

- TAP101 — call into a forbidden module (time/random/socket/...);
- TAP102 — import of a forbidden module (module or function scope);
- TAP103 — environment access (os.environ / os.getenv);
- TAP104 — module-global mutation (``global``, assignment or mutating
  method call on a module-level name from inside a function);
- TAP105 — builtin I/O call (open/input/print).
"""

from __future__ import annotations

import ast

from tpu_autoscaler.analysis.core import (
    Checker,
    Finding,
    SourceFile,
    dotted_name,
    root_name,
)

#: Modules whose very import into a pure decision module is a finding.
FORBIDDEN_MODULES = frozenset({
    "time", "random", "secrets", "socket", "subprocess", "requests",
    "urllib", "http", "shutil", "tempfile", "io", "pathlib", "threading",
    "multiprocessing", "asyncio", "signal",
})

#: ``os`` is forbidden too, but env access gets its own code (TAP103).
_ENV_CALLS = frozenset({"os.environ", "os.getenv", "os.putenv",
                        "os.environb"})

#: Wall-clock reads via datetime (datetime arithmetic itself is pure).
_CLOCK_CALLS = frozenset({
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today", "datetime.now",
    "datetime.utcnow", "date.today",
})

_IO_BUILTINS = frozenset({"open", "input", "print", "exec", "eval"})

#: Method calls that mutate their receiver in place.
MUTATING_METHODS = frozenset({
    "append", "extend", "insert", "remove", "pop", "clear", "add",
    "discard", "update", "setdefault", "popitem", "sort", "reverse",
    "__setitem__",
})

#: Default scope: the decision modules named by the invariant.  The
#: policy subsystem's forecast/SLO math (ISSUE 8) is pure computation
#: over injected timestamps by the same contract — the stateful
#: PolicyEngine wrapper (engine.py) stays outside, like the Reconciler.
DEFAULT_SCOPE = (
    "tpu_autoscaler/engine/planner.py",
    "tpu_autoscaler/engine/fitter.py",
    "tpu_autoscaler/engine/columnar.py",
    "tpu_autoscaler/k8s/scheduling.py",
    "tpu_autoscaler/policy/forecast.py",
    "tpu_autoscaler/policy/slo.py",
    # The request router decides placement for every request on the
    # hot path (ISSUE 18): same contract — the wall clock is injected
    # (`now` parameters), no I/O, no randomness, so a routing decision
    # is replayable from the adapter state + the dispatch sequence.
    "tpu_autoscaler/serving/router.py",
    # The pass profiler (ISSUE 20): the clock is an injected callable,
    # no I/O — a pass profile is replayable from its recorded spans
    # (rebuild_from_events is the oracle the property suite holds the
    # incremental ledger to).
    "tpu_autoscaler/obs/profiler.py",
)


class PurityChecker(Checker):
    name = "purity"
    codes = {
        "TAP101": "call into a forbidden (impure) module",
        "TAP102": "import of a forbidden module in a pure module",
        "TAP103": "environment access in a pure module",
        "TAP104": "module-global mutation in a pure module",
        "TAP105": "builtin I/O call in a pure module",
    }

    def __init__(self, scope: tuple[str, ...] = DEFAULT_SCOPE) -> None:
        self._scope = scope

    def applies_to(self, rel_path: str) -> bool:
        return any(rel_path.endswith(s) for s in self._scope)

    # ------------------------------------------------------------------ #

    def check(self, src: SourceFile) -> list[Finding]:
        findings: list[Finding] = []
        module_names = _module_level_names(src.tree)

        def emit(node: ast.AST, code: str, message: str) -> None:
            findings.append(Finding(src.rel_path, node.lineno, code,
                                    message))

        for node in ast.walk(src.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    top = alias.name.split(".")[0]
                    if top in FORBIDDEN_MODULES or top == "os":
                        emit(node, "TAP102",
                             f"pure module imports {alias.name!r}")
            elif isinstance(node, ast.ImportFrom):
                top = (node.module or "").split(".")[0]
                if top in FORBIDDEN_MODULES or top == "os":
                    emit(node, "TAP102",
                         f"pure module imports from {node.module!r}")
            elif isinstance(node, ast.Call):
                findings.extend(self._check_call(src, node))
            elif isinstance(node, (ast.Attribute, ast.Subscript)):
                d = dotted_name(node.value if isinstance(node, ast.Subscript)
                                else node)
                if d in ("os.environ", "os.environb"):
                    emit(node, "TAP103",
                         "pure module reads the process environment")
            elif isinstance(node, ast.Global):
                emit(node, "TAP104",
                     f"'global {', '.join(node.names)}' in a pure module")

        findings.extend(self._check_global_mutation(src, module_names))
        # One env access yields matches on nested nodes (the Call AND
        # its inner ``os.environ`` Attribute, on the same line); keep
        # the first — walk order puts the most specific message first.
        env_lines: set[int] = set()
        deduped: list[Finding] = []
        for f in findings:
            if f.code == "TAP103":
                if f.line in env_lines:
                    continue
                env_lines.add(f.line)
            deduped.append(f)
        return deduped

    def _check_call(self, src: SourceFile,
                    node: ast.Call) -> list[Finding]:
        out: list[Finding] = []
        func = node.func
        d = dotted_name(func)
        if d is not None:
            top = d.split(".")[0]
            if d in _ENV_CALLS or d.startswith("os.environ"):
                out.append(Finding(src.rel_path, node.lineno, "TAP103",
                                   f"environment access via {d}()"))
            elif top in FORBIDDEN_MODULES or top == "os":
                out.append(Finding(
                    src.rel_path, node.lineno, "TAP101",
                    f"pure module calls {d}()"))
            elif d in _CLOCK_CALLS:
                out.append(Finding(
                    src.rel_path, node.lineno, "TAP101",
                    f"pure module reads the wall clock via {d}()"))
        if isinstance(func, ast.Name) and func.id in _IO_BUILTINS:
            out.append(Finding(
                src.rel_path, node.lineno, "TAP105",
                f"pure module calls builtin {func.id}()"))
        return out

    def _check_global_mutation(self, src: SourceFile,
                               module_names: set[str]) -> list[Finding]:
        """Writes to module-level names from inside function bodies."""
        out: list[Finding] = []

        def visit_fn(fn: ast.AST) -> None:
            for node in ast.walk(fn):
                targets: list[ast.AST] = []
                if isinstance(node, ast.Assign):
                    targets = list(node.targets)
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                elif isinstance(node, ast.Delete):
                    targets = list(node.targets)
                elif isinstance(node, ast.Call):
                    f = node.func
                    if (isinstance(f, ast.Attribute)
                            and f.attr in MUTATING_METHODS):
                        root = root_name(f.value)
                        if root in module_names:
                            out.append(Finding(
                                src.rel_path, node.lineno, "TAP104",
                                f"mutates module-level {root!r} via "
                                f".{f.attr}()"))
                    continue
                for t in targets:
                    # Plain Name assignment inside a function is a LOCAL
                    # binding (unless global-declared, caught above);
                    # only subscript/attribute writes reach module state.
                    if isinstance(t, (ast.Subscript, ast.Attribute)):
                        root = root_name(t)
                        if root in module_names:
                            out.append(Finding(
                                src.rel_path, node.lineno, "TAP104",
                                f"writes module-level {root!r} from a "
                                f"function body"))

        for node in src.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                visit_fn(node)
        return out


def _module_level_names(tree: ast.Module) -> set[str]:
    names: set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            if isinstance(node.target, ast.Name):
                names.add(node.target.id)
    return names
