"""Interprocedural escape/lockset race detector (TAR5xx, layer 1).

TAT2xx checks one class at a time: a lock-holding class must guard its
own writes, a Thread subclass must keep its state inside ``run()``'s
private call graph.  What it cannot see is an object CONSTRUCTED on one
thread and MUTATED from another — the informer's ObjectCache, the
executor's bookkeeping, the TokenProvider cache.  This pass can:

1. thread roots come from the whole-package call graph
   (``callgraph.PackageGraph``): ``run()`` of every Thread subclass,
   every resolvable ``Thread(target=...)``, every thunk handed to a
   worker pool's ``submit`` (the ActuationExecutor dispatch path);
   everything not exclusively reachable from thread roots is also
   reachable from the ``main`` root (tests, CLI, the reconcile loop);
2. every attribute access whose receiver type resolves to a package
   class is attributed to the accessing function's root set — an object
   whose attributes are reached from two or more roots has ESCAPED to
   multiple threads;
3. each access carries its lexical lockset (the ``with self._lock:`` /
   ``with _module_lock:`` blocks enclosing it); conflicting accesses
   (at least one write) from different roots with DISJOINT locksets are
   races:

   - TAR501 — cross-thread write/write with no common lock;
   - TAR502 — read racing a cross-thread write with no common lock;
   - TAR503 — object shared across roots by a class that holds no lock
     at all (nothing to guard with: share it through a Lock or hand it
     off through an Event).

Construction is exempt: accesses inside ``__init__`` happen before the
object can escape (the ``Thread.start()`` edge publishes them), and
calls ON synchronization primitives (``self._stopped.set()``) are the
sanctioned channel, never data accesses.

Precision notes: locksets are lexical (a method that takes its own lock
intersects with every caller — the repo's idiom); conflation is
class-level (two instances of one class are not distinguished); what
the graph cannot resolve produces no evidence and therefore no finding
— the TAT2xx heuristic and the deterministic-schedule harness
(testing/sched.py) cover that remainder.
"""

from __future__ import annotations

import ast
import dataclasses

from tpu_autoscaler.analysis.callgraph import (
    ClassInfo,
    FuncInfo,
    PackageGraph,
    lock_id,
    shared_graph,
)
from tpu_autoscaler.analysis.core import (
    Finding,
    ProgramChecker,
    SourceFile,
)
from tpu_autoscaler.analysis.purity import MUTATING_METHODS
from tpu_autoscaler.analysis.threads import _walk_method

WRITE = "write"
READ = "read"


@dataclasses.dataclass
class _Access:
    cls: ClassInfo
    attr: str
    kind: str
    fn: FuncInfo
    line: int
    locks: frozenset[str]

    @property
    def where(self) -> str:
        parts = self.fn.qname.split(".")
        return ".".join(parts[-2:]) if self.fn.cls is not None \
            else parts[-1]


# Body walker: skip nested classes and nested functions that rebind
# ``self`` (plain closures keep the outer self and are walked).  ONE
# copy of the scoping rule for the whole package — the TAT2xx checker
# owns it.
_walk_scoped = _walk_method


class EscapeRaceChecker(ProgramChecker):
    name = "escape-race"
    codes = {
        "TAR501": "cross-thread write/write with no common lock",
        "TAR502": "read racing a cross-thread write with no common lock",
        "TAR503": "object shared across thread roots without any lock",
    }

    def applies_to(self, rel_path: str) -> bool:
        # The deterministic scheduler (testing/sched.py) is the one
        # module whose mutual exclusion is BY CONSTRUCTION (exactly one
        # managed thread runs at a time, handed off through semaphores)
        # rather than by locks — a lockset model cannot express that,
        # and the harness's own unit tests prove it instead.
        return "tpu_autoscaler/testing/" not in rel_path

    # -- access extraction ------------------------------------------------

    def _guard_ranges(self, fn: FuncInfo, locals_: dict[str, str],
                      graph: PackageGraph) -> list[tuple[int, int, str]]:
        out: list[tuple[int, int, str]] = []
        for node in _walk_scoped(fn.node):
            if isinstance(node, ast.With):
                for item in node.items:
                    lid = lock_id(item.context_expr, fn, locals_, graph)
                    if lid is not None:
                        out.append((node.lineno,
                                    node.end_lineno or node.lineno, lid))
        return out

    def _accesses_in(self, fn: FuncInfo,
                     graph: PackageGraph) -> list[_Access]:
        if fn.node.name == "__init__":
            return []                          # construction is exempt
        locals_ = graph.local_types(fn)
        guards = self._guard_ranges(fn, locals_, graph)

        def locks_at(line: int) -> frozenset[str]:
            return frozenset(lid for lo, hi, lid in guards
                             if lo <= line <= hi)

        out: list[_Access] = []

        def target_class(expr: ast.AST) -> ClassInfo | None:
            t = graph.expr_type(expr, fn, locals_)
            return graph.classes.get(t) if t else None

        def note(expr: ast.AST, kind: str) -> None:
            if not isinstance(expr, ast.Attribute):
                return
            ci = target_class(expr.value)
            if ci is None:
                return
            attr = expr.attr
            if attr in ci.sync_attrs:
                return                          # the sanctioned channel
            if graph._method(ci, attr) is not None:
                return                          # method/property: an edge
            out.append(_Access(ci, attr, kind, fn, expr.lineno,
                               locks_at(expr.lineno)))

        for node in _walk_scoped(fn.node):
            if isinstance(node, ast.Attribute):
                if isinstance(node.ctx, (ast.Store, ast.Del)):
                    note(node, WRITE)
                else:
                    note(node, READ)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in MUTATING_METHODS:
                # self.x.append(...) mutates x.
                note(node.func.value, WRITE)
        return out

    # -- conflict detection -----------------------------------------------

    def check_program(self, files: list[SourceFile]) -> list[Finding]:
        graph = shared_graph(files)
        by_attr: dict[tuple[str, str], list[_Access]] = {}
        for fn in graph.funcs.values():
            for acc in self._accesses_in(fn, graph):
                by_attr.setdefault((acc.cls.qname, acc.attr), []) \
                    .append(acc)

        findings: list[Finding] = []
        seen: set[tuple[str, int, str, str]] = set()

        def emit(f: Finding) -> None:
            key = (f.file, f.line, f.code, f.message)
            if key not in seen:
                seen.add(key)
                findings.append(f)

        for (cls_q, attr), accs in sorted(by_attr.items()):
            cls = graph.classes[cls_q]
            writes = [a for a in accs if a.kind == WRITE]
            if not writes:
                continue
            for w in writes:
                wr = graph.roots_of.get(w.fn.qname, frozenset())
                for other in accs:
                    orr = graph.roots_of.get(other.fn.qname, frozenset())
                    if not wr or not orr or len(wr | orr) < 2:
                        continue                # never on two roots
                    if w is other and len(wr) < 2:
                        continue
                    if w.locks & other.locks:
                        continue                # common lock: synchronized
                    roots = ", ".join(sorted(wr | orr))
                    if not cls.lock_attrs:
                        emit(Finding(
                            w.fn.rel_path, w.line, "TAR503",
                            f"'{cls.name}.{attr}' escapes to roots "
                            f"[{roots}] (written in {w.where}) but "
                            f"{cls.name} holds no lock — guard it or "
                            f"hand it off through an Event"))
                    elif other.kind == WRITE:
                        emit(Finding(
                            w.fn.rel_path, w.line, "TAR501",
                            f"write to '{cls.name}.{attr}' in {w.where} "
                            f"races write in {other.where} across roots "
                            f"[{roots}] with no common lock"))
                    else:
                        emit(Finding(
                            other.fn.rel_path, other.line, "TAR502",
                            f"read of '{cls.name}.{attr}' in "
                            f"{other.where} races write in {w.where} "
                            f"across roots [{roots}] with no common "
                            f"lock"))
        findings.sort(key=lambda f: (f.file, f.line, f.code))
        return findings
