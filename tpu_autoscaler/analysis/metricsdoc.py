"""Metrics/runbook drift checkers (TAO6xx).

docs/OPERATIONS.md's "Metrics to alert on" table is the operator
contract for every series the controller exports — but nothing kept it
honest: PR 2/3 each added metrics the runbook never learned about, and
nothing would notice a doc row whose metric was renamed away.  This
checker closes the loop in both directions:

- **TAO601** — a metric name ``inc``/``observe``/``set_gauge``/
  ``declare_histogram``'d (or fed via a tracer ``metric=`` keyword) in
  the package does not appear in the runbook table;
- **TAO602** — a runbook table entry matches no metric in the code
  (dead documentation — worse than none: operators alert on it).

ISSUE 10 extends the same both-directions contract to the alert
catalog (:class:`AlertDocChecker`): every ``AlertRule`` declared in
``obs/alerts.py`` must reference an exported metric family and appear
in the runbook's "Alert catalog" table, and every documented alert row
must match a declared rule:

- **TAO603** — a rule's ``metric=`` matches no exported metric family
  (the alert can never fire: it watches a series nobody emits);
- **TAO604** — a rule declared in ``obs/alerts.py`` has no row in the
  runbook's alert catalog (operators get paged by an alert with no
  runbook);
- **TAO605** — a documented alert row matches no declared rule (dead
  runbook: operators trust an alert that no longer exists).

Dynamic names are matched by family: code like
``f"namespace_chips_used_{ns}"`` is documented as
``namespace_chips_used_<ns>`` — the literal prefix before the first
interpolation must equal the doc entry's prefix before ``<``.  An
f-string with NO literal prefix is unmatchable and reported as TAO601
(name the family or hoist a prefix).

It is a :class:`ProgramChecker`: the code side needs every file, the
doc side one read of the runbook.  Wired into ``default_checkers`` so
``scripts/lint.sh``, ``scripts/ci_gate.sh`` and ``TestRepoIsClean``
all gate on it.
"""

from __future__ import annotations

import ast
import os
import re

from tpu_autoscaler.analysis.core import (
    Finding,
    ProgramChecker,
    SourceFile,
)

#: Registry verbs (and the private wrappers the executor / informer /
#: GcpRest layer put in front of them).
_METRIC_METHODS = frozenset({
    "inc", "_inc", "observe", "_observe", "set_gauge",
    "declare_histogram",
})

#: The runbook section that IS the metrics contract.
_DOC_SECTION = "## Metrics to alert on"

#: The runbook section that IS the alert contract (ISSUE 10), and the
#: one module whose ``AlertRule(...)`` calls define the catalog (the
#: chaos engine builds scenario-scale rules too — those are test
#: instruments, not the operator catalog, and stay out of scope).
_ALERT_SECTION = "## Alert catalog"
_ALERTS_MODULE = "tpu_autoscaler/obs/alerts.py"

_DEFAULT_DOC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "docs", "OPERATIONS.md")


def _joinedstr_prefix(node: ast.JoinedStr) -> str:
    """Literal prefix of an f-string before its first interpolation."""
    prefix = ""
    for part in node.values:
        if isinstance(part, ast.Constant) and isinstance(part.value, str):
            prefix += part.value
        else:
            break
    return prefix


class MetricsDocChecker(ProgramChecker):
    """Every exported metric documented; every documented metric real."""

    name = "metrics-doc"
    codes = {
        "TAO601": "metric exported in code but missing from "
                  "docs/OPERATIONS.md 'Metrics to alert on'",
        "TAO602": "documented metric matches no metric in the code",
    }

    def __init__(self, doc_path: str | None = None,
                 doc_text: str | None = None) -> None:
        self._doc_path = doc_path or _DEFAULT_DOC
        self._doc_text = doc_text  # tests inject the table directly

    def applies_to(self, rel_path: str) -> bool:
        return rel_path.startswith("tpu_autoscaler/")

    # -- doc side ---------------------------------------------------------

    def _doc_entries(self) -> tuple[dict[str, int], dict[str, int], str]:
        """(exact name -> line, family prefix -> line, doc rel path).
        Names come from backticked tokens in the first column of the
        metrics table; ``foo_<x>`` rows become the family prefix
        ``foo_``."""
        if self._doc_text is not None:
            text, rel = self._doc_text, "docs/OPERATIONS.md"
        else:
            try:
                with open(self._doc_path, encoding="utf-8") as f:
                    text = f.read()
            except OSError:
                return {}, {}, "docs/OPERATIONS.md"
            rel = "docs/OPERATIONS.md"
        exact: dict[str, int] = {}
        families: dict[str, int] = {}
        in_section = False
        for lineno, line in enumerate(text.splitlines(), 1):
            if line.startswith("## "):
                in_section = line.strip() == _DOC_SECTION
                continue
            if not in_section or not line.startswith("|"):
                continue
            first_cell = line.split("|")[1] if line.count("|") >= 2 else ""
            for token in re.findall(r"`([^`]+)`", first_cell):
                token = token.strip()
                if not token or token in ("Metric", "---"):
                    continue
                if "<" in token:
                    families.setdefault(token.split("<", 1)[0], lineno)
                else:
                    exact.setdefault(token, lineno)
        return exact, families, rel

    # -- code side --------------------------------------------------------

    @staticmethod
    def _code_metrics(files: list[SourceFile]) -> tuple[
            dict[str, tuple[str, int]], dict[str, tuple[str, int]],
            list[tuple[str, int]]]:
        """(exact name -> first site, dynamic prefix -> first site,
        unmatchable dynamic sites)."""
        exact: dict[str, tuple[str, int]] = {}
        prefixes: dict[str, tuple[str, int]] = {}
        unmatchable: list[tuple[str, int]] = []
        for src in files:
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.Call):
                    continue
                args: list[ast.expr] = []
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr in _METRIC_METHODS
                        and node.args):
                    args.append(node.args[0])
                # Tracer span→histogram feeds: metric="name" keywords
                # (obs/trace.py record/end) count as exports too —
                # EXCEPT on AlertRule(...) constructions, whose
                # metric= is a REFERENCE to a family exported
                # elsewhere: counting it would let a rule watching a
                # renamed-away metric mask its own TAO603 (and fake
                # a TAO601/602 export).
                is_alert_rule = (
                    isinstance(node.func, ast.Name)
                    and node.func.id == "AlertRule") or (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "AlertRule")
                if not is_alert_rule:
                    for kw in node.keywords:
                        if kw.arg == "metric":
                            args.append(kw.value)
                for arg in args:
                    site = (src.rel_path, arg.lineno)
                    if isinstance(arg, ast.Constant) \
                            and isinstance(arg.value, str):
                        exact.setdefault(arg.value, site)
                    elif isinstance(arg, ast.JoinedStr):
                        prefix = _joinedstr_prefix(arg)
                        if prefix:
                            prefixes.setdefault(prefix, site)
                        else:
                            unmatchable.append(site)
        return exact, prefixes, unmatchable

    # -- the check --------------------------------------------------------

    def check_program(self, files: list[SourceFile]) -> list[Finding]:
        if not files:
            return []  # nothing in scope (foreign tree): no evidence
        doc_exact, doc_families, doc_rel = self._doc_entries()
        code_exact, code_prefixes, unmatchable = self._code_metrics(files)
        findings: list[Finding] = []
        # Dead-doc-entry findings (TAO602) need the WHOLE package in
        # view — on a subset run (`... analysis tpu_autoscaler/k8s/`)
        # an absent metric proves nothing.  The registry module is the
        # sentinel: if it was scanned, this is a full-package run.
        full_view = any(
            s.rel_path == "tpu_autoscaler/metrics/metrics.py"
            for s in files)

        def documented(name: str) -> bool:
            return name in doc_exact or any(
                name.startswith(p) for p in doc_families)

        for name, (path, line) in sorted(code_exact.items()):
            if not documented(name):
                findings.append(Finding(
                    path, line, "TAO601",
                    f"metric '{name}' is exported here but not in "
                    f"{doc_rel} '{_DOC_SECTION[3:]}'"))
        for prefix, (path, line) in sorted(code_prefixes.items()):
            if prefix not in doc_families:
                findings.append(Finding(
                    path, line, "TAO601",
                    f"dynamic metric family '{prefix}<...>' is exported "
                    f"here but has no '{prefix}<...>' row in {doc_rel}"))
        for path, line in unmatchable:
            findings.append(Finding(
                path, line, "TAO601",
                "dynamic metric name has no literal prefix — it cannot "
                "be matched against the runbook; hoist a stable prefix"))
        if not full_view:
            return findings
        for name, lineno in sorted(doc_exact.items()):
            if name in code_exact:
                continue
            if any(name.startswith(p) for p in code_prefixes):
                continue  # a concrete instance of a dynamic family
            findings.append(Finding(
                doc_rel, lineno, "TAO602",
                f"documented metric '{name}' matches nothing in the "
                f"code (renamed or removed?)"))
        for prefix, lineno in sorted(doc_families.items()):
            if prefix in code_prefixes:
                continue
            if any(n.startswith(prefix) for n in code_exact):
                continue  # family documented, members emitted literally
            findings.append(Finding(
                doc_rel, lineno, "TAO602",
                f"documented metric family '{prefix}<...>' matches "
                f"nothing in the code"))
        return findings


class AlertDocChecker(ProgramChecker):
    """Every declared alert rule watches a real metric and has a
    runbook row; every runbook row names a real rule (ISSUE 10 — the
    TAO601/602 contract extended to the alert catalog)."""

    name = "alert-doc"
    codes = {
        "TAO603": "alert rule references a metric family the code "
                  "never exports",
        "TAO604": "alert rule missing from docs/OPERATIONS.md "
                  "'Alert catalog'",
        "TAO605": "documented alert matches no rule in obs/alerts.py",
    }

    def __init__(self, doc_path: str | None = None,
                 doc_text: str | None = None) -> None:
        self._doc_path = doc_path or _DEFAULT_DOC
        self._doc_text = doc_text  # tests inject the table directly

    def applies_to(self, rel_path: str) -> bool:
        return rel_path.startswith("tpu_autoscaler/")

    def _doc_alerts(self) -> tuple[dict[str, int], str]:
        """Alert names from backticked tokens in the first column of
        the 'Alert catalog' table -> line."""
        if self._doc_text is not None:
            text = self._doc_text
        else:
            try:
                with open(self._doc_path, encoding="utf-8") as f:
                    text = f.read()
            except OSError:
                return {}, "docs/OPERATIONS.md"
        out: dict[str, int] = {}
        in_section = False
        for lineno, line in enumerate(text.splitlines(), 1):
            if line.startswith("## "):
                in_section = line.strip() == _ALERT_SECTION
                continue
            if not in_section or not line.startswith("|"):
                continue
            first_cell = line.split("|")[1] if line.count("|") >= 2 else ""
            for token in re.findall(r"`([^`]+)`", first_cell):
                token = token.strip()
                if token and token not in ("Alert", "---"):
                    out.setdefault(token, lineno)
        return out, "docs/OPERATIONS.md"

    @staticmethod
    def _declared_rules(files: list[SourceFile]
                        ) -> dict[str, tuple[str, int, int]]:
        """``AlertRule(name=..., metric=...)`` literals in the catalog
        module: name -> (metric, line of the call, line of metric)."""
        out: dict[str, tuple[str, int, int]] = {}
        for src in files:
            if src.rel_path != _ALERTS_MODULE:
                continue
            for node in ast.walk(src.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id == "AlertRule"):
                    continue
                name = metric = None
                metric_line = node.lineno
                for kw in node.keywords:
                    if kw.arg == "name" \
                            and isinstance(kw.value, ast.Constant) \
                            and isinstance(kw.value.value, str):
                        name = kw.value.value
                    elif kw.arg == "metric" \
                            and isinstance(kw.value, ast.Constant) \
                            and isinstance(kw.value.value, str):
                        metric = kw.value.value
                        metric_line = kw.value.lineno
                if name is not None and metric is not None:
                    out.setdefault(name, (metric, node.lineno,
                                          metric_line))
        return out

    def check_program(self, files: list[SourceFile]) -> list[Finding]:
        if not files:
            return []
        rules = self._declared_rules(files)
        doc_alerts, doc_rel = self._doc_alerts()
        # _code_metrics already excludes every AlertRule(metric=...)
        # kwarg from the export set (a rule's reference — in this
        # module or anywhere else, e.g. chaos-scale rules — must
        # never satisfy its own TAO603).
        code_exact, code_prefixes, _ = \
            MetricsDocChecker._code_metrics(files)
        findings: list[Finding] = []
        # Metric-existence needs the whole package in view (the rule's
        # family may be exported anywhere); same sentinel as TAO602.
        full_view = any(
            s.rel_path == "tpu_autoscaler/metrics/metrics.py"
            for s in files)
        for name, (metric, line, metric_line) in sorted(rules.items()):
            if full_view and metric not in code_exact \
                    and not any(metric.startswith(p)
                                for p in code_prefixes):
                findings.append(Finding(
                    _ALERTS_MODULE, metric_line, "TAO603",
                    f"alert rule '{name}' watches metric '{metric}', "
                    f"which the code never exports"))
            if name not in doc_alerts:
                findings.append(Finding(
                    _ALERTS_MODULE, line, "TAO604",
                    f"alert rule '{name}' has no row in {doc_rel} "
                    f"'{_ALERT_SECTION[3:]}'"))
        # Dead-doc-row findings need the catalog module in view.
        if not any(s.rel_path == _ALERTS_MODULE for s in files):
            return findings
        for name, lineno in sorted(doc_alerts.items()):
            if name not in rules:
                findings.append(Finding(
                    doc_rel, lineno, "TAO605",
                    f"documented alert '{name}' matches no AlertRule "
                    f"in {_ALERTS_MODULE}"))
        return findings
