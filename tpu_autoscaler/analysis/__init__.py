"""Invariant linter: the repo's architectural contracts as code.

``python -m tpu_autoscaler.analysis tpu_autoscaler/`` runs four AST
checkers (planner purity, thread discipline, crash-only exception
hygiene, jax trace purity) and exits non-zero on any finding not
waived inline or grandfathered in ``analysis/baseline.toml``.
See docs/ANALYSIS.md.
"""

from tpu_autoscaler.analysis.core import (
    AnalysisResult,
    Checker,
    Finding,
    ProgramChecker,
    SourceFile,
    parse_baseline,
    render_baseline,
    run_analysis,
)
from tpu_autoscaler.analysis.blocking import BlockingUnderLockChecker
from tpu_autoscaler.analysis.determinism import DeterminismChecker
from tpu_autoscaler.analysis.escape import EscapeRaceChecker
from tpu_autoscaler.analysis.exceptions import ExceptionHygieneChecker
from tpu_autoscaler.analysis.jaxpurity import JaxPurityChecker
from tpu_autoscaler.analysis.lockorder import LockOrderChecker
from tpu_autoscaler.analysis.metricsdoc import (
    AlertDocChecker,
    MetricsDocChecker,
)
from tpu_autoscaler.analysis.purity import PurityChecker
from tpu_autoscaler.analysis.threads import ThreadDisciplineChecker
from tpu_autoscaler.analysis.units import UnitsChecker


def default_checkers() -> list[Checker]:
    # TAT2xx stays in the lineup as the fallback for sharing the
    # interprocedural TAR5xx pass cannot resolve (docs/ANALYSIS.md).
    # The five whole-program passes (TAR/TAL/TAB/TAD/TAU) share one
    # PackageGraph per run via callgraph.shared_graph.
    return [PurityChecker(), ThreadDisciplineChecker(),
            ExceptionHygieneChecker(), JaxPurityChecker(),
            EscapeRaceChecker(), LockOrderChecker(),
            BlockingUnderLockChecker(), DeterminismChecker(),
            MetricsDocChecker(), AlertDocChecker(), UnitsChecker()]


__all__ = [
    "AlertDocChecker",
    "AnalysisResult",
    "BlockingUnderLockChecker",
    "Checker",
    "DeterminismChecker",
    "EscapeRaceChecker",
    "ExceptionHygieneChecker",
    "Finding",
    "JaxPurityChecker",
    "LockOrderChecker",
    "MetricsDocChecker",
    "ProgramChecker",
    "PurityChecker",
    "SourceFile",
    "ThreadDisciplineChecker",
    "UnitsChecker",
    "default_checkers",
    "parse_baseline",
    "render_baseline",
    "run_analysis",
]
