"""Crash-only exception-hygiene checker (TAE3xx).

The control plane is crash-only (SURVEY §6.3): a broad ``except
Exception`` is legitimate ONLY as a deliberate degradation point — the
reconcile loop's catch-all, an advisory API write, an actuator poll that
retries next pass.  Every such point must be observable or explicitly
justified, or it silently swallows the exact failures (actuator errors,
apiserver flakes) an operator needs to see.

A broad handler in ``controller/``, ``actuators/``, or ``k8s/`` passes
the check when it does at least one of:

- re-raises (a ``raise`` anywhere in the handler body);
- increments a metric (a ``*.inc(...)`` call — the ``watch_failures``
  pattern from controller/watch.py);
- carries an explicit waiver comment ``# crash-only: <reason>`` on the
  ``except`` line or between it and the handler's first statement.

Codes:

- TAE301 — broad handler with none of the three;
- TAE302 — bare ``except:`` (catches SystemExit/KeyboardInterrupt; name
  ``Exception`` instead — never waivable).
"""

from __future__ import annotations

import ast

from tpu_autoscaler.analysis.core import Checker, Finding, SourceFile

WAIVER = "crash-only:"

_BROAD = frozenset({"Exception", "BaseException"})

DEFAULT_SCOPE = (
    "tpu_autoscaler/controller/",
    "tpu_autoscaler/actuators/",
    "tpu_autoscaler/k8s/",
)


def _is_broad(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    t = handler.type
    if isinstance(t, ast.Name):
        return t.id in _BROAD
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in _BROAD
                   for e in t.elts)
    return False


def _reraises(handler: ast.ExceptHandler) -> bool:
    return any(isinstance(n, ast.Raise) for n in ast.walk(handler))


def _increments_metric(handler: ast.ExceptHandler) -> bool:
    # ``metrics.inc(...)``, ``self._rest.inc(...)`` — any .inc() call.
    for n in ast.walk(handler):
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                and n.func.attr == "inc"):
            return True
    return False


class ExceptionHygieneChecker(Checker):
    name = "exception-hygiene"
    codes = {
        "TAE301": "broad except without re-raise, metric, or waiver",
        "TAE302": "bare except (catches SystemExit/KeyboardInterrupt)",
    }

    def __init__(self, scope: tuple[str, ...] = DEFAULT_SCOPE) -> None:
        self._scope = scope

    def applies_to(self, rel_path: str) -> bool:
        return any(s in rel_path for s in self._scope)

    def check(self, src: SourceFile) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                findings.append(Finding(
                    src.rel_path, node.lineno, "TAE302",
                    "bare 'except:' also catches SystemExit/"
                    "KeyboardInterrupt; catch Exception explicitly"))
                continue
            if not _is_broad(node):
                continue
            if _reraises(node) or _increments_metric(node):
                continue
            first_stmt = node.body[0].lineno if node.body else node.lineno
            if src.comment_in_range(node.lineno, first_stmt, WAIVER):
                continue
            findings.append(Finding(
                src.rel_path, node.lineno, "TAE301",
                "broad 'except Exception' swallows errors: re-raise, "
                "increment a metric, or add '# crash-only: <reason>'"))
        return findings

    def waiver_audit(self, src: SourceFile) -> tuple[set[int], set[int]]:
        """(every 'crash-only:' comment line, the subset whose waiver
        actually suppressed a finding).  The difference is dead waivers:
        comments on handlers that re-raise/count anyway, or on no
        handler at all — reported by the runner as TAW002 so waiver debt
        shrinks as handlers are fixed."""
        all_lines = {n for n, c in src.comments.items() if WAIVER in c}
        used: set[int] = set()
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None or not _is_broad(node):
                continue                       # TAE302 is never waivable
            if _reraises(node) or _increments_metric(node):
                continue                       # passes without the waiver
            first_stmt = node.body[0].lineno if node.body else node.lineno
            for n in range(node.lineno, first_stmt + 1):
                if WAIVER in src.comments.get(n, ""):
                    used.add(n)
        return all_lines, used
