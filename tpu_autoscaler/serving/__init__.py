"""Serving-aware autoscaling (ISSUE 9): the live-signal hot path from
the serving engines to the planner.

Three layers, engineered to fleet scale (docs/SERVING.md "Autoscaler
integration"):

- ``stats``   — per-engine tick statistics as fixed numpy rings with an
  O(1) snapshot API (zero per-request Python object churn; export costs
  nothing on the decode path).  The batcher family
  (``workloads/serving.py`` / ``paged.py`` / ``spec_serving.py``)
  owns one recorder each and exposes ``stats()``.
- ``adapter`` — folds snapshots from thousands of replicas into
  per-pool demand signals with CapacityView-style incremental sums
  (O(churn) per reconcile pass, vectorized over the dirty set; full
  rebuild on demand).  Counter resets and stale/out-of-order snapshots
  are absorbed here — rates are never negative.
- ``scaler``  — turns SLO pressure into advisory replica demand
  through the planner's existing ``advisory_gangs`` hook (planner
  stays pure), with the PR 8 forecasters fed by the live queue-depth /
  throughput series as arrival sources.  Scale-in advice rides the
  ``serve.py`` drain contract: a replica finishes its queue before its
  slice is reclaimed.

``replay`` is the evaluation loop: a diurnal+spike millions-of-users
traffic replay through the real Controller, signal-driven vs
pod-pending reactive — the ``bench.py serving`` gate.
"""

from tpu_autoscaler.serving.stats import (  # noqa: F401
    ServingSnapshot,
    ServingStatsRecorder,
)
