"""Replica-target controller: SLO pressure -> advisory serving demand.

Closes the ISSUE 9 loop on the decision side: each reconcile pass the
Controller hands this scaler the pass clock and the actuator statuses;
the scaler folds the metrics adapter, turns each pool's live signal
into a desired replica count, and expresses any deficit as synthetic
one-pod gangs keyed ``("serving", ns, name)`` through the planner's
existing ``advisory_gangs`` hook — the exact mechanism prewarms (ISSUE
8) and slice repairs (ISSUE 7) already use, so the planner stays a pure
function and a serving misprediction can never displace organic demand
(advisory gangs are admitted last, under the same clamp algebra).

Desired replicas per pool:

- **pressure** — enough replicas to hold the live backlog (queued +
  in-flight requests) at the target utilization;
- **SLO bump** — attainment below target adds headroom even when
  utilization looks fine (tail latency leads utilization);
- **forecast** — the live queue-depth/throughput series feed a PR 8
  Holt-Winters forecaster as an arrival source (ROADMAP's "the
  arrival-series plumbing accepts any demand source"); a confident
  prediction inside the provisioning horizon raises desired BEFORE the
  ramp arrives.

Scale-out bookkeeping mirrors the prewarm lifecycle: one record per
requested replica, re-emitted as advisory demand every pass until its
provision lands and the replica has had ``replica_grace_seconds`` to
join (or the record expires).  Scale-in is ADVICE ONLY
(``ServingAdvice.scale_in``): the serving platform drains the surplus
replicas through the ``serve.py`` drain contract — stop admitting,
finish the queue, exit with a ``final_stats`` line that parses as a
typed :class:`~tpu_autoscaler.serving.drain.DrainReceipt`
(``confirm_scale_in`` validates it and retires the row; the router's
``absorb_drain`` migrates any unserved remainder) — and the idle slice
is then reclaimed by the normal maintenance path, so no queued request
is ever lost to a reclaim.

Reconcile-thread-only state, crash-only wiring (reconciler.py
``_serving_pass``): a scaler failure degrades to reactive scaling.
"""

from __future__ import annotations

import dataclasses
import logging
import math
from typing import Any, Mapping, Sequence

from tpu_autoscaler.k8s.gangs import Gang
from tpu_autoscaler.k8s.objects import Pod
from tpu_autoscaler.policy.forecast import HoltWintersForecaster
from tpu_autoscaler.serving.adapter import (
    PoolSignal,
    ServingMetricsAdapter,
)
from tpu_autoscaler.units import Fraction, Seconds

log = logging.getLogger(__name__)

#: Namespace serving advisory gangs carry (like the prewarm namespace:
#: outside tenant quota maps, riding the global chip clamp only).
SERVING_NAMESPACE = "tpu-serving"


@dataclasses.dataclass(frozen=True)
class ServingPolicy:
    """Scaler tuning (docs/SERVING.md "Autoscaler integration")."""

    target_utilization: Fraction = 0.75  # active / slots to aim for
    # Scale-in deadband: surplus exists only above the fleet size that
    # would still sit BELOW this utilization (a wide gap between the
    # scale-out and scale-in targets is what stops thrash — a drained
    # replica's queue re-routes onto the rest, which must not
    # immediately re-trigger scale-out).
    scalein_utilization: Fraction = 0.45
    #: Per-decision scale-in cap as a fleet fraction denominator
    #: (drain at most replicas // this per decision).
    scalein_step_div: int = 8
    slo_attainment_target: Fraction = 0.98  # below this, add headroom
    slo_bump_replicas: int = 1           # replicas added per SLO miss
    min_replicas: int = 0
    max_replicas: int = 256
    # Scale-out record lifecycle.
    scaleout_hold_seconds: Seconds = 300.0  # unprovisioned record TTL
    replica_grace_seconds: Seconds = 60.0   # ACTIVE -> replica joined
    # Scale-in hysteresis: surplus must persist this long.
    scalein_hold_seconds: Seconds = 180.0
    # Live-series forecasting (PR 8 Holt-Winters over demand samples).
    forecast: bool = True
    min_confidence: Fraction = 0.6
    provision_estimate_seconds: Seconds = 150.0
    sample_seconds: Seconds = 30.0       # demand-series sample period
    hw_bin_seconds: Seconds = 60.0
    hw_season_bins: int = 24


@dataclasses.dataclass
class ServingAdvice:
    """One pass's serving-scaler output."""

    advisory: list[tuple[Gang, str]] = dataclasses.field(
        default_factory=list)
    #: pool -> surplus replica count the platform should drain
    #: (serve.py drain contract; never a forced reclaim).
    scale_in: dict[str, int] = dataclasses.field(default_factory=dict)
    #: pool -> desired replicas (gauges/tests/debug).
    desired: dict[str, int] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class _ScaleOut:
    """Lifecycle record of one requested replica (prewarm-shaped)."""

    gang: Gang
    pool: str
    shape_name: str
    created_at: Seconds
    provision_id: str | None = None
    active_at: Seconds | None = None

    def expired(self, now: Seconds, policy: ServingPolicy) -> bool:
        if self.active_at is not None:
            return now - self.active_at > policy.replica_grace_seconds
        return now - self.created_at > policy.scaleout_hold_seconds


class ServingScaler:
    """Fold signals, decide replica targets, emit advisory demand."""

    def __init__(self, adapter: ServingMetricsAdapter,
                 policy: ServingPolicy | None = None) -> None:
        self.adapter = adapter
        self.policy = policy or ServingPolicy()
        self._metrics: Any = None
        self._tracer: Any = None
        self._seq = 0
        self._scaleouts: dict[tuple, _ScaleOut] = {}
        self._surplus_since: dict[str, Seconds] = {}
        # Pool replica census as of the last pass: a rise retires the
        # oldest scale-out records (they were satisfied — whether by a
        # provision or by the planner adopting a free slice).
        self._replicas_seen: dict[str, int] = {}
        self._hw = HoltWintersForecaster(
            bin_seconds=self.policy.hw_bin_seconds,
            season_bins=self.policy.hw_season_bins)
        self._last_sample: dict[str, Seconds] = {}

    def bind(self, metrics: Any = None, tracer: Any = None) -> None:
        """Adopt the controller's registries (Controller calls this)."""
        if metrics is not None:
            self._metrics = metrics
            if self.adapter._metrics is None:
                self.adapter._metrics = metrics
        if tracer is not None:
            self._tracer = tracer

    # -- metrics helpers --------------------------------------------------

    def _inc(self, name: str, by: float = 1.0) -> None:
        if self._metrics is not None:
            self._metrics.inc(name, by)

    def set_gauge(self, name: str, value: float) -> None:
        if self._metrics is not None:
            self._metrics.set_gauge(name, value)

    # -- drain receipts (ISSUE 18) ----------------------------------------

    def confirm_scale_in(self, receipt: Any) -> bool:
        """Consume one typed drain receipt for a replica this scaler
        advised out (:class:`~tpu_autoscaler.serving.drain.
        DrainReceipt` — the same serve.py contract the router's
        ``absorb_drain`` migrates from, so the two consumers can't
        drift on field names).  Retires the replica from the adapter
        census immediately (its contribution leaves the pool sums this
        pass, not at snapshot timeout) and accounts the drain: True
        iff it was clean (drained with zero unserved).  A dirty drain
        is the router migration path's problem — counted here so the
        ``serving_drain_unserved`` rate surfaces it either way."""
        from tpu_autoscaler.serving.drain import DrainReceipt

        if not isinstance(receipt, DrainReceipt):
            receipt = DrainReceipt.from_payload(receipt)
        if receipt.replica:
            self.adapter.remove(receipt.replica)
        self._inc("serving_drains_confirmed")
        if receipt.unserved:
            self._inc("serving_drain_unserved",
                      float(receipt.unserved))
        return receipt.clean

    # -- decision helpers -------------------------------------------------

    def _slots_per_replica(self, sig: PoolSignal) -> float:
        if sig.replicas <= 0 or sig.slots <= 0:
            return 1.0
        return sig.slots / sig.replicas

    def _chips_per_replica(self, shape_name: str) -> int:
        from tpu_autoscaler.topology.catalog import shape_by_name

        try:
            return max(1, shape_by_name(shape_name).chips)
        except KeyError:
            return 1

    def _pressure_target(self, sig: PoolSignal) -> int:
        """Replicas needed to hold the live backlog at target
        utilization, plus SLO headroom when attainment is burning."""
        spr = self._slots_per_replica(sig)
        per_replica = max(1e-9,
                          spr * self.policy.target_utilization)
        need = math.ceil(sig.backlog / per_replica)
        if sig.finished_per_s > 0.0 and sig.slo_attainment \
                < self.policy.slo_attainment_target:
            need += self.policy.slo_bump_replicas
        return need

    def _forecast_target(self, sig: PoolSignal, now: Seconds) -> int:
        """Predicted near-term demand (Holt-Winters over the live
        backlog series) converted to replicas; 0 when silent or
        unconfident."""
        if not self.policy.forecast:
            return 0
        chips_per = self._chips_per_replica(sig.shape_name)
        last = self._last_sample.get(sig.pool)
        if last is None or now - last >= self.policy.sample_seconds:
            # The live arrival source: demand in chip terms, sampled
            # on a fixed period so the bins mean something.  The queue
            # term is BOUNDED by the occupancy: an under-provisioned
            # pool's exploding queue is a symptom of lag, not of
            # demand — unbounded it would poison the seasonal model
            # with outliers and the forecaster would never earn
            # confidence.
            spr = self._slots_per_replica(sig)
            demand_slots = sig.active + min(sig.queue_depth,
                                            sig.active + spr)
            demand_chips = int(round(
                demand_slots / max(1e-9, spr) * chips_per))
            # Series keyed by POOL (the forecaster's "class" slot):
            # two pools sharing an accelerator class have independent
            # day-shapes — one interleaved series would poison the
            # seasonal model and hand each pool the other's forecast.
            self._hw.note(sig.pool, sig.shape_name, now, demand_chips)
            self._last_sample[sig.pool] = now
        horizon = (self.policy.provision_estimate_seconds
                   + self.policy.hw_bin_seconds)
        for f in self._hw.forecasts(now):
            if f.accel_class != sig.pool:
                continue
            if f.confidence < self.policy.min_confidence:
                continue
            if f.at - now > horizon:
                continue
            return math.ceil(
                f.chips / (chips_per
                           * self.policy.target_utilization))
        return 0

    def _advisory_gang(self, pool: str, shape_name: str) -> Gang:
        from tpu_autoscaler.policy.engine import _probe_pod_payload

        self._seq += 1
        name = f"serve-{pool}-{self._seq}"
        return Gang(
            key=("serving", SERVING_NAMESPACE, name),
            pods=[Pod(_probe_pod_payload(shape_name, name,
                                         SERVING_NAMESPACE))])

    # -- the pass ---------------------------------------------------------

    def advise(self, statuses: Sequence[Any], now: Seconds,
               signals: Mapping[str, PoolSignal] | None = None
               ) -> ServingAdvice:
        """One pass: fold the adapter, advance scale-out lifecycles off
        the actuator statuses, emit this pass's advisory demand."""
        pol = self.policy
        if signals is None:
            self.adapter.fold(now)
            signals = self.adapter.signals()
        advice = ServingAdvice()

        # ---- scale-out lifecycle off the actuator statuses -------------
        by_key: dict[tuple, Any] = {}
        for status in statuses:
            key = getattr(status.request, "gang_key", None)
            if key is not None and key and key[0] == "serving":
                by_key[key] = status
        for key, so in list(self._scaleouts.items()):
            status = by_key.get(key)
            if status is not None:
                so.provision_id = status.id
                if status.state == "ACTIVE" and so.active_at is None:
                    so.active_at = now
                elif status.state == "FAILED":
                    # Keep the record: re-emission resumes and the
                    # reconciler's per-key backoff paces the retry.
                    so.provision_id = None
            if so.expired(now, pol):
                del self._scaleouts[key]

        # Retire records their pool's replica census has caught up to:
        # a joined replica satisfied the OLDEST outstanding request,
        # whether its slice came from a provision or from the planner
        # adopting a free slice (no actuator status in that case).
        for pool in sorted(signals):
            sig = signals[pool]
            seen = self._replicas_seen.get(pool)
            self._replicas_seen[pool] = sig.replicas
            joined = sig.replicas - (seen if seen is not None
                                     else sig.replicas)
            if joined <= 0:
                continue
            mine = sorted(
                (k for k, so in self._scaleouts.items()
                 if so.pool == pool),
                key=lambda k: self._scaleouts[k].created_at)
            for key in mine[:joined]:
                self._record_scaleout_trace(self._scaleouts[key], now)
                del self._scaleouts[key]

        pending_by_pool: dict[str, int] = {}
        for so in self._scaleouts.values():
            pending_by_pool[so.pool] = pending_by_pool.get(so.pool,
                                                           0) + 1

        # ---- per-pool targets ------------------------------------------
        total_replicas = 0.0
        total_queue = 0.0
        worst_attainment = 1.0
        kv_used = kv_cap = 0.0
        preempted_per_s = 0.0
        trace_sampled = trace_tail = trace_dropped = 0.0
        for pool in sorted(signals):
            sig = signals[pool]
            total_replicas += sig.replicas
            total_queue += sig.queue_depth
            kv_used += sig.kv_used
            kv_cap += sig.kv_capacity
            preempted_per_s += sig.preempted_per_s
            trace_sampled += sig.trace_sampled_per_s
            trace_tail += sig.trace_tail_per_s
            trace_dropped += sig.trace_dropped_per_s
            if sig.finished_per_s > 0.0:
                worst_attainment = min(worst_attainment,
                                       sig.slo_attainment)
            desired = max(self._pressure_target(sig),
                          self._forecast_target(sig, now))
            desired = min(max(desired, pol.min_replicas),
                          pol.max_replicas)
            advice.desired[pool] = desired
            deficit = (desired - sig.replicas
                       - pending_by_pool.get(pool, 0))
            for _ in range(max(0, deficit)):
                gang = self._advisory_gang(pool, sig.shape_name)
                self._scaleouts[gang.key] = _ScaleOut(
                    gang=gang, pool=pool, shape_name=sig.shape_name,
                    created_at=now)
                self._inc("serving_scaleouts")
                log.info("serving scale-out decided: %s -> %d replicas "
                         "(%s)", pool, desired, gang.key[2])
            # Scale-in: deadband target (the fleet that would still be
            # UNDER-utilized), persistence through the hysteresis
            # window, and a per-decision step cap — all three guard
            # against drain/provision thrash.
            spr = self._slots_per_replica(sig)
            floor_target = max(
                desired, pol.min_replicas,
                math.ceil(sig.backlog
                          / max(1e-9,
                                spr * pol.scalein_utilization)))
            surplus = sig.replicas - floor_target \
                - pending_by_pool.get(pool, 0)
            if surplus > 0:
                since = self._surplus_since.setdefault(pool, now)
                if now - since >= pol.scalein_hold_seconds:
                    step = max(1, sig.replicas // pol.scalein_step_div)
                    advice.scale_in[pool] = min(surplus, step)
                    self._inc("serving_scaleins",
                              advice.scale_in[pool])
                    self._surplus_since[pool] = now  # re-arm
            else:
                self._surplus_since.pop(pool, None)

        # Pools whose census dropped to ZERO vanish from signals() —
        # they must still be scalable from zero: min_replicas holds,
        # and their stale scale-in hysteresis must not survive into a
        # future reappearance (it would bypass the hold).
        for pool in self.adapter.pools:
            if pool in signals:
                continue
            self._surplus_since.pop(pool, None)
            self._replicas_seen[pool] = 0
            want = min(pol.min_replicas, pol.max_replicas)
            if want <= 0:
                continue
            advice.desired[pool] = want
            _accel, shape = self.adapter.pool_meta(pool)
            for _ in range(max(0,
                               want - pending_by_pool.get(pool, 0))):
                gang = self._advisory_gang(pool, shape)
                self._scaleouts[gang.key] = _ScaleOut(
                    gang=gang, pool=pool, shape_name=shape,
                    created_at=now)
                self._inc("serving_scaleouts")
                log.info("serving scale-from-zero: %s -> %d replicas",
                         pool, want)

        for so in self._scaleouts.values():
            # A record whose provision went ACTIVE stops emitting
            # demand (the slice exists; the replica is joining) but
            # keeps counting toward ``pending`` through its grace —
            # re-emitting would provision a SECOND slice the moment
            # the first one's replica pod made it look busy.
            if so.active_at is None:
                advice.advisory.append((so.gang, so.shape_name))

        self.set_gauge("serving_replicas", total_replicas)
        self.set_gauge("serving_queue_depth", total_queue)
        self.set_gauge("serving_slo_attainment", worst_attainment)
        self.set_gauge("serving_desired_replicas",
                    float(sum(advice.desired.values())))
        self.set_gauge("serving_advisory_gangs", len(advice.advisory))
        self.set_gauge("serving_pools", float(len(signals)))
        # Data-plane health correlates (ISSUE 14): the series the
        # tail-cause analyzer reads next to the sampled request spans
        # — fleet KV pressure, preemption rate, sampler promotion/
        # drop rates (a rising drop rate means coverage degraded).
        self.set_gauge("serving_kv_occupancy",
                       kv_used / kv_cap if kv_cap > 0 else 0.0)
        self.set_gauge("serving_preempted_per_s", preempted_per_s)
        self.set_gauge("serving_trace_sampled_per_s", trace_sampled)
        self.set_gauge("serving_trace_tail_per_s", trace_tail)
        self.set_gauge("serving_trace_dropped_per_s", trace_dropped)
        return advice

    def _record_scaleout_trace(self, so: _ScaleOut,
                               now: Seconds) -> None:
        """A satisfied scale-out record closes as a retroactive
        ``scaleup-*`` trace (ISSUE 14): root ``scale_up`` span
        decided→replica-joined, a ``provision`` child when an actual
        provision served it, and the ``pods_running`` join phase —
        the control-plane anchor the tail-report cross-links a
        queue-wait-dominated request tail to.  Serving provisions are
        advisory (no Unschedulable pod ever exists), so without this
        the data plane's "replica arrived late" verdict would have
        nothing to point at."""
        if self._tracer is None:
            return
        trace_id = self._tracer.new_trace("scaleup")
        root = self._tracer.start(
            "scale_up", trace_id=trace_id, parent=None,
            t=so.created_at,
            attrs={"gang": so.gang.key[2], "serving_pool": so.pool,
                   "shape": so.shape_name,
                   "kind": "serving_scaleout"})
        joined_from = so.created_at
        if so.active_at is not None:
            self._tracer.record("provision", start=so.created_at,
                                end=so.active_at, parent=root,
                                attrs={"provision_id":
                                       so.provision_id})
            joined_from = so.active_at
        self._tracer.record("pods_running", start=joined_from,
                            end=now, parent=root)
        self._tracer.end(root, t=now,
                         attrs={"latency_s":
                                round(now - so.created_at, 3)})

    # -- introspection ----------------------------------------------------

    def debug_state(self) -> dict[str, Any]:
        """JSON-able scale-out table (reconcile-thread callers only —
        unlike /debugz readers, nothing copies this concurrently)."""
        return {
            "scaleouts": {
                "/".join(k[1:]): {
                    "pool": so.pool, "shape": so.shape_name,
                    "created_at": so.created_at,
                    "provision_id": so.provision_id,
                    "active_at": so.active_at,
                } for k, so in self._scaleouts.items()},
            "replicas": self.adapter.replicas,
        }
