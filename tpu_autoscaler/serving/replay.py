"""Serving replay: millions-of-users traffic through the real
Controller, signal-driven vs pod-pending reactive.

The evaluation loop behind ``bench.py serving`` (the ISSUE 9 outcome
gate), shaped like ``policy/replay.py``: a seeded diurnal+spike
request-level traffic program (``policy/traffic.py`` — the SAME
day-shape the gang-level programs use) drives a fleet of simulated
serving replicas against ``FakeKube`` + the production ``Controller``,
once per scaling mode:

- ``reactive``  — pod-pending scaling, the pre-ISSUE-9 world: replica
  demand enters the control plane only as a pending serving pod, so
  provisioning starts when the pod goes Unschedulable (after the
  overload already exists);
- ``signal``    — the live-signal hot path: every replica exports its
  engine stats (real :class:`ServingStatsRecorder` rings), the
  metrics adapter folds them O(churn), and the ServingScaler's
  replica-target / forecast advice prewarms supply through the
  planner's advisory hook before the ramp bites.

Replicas are queueing models, not JAX engines (thousands of engines
would measure JAX, not the autoscaler): FIFO request cohorts, a fixed
service rate, a slot cap — but their export path is the REAL stats
recorder and the adapter/scaler under test are the production objects.
Scale-in honors the serve.py drain contract in both modes: a surplus
replica stops admitting, finishes its queue (work re-routes), and only
then does its slice idle into reclaim — the zero-lost-requests
assertion at the end of every replay.

Scored like the policy bench: the first ``days - 1`` days are warmup
(the Holt-Winters forecaster must earn its seasonal confidence), the
last day — ramp, peak, and an unforecastable spike — is the scored
tail.  The gate compares per-request SLO attainment there.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Any

import numpy as np

from tpu_autoscaler.policy import traffic
from tpu_autoscaler.serving.adapter import ServingMetricsAdapter
from tpu_autoscaler.serving.drain import DrainReceipt
from tpu_autoscaler.serving.reqtrace import SAMPLE_DENOM
from tpu_autoscaler.serving.scaler import ServingPolicy, ServingScaler
from tpu_autoscaler.serving.stats import ServingStatsRecorder

#: Realistic-actuation profile (mirrors policy/replay.py).
PROVISION_DELAY_S = 90.0
HOST_STAGGER_S = 2.0

#: Serving replica slice shape: single-host v5e-4 (one replica = one
#: slice = one node; the cheapest unit the catalog offers).
REPLICA_SHAPE = "v5e-4"

#: "Millions of users" derivation: modeled requests per user per hour.
REQS_PER_USER_PER_HOUR = 1.0


@dataclasses.dataclass(frozen=True)
class ServingReplayConfig:
    """One replay's traffic + fleet geometry (pure data)."""

    seed: int = 0
    day_seconds: float = 2400.0     # one compressed "day"
    # Last day is the scored tail; the Holt-Winters forecaster needs
    # two complete seasons before it reports confidence at all
    # (forecast.py), so 4 days = 3 warmup days + a scored day with a
    # confident seasonal model.
    days: int = 4
    step: float = 5.0
    peak_rps: float = 600.0
    trough_rps: float = 60.0
    # Sharp shoulders: the ramp (~190 s) is shorter than reactive
    # detection + provision (~100 s lag against a moving target) —
    # exactly the regime where signal lead time shows.
    ramp_fraction: float = 0.08
    # The unforecastable burst, in the LAST day's quiet phase:
    # (start offset into the last day, duration, rate multiplier).
    spike_offset: float = 0.75
    spike_duration: float = 240.0
    spike_mult: float = 5.0
    # Replica service model.
    slots_per_replica: int = 16
    replica_rps: float = 8.0        # completions/s at saturation
    tokens_per_request: int = 100
    slo_seconds: float = 15.0       # arrival -> completion target
    # Scale-in pacing, SHARED by both modes (the comparison must not
    # hand either side a lazier drain): deadband utilization floor,
    # persistence hold, per-decision fleet-fraction cap.
    scalein_utilization: float = 0.45
    scalein_hold_seconds: float = 120.0
    scalein_step_div: int = 4
    report_every_steps: int = 3     # snapshot export period (staggered)
    baseline_replicas: int = 16     # warm fleet at t=0 (both modes)
    max_replicas: int = 160
    target_utilization: float = 0.75
    # Reactive trigger hysteresis: overload must persist this many
    # steps before the pod-pending submitter fires (HPA-ish lag).
    reactive_hold_steps: int = 2
    idle_threshold_seconds: float = 180.0
    # Request-level tracing (ISSUE 14): head-sampling rate for the
    # per-replica RequestTraceSampler (0 = tracing off).  Tail capture
    # (SLO misses, drain losses) is always on when tracing is on; the
    # samplers share the Controller's flight recorder, so request
    # traces land in the same /debugz dumps and incident bundles as
    # the control-plane traces.
    trace_sample_rate: float = 0.0
    # Request dispatch (ISSUE 18): how arriving cohorts land on
    # replicas.  "spread" is the legacy emptiest-third split;
    # "random" / "rr" are the router-gate baselines (whole cohort to
    # one uniformly-random / round-robin replica); "router" drives
    # the real RouterCore over the adapter's score columns — session
    # affinity, drain masking and migration included.
    route_mode: str = "spread"
    # Sub-cohort granularity for the routed modes: arrivals split
    # into dispatch units of at most this many requests (one unit ~
    # one conversation burst).  0 keeps the legacy one-cohort-per-
    # step granularity (spread mode's historical behavior).
    cohort_max: int = 0
    # Fraction of dispatch units carrying a session key, drawn from a
    # bounded id pool so conversations recur and affinity can earn
    # hits (router mode only).
    session_fraction: float = 0.0
    session_pool: int = 2000
    # Freeze the fleet at ``baseline_replicas`` for the whole trace:
    # no scaler, no reactive submitter, no drains — the router gate's
    # "equal provisions" ground rule (every route mode sees the
    # identical fleet, so the measured difference is dispatch alone).
    freeze_fleet: bool = False

    @property
    def spikes(self) -> tuple[tuple[float, float, float], ...]:
        start = (self.day_seconds * (self.days - 1)
                 + self.spike_offset * self.day_seconds)
        return ((start, self.spike_duration, self.spike_mult),)

    @property
    def until(self) -> float:
        return self.day_seconds * self.days

    @property
    def modeled_users(self) -> int:
        """Users whose aggregate peak demand this trace models."""
        return int(self.peak_rps * 3600.0 / REQS_PER_USER_PER_HOUR)

    def rate(self, t: float) -> float:
        return traffic.request_rate(
            t, self.day_seconds, self.peak_rps, self.trough_rps,
            ramp_fraction=self.ramp_fraction, spikes=self.spikes)


class _Replica:
    """One simulated serving replica: FIFO cohorts + a real recorder.

    Service model: ``slots`` concurrent requests, each occupying its
    slot for ``tau = slots / replica_rps`` seconds — so saturation
    throughput is ``replica_rps`` and the *active* count at the end of
    a step reflects true occupancy (``lambda * tau`` when subcritical,
    ``slots`` when saturated).  That occupancy is the load signal the
    stats recorder exports; without it, instantaneous queues carry no
    information at steady state."""

    __slots__ = ("name", "node", "fifo", "queued", "carry", "draining",
                 "recorder", "decode_tokens", "active", "sampler",
                 "_aseq", "_hash_base", "_bar")

    def __init__(self, name: str, node: str, cfg: ServingReplayConfig,
                 trace_recorder=None) -> None:
        self.name = name
        self.node = node
        # Cohorts: [arrival_t, n] untraced; [arrival_t, n, rid,
        # head_sampled] with the sampler on.
        self.fifo: deque[list] = deque()
        self.queued = 0
        self.carry = 0.0
        self.draining = False
        self.decode_tokens = 0
        self.active = 0
        self.recorder = ServingStatsRecorder(
            cfg.slots_per_replica,
            slo_ticks=max(1, int(cfg.slo_seconds // cfg.step)))
        # Request-trace sampler (ISSUE 14): cohort-granular — one
        # trace per scored completion cohort, head-sampled by cohort
        # id plus always-on tail capture.  The latency unit here is
        # SECONDS (the replay's clock), so the tail bound is the
        # replay's SLO directly.
        self.sampler = None
        self._aseq = 0
        if cfg.trace_sample_rate > 0.0:
            import zlib

            from tpu_autoscaler.serving.reqtrace import (
                RequestTraceSampler,
            )

            self.sampler = RequestTraceSampler(
                name, sample_rate=cfg.trace_sample_rate,
                slo_ticks=cfg.slo_seconds, stats=self.recorder,
                recorder=trace_recorder)
            # Integer head-sampling (the assign fast path): one crc32
            # of the replica name at construction, then a multiply/mod
            # per cohort — deterministic for a given seed, no string
            # build or byte hash per assignment.
            self._hash_base = zlib.crc32(name.encode())
            self._bar = int(cfg.trace_sample_rate * SAMPLE_DENOM)

    def assign(self, t: float, n: int,
               decision: str | None = None) -> None:
        """``decision``: the router's verdict for this cohort (stick/
        hedge/migrate/dispatch, ISSUE 18) — stamped onto any promoted
        request trace so a bad affinity table shows up as a named
        attribute in the tail-report decomposition."""
        if n <= 0:
            return
        if self.sampler is None:
            self.fifo.append([t, n])
        else:
            # Decide the cohort's head-sampling verdict ONCE here
            # (integer mix of the replica hash and the cohort seq);
            # the per-completion-chunk path then pays two compares,
            # and the cohort id string is built only on promotion.
            self._aseq += 1
            head = ((self._hash_base + self._aseq * 2654435761)
                    % SAMPLE_DENOM) < self._bar
            self.fifo.append([t, n, self._aseq, head, decision])
        self.queued += n
        self.recorder.note_admit(n)

    def reroute(self) -> list[list]:
        """Drain contract, queue half: everything beyond one slot-full
        of in-flight work re-routes to other replicas (nothing is
        lost; the in-flight tail finishes here before the slice may
        idle into reclaim)."""
        keep = min(self.queued, self.recorder.slots)
        out: list[list] = []
        surplus = self.queued - keep
        while surplus > 0 and self.fifo:
            tail = self.fifo[-1]
            take = min(surplus, tail[1])
            tail[1] -= take
            surplus -= take
            self.queued -= take
            out.append([tail[0], take])
            if tail[1] == 0:
                self.fifo.pop()
        return out

    def step(self, t: float, cfg: ServingReplayConfig,
             score) -> None:
        """Serve one sim step: FIFO completions at the service rate,
        then close the stats tick."""
        cap = self.carry + cfg.replica_rps * cfg.step
        tau = cfg.slots_per_replica / cfg.replica_rps
        done = 0
        while cap >= 1.0 and self.fifo:
            head = self.fifo[0]
            take = min(int(cap), head[1])
            if take <= 0:
                break
            head[1] -= take
            cap -= take
            done += take
            self.queued -= take
            latency = t + cfg.step - head[0]
            score(head[0], t + cfg.step, take)
            if self.sampler is not None:
                # One trace per scored completion cohort: head verdict
                # decided at assignment, SLO misses ALWAYS captured
                # (queue_wait = everything beyond the service time —
                # the queueing model's attribution).  The unpromoted
                # fast path is these two compares; everything else
                # happens only for the ~1% + tail.
                miss = latency > cfg.slo_seconds
                if head[3] or miss:
                    self.sampler.note_cohort(
                        f"{self.name}-a{head[2]}", arrival=head[0],
                        finish=t + cfg.step, n=take,
                        exec_time=min(tau, latency), head=head[3],
                        attrs=({"router": head[4]} if head[4]
                               else None))
                if latency - tau >= cfg.step:
                    # Wait-split feed, cohort-approximate (one write
                    # per waiting completion chunk, like the bounded
                    # note_finish loop below — the per-request exact
                    # split lives in the real engines'
                    # _note_admitted).  The guard keeps the value
                    # positive and sub-tick waits (which would round
                    # to zero anyway) off the ring — the fast path
                    # stays one subtract + compare.
                    self.recorder.note_first_scheduled(
                        int((latency - tau) // cfg.step))
            lat_ticks = max(0, int(latency // cfg.step))
            for _ in range(min(take, 32)):
                # Bounded per-cohort recorder writes: the ring only
                # needs the latency distribution, not every request.
                self.recorder.note_finish(lat_ticks)
            extra = take - 32
            if extra > 0:
                self.recorder.finished_total += extra
                if self.recorder.slo_ticks is None \
                        or lat_ticks <= self.recorder.slo_ticks:
                    self.recorder.slo_ok_total += extra
            if head[1] == 0:
                self.fifo.popleft()
        self.carry = cap - int(cap) if self.fifo else 0.0
        self.decode_tokens += done * cfg.tokens_per_request
        # Occupancy at step end: lambda * tau when keeping up, the
        # full slot set when a queue persists (saturated).
        tau = cfg.slots_per_replica / cfg.replica_rps
        if self.queued > 0:
            self.active = self.recorder.slots
        else:
            self.active = min(self.recorder.slots,
                              int(round(done * tau / cfg.step)))
        self.recorder.end_tick(
            queue_depth=self.queued, active=self.active,
            kv_used=self.active * cfg.tokens_per_request,
            kv_capacity=self.recorder.slots * 256,
            decode_tokens_total=self.decode_tokens)


@dataclasses.dataclass
class ServingReplayResult:
    mode: str
    arrived: int
    served: int
    unserved: int
    attainment: float          # whole trace
    tail_attainment: float     # scored window (the last day)
    tail_miss_rate: float
    worst_window_attainment: float
    latency_p50_s: float
    latency_p99_s: float
    peak_replicas: int
    provisions: int
    scaleouts: int
    passes: int
    # Mean (over scored-tail steps) population variance of the
    # per-replica KV-cache occupancy ratio — the router gate's
    # balance metric (ISSUE 18): random dispatch saturates some
    # pagers while neighbors idle blocks; the score's KV term keeps
    # this flat.
    kv_occ_variance: float = 0.0
    route_mode: str = "spread"

    def as_dict(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        for k in ("attainment", "tail_attainment", "tail_miss_rate",
                  "worst_window_attainment"):
            d[k] = round(d[k], 4)
        d["kv_occ_variance"] = round(d["kv_occ_variance"], 6)
        return d


def _serving_policy(cfg: ServingReplayConfig) -> ServingPolicy:
    season = max(8, int(cfg.day_seconds // 120.0))
    return ServingPolicy(
        target_utilization=cfg.target_utilization,
        scalein_utilization=cfg.scalein_utilization,
        scalein_step_div=cfg.scalein_step_div,
        slo_attainment_target=0.97,
        max_replicas=cfg.max_replicas,
        min_replicas=1,
        scaleout_hold_seconds=PROVISION_DELAY_S + 180.0,
        replica_grace_seconds=90.0,
        scalein_hold_seconds=cfg.scalein_hold_seconds,
        forecast=True, min_confidence=0.35,
        provision_estimate_seconds=PROVISION_DELAY_S + 60.0,
        sample_seconds=cfg.day_seconds / season,
        hw_bin_seconds=cfg.day_seconds / season,
        hw_season_bins=season)


class _Score:
    """Request-latency scoreboard (exact, cohort-weighted)."""

    def __init__(self, cfg: ServingReplayConfig) -> None:
        self._cfg = cfg
        # The scored tail covers the LAST day including its morning
        # ramp, which (wrap shoulder) starts at the end of the
        # previous day — the exact window reactive lag bleeds in.
        self._scored_from = cfg.day_seconds * (
            cfg.days - 1 - cfg.ramp_fraction)
        self.served = 0
        self.ok = 0
        self.tail_served = 0
        self.tail_ok = 0
        # Latency histogram in whole seconds (exact p50/p99 to 1 s).
        self._lat = np.zeros(4096, np.int64)
        # Rolling 5-minute windows for worst-window attainment.
        self._window: dict[int, list[int]] = {}
        # SLO-missing completion cohorts (arrival, finish, n) — the
        # ISSUE 14 tail-coverage oracle: with tracing on, EVERY one of
        # these must have a tail-captured request trace.
        self.miss_cohorts: list[tuple[float, float, int]] = []

    def __call__(self, arrival_t: float, finish_t: float,
                 n: int) -> None:
        latency = finish_t - arrival_t
        ok = latency <= self._cfg.slo_seconds
        self.served += n
        self.ok += n if ok else 0
        if not ok:
            self.miss_cohorts.append((arrival_t, finish_t, n))
        if arrival_t >= self._scored_from:
            self.tail_served += n
            self.tail_ok += n if ok else 0
        self._lat[min(4095, int(latency))] += n
        w = int(arrival_t // 300.0)
        cell = self._window.setdefault(w, [0, 0])
        cell[0] += n
        cell[1] += n if ok else 0

    def percentile(self, q: float) -> float:
        total = int(self._lat.sum())
        if not total:
            return 0.0
        cum = np.cumsum(self._lat)
        return float(np.searchsorted(cum, q * total, side="left"))

    @property
    def worst_window(self) -> float:
        worst = 1.0
        for n, ok in self._window.values():
            if n >= 50:
                worst = min(worst, ok / n)
        return worst


def replay(config: ServingReplayConfig, *, mode: str,
           probe=None, artifacts: dict | None = None
           ) -> ServingReplayResult:
    """Drive one traffic program through the real control loop.

    ``probe``: optional per-step callback ``(t, replica_count,
    backlog, score)`` for tests and trace inspection.

    ``artifacts``: optional dict the replay fills with its live
    objects (``controller``, ``score``, ``samplers``) — the ISSUE 14
    acceptance surface (request traces, exemplars, incident bundles)
    without widening the scorecard result."""
    if mode not in ("reactive", "signal"):
        raise ValueError(f"unknown serving replay mode {mode!r}")
    if config.route_mode not in ("spread", "random", "rr", "router"):
        raise ValueError(
            f"unknown route mode {config.route_mode!r}")
    from tpu_autoscaler.actuators.fake import FakeActuator
    from tpu_autoscaler.controller import Controller, ControllerConfig
    from tpu_autoscaler.engine.planner import PoolPolicy
    from tpu_autoscaler.k8s.fake import FakeKube
    from tpu_autoscaler.k8s.informer import ClusterInformer
    from tpu_autoscaler.k8s.objects import clear_parse_caches
    from tpu_autoscaler.sim import gang_pods
    from tpu_autoscaler.topology.catalog import shape_by_name

    clear_parse_caches()
    cfg = config
    shape = shape_by_name(REPLICA_SHAPE)
    accel = shape.accelerator_type
    kube = FakeKube()
    actuator = FakeActuator(kube, provision_delay=PROVISION_DELAY_S,
                            stagger_seconds=HOST_STAGGER_S)
    informer = ClusterInformer(kube, timeout_seconds=0)
    adapter = ServingMetricsAdapter()
    # freeze_fleet: no scaler — the fleet stays at baseline size in
    # every route mode (the "equal provisions" ground rule of the
    # router gate).
    scaler = (ServingScaler(adapter, _serving_policy(cfg))
              if mode == "signal" and not cfg.freeze_fleet else None)
    router = None
    route_rng = None
    rr_next = [0]
    if cfg.route_mode == "router":
        from tpu_autoscaler.serving.router import RouterCore

        router = RouterCore(adapter)
    if cfg.route_mode in ("random", "rr", "router"):
        # A dispatch-only RNG stream, distinct from the arrival RNG:
        # every route mode sees the byte-identical arrival sequence.
        route_rng = np.random.default_rng((cfg.seed << 1) ^ 0x5E55)
    recorder = None
    if cfg.trace_sample_rate > 0.0:
        # Request traces share the controller's flight recorder (one
        # dump carries both planes); a deeper ring so a spike's tail
        # captures survive to the post-replay assertions.
        from tpu_autoscaler.obs import FlightRecorder

        recorder = FlightRecorder(max_spans=32768)
    controller = Controller(
        kube, actuator,
        ControllerConfig(
            policy=PoolPolicy(spare_nodes=0, max_total_chips=8192),
            grace_seconds=60.0,
            idle_threshold_seconds=cfg.idle_threshold_seconds,
            drain_grace_seconds=30.0,
            provision_timeout_seconds=600.0),
        informer=informer, serving_scaler=scaler, recorder=recorder)
    trace_recorder = controller.recorder \
        if cfg.trace_sample_rate > 0.0 else None

    rng = np.random.default_rng(cfg.seed)
    score = _Score(cfg)
    replicas: dict[str, _Replica] = {}   # node name -> replica
    samplers: list = []                  # every sampler ever built

    def _new_replica(pod_name: str, node: str) -> _Replica:
        rep = _Replica(pod_name, node, cfg,
                       trace_recorder=trace_recorder)
        if rep.sampler is not None:
            samplers.append(rep.sampler)
        return rep
    unassigned: deque[list] = deque()    # pool-level cohorts
    pod_of: dict[str, str] = {}          # node -> serving pod name
    # Nodes whose replica drained away: they idle toward reclaim and
    # the DaemonSet must NOT resurrect them — unless new scale-out
    # demand re-enlists the warm slice first (cheaper than a
    # provision; the planner's free-slice adoption models the same).
    retired: set[str] = set()
    seq = [0]
    overload_streak = [0]
    reactive_surplus_since: list = [None]
    arrived = 0
    passes = 0
    peak = 0
    scaleouts_metric = "serving_scaleouts"
    # KV-occupancy balance accounting over the scored tail (ISSUE 18).
    scored_from = cfg.day_seconds * (cfg.days - 1 - cfg.ramp_fraction)
    kv_var_sum = 0.0
    kv_var_n = 0

    def serving_nodes() -> dict[str, Any]:
        out = {}
        for n in informer.nodes():
            if n.is_tpu and n.tpu_accelerator == accel \
                    and n.is_ready and not n.unschedulable:
                out[n.name] = n
            elif not n.is_ready and n.name in replicas:
                # A host failure mid-replay: reroute and drop.
                _kill_replica(n.name)
        return out

    def _kill_replica(node: str, now: float = 0.0) -> None:
        rep = replicas.pop(node, None)
        if rep is None:
            return
        unassigned.extend(rep.fifo)
        pod = pod_of.pop(node, None)
        if pod is not None and kube.get_pod("default", pod):
            kube.delete_pod("default", pod)
        if router is not None:
            # The replica's end of life flows through the typed drain
            # contract (ISSUE 18): a DrainReceipt accounts what it
            # served and what it hands off; the scaler retires it
            # from the census, the router stops masking it.  The
            # unserved remainder migrates via ``unassigned`` above —
            # the zero-lost assertion covers it.
            receipt = DrainReceipt(
                replica=node,
                served=int(rep.recorder.finished_total),
                unserved=int(rep.queued),
                drained=bool(rep.draining),
                elapsed_s=max(0.0, float(now)),
                ticks=int(rep.recorder.snapshot().seq),
                decode_tokens=int(rep.decode_tokens),
                request_latency_ticks=(), request_wait_ticks=(),
                request_exec_ticks=(), stats={})
            if scaler is not None:
                scaler.confirm_scale_in(receipt)
            else:
                adapter.remove(node)
            router.clear_draining(node)
        else:
            adapter.remove(node)
        retired.add(node)

    def _bind_daemonset(t: float) -> None:
        """A serving pod on every Ready serving-class node (signal
        mode's replica source; in reactive mode replicas arrive as
        scheduled pending pods instead)."""
        for name in serving_nodes():
            if name in replicas or name in retired:
                continue
            seq[0] += 1
            pod_name = f"serve-web-{seq[0]}"
            payload = gang_pods(REPLICA_SHAPE, pod_name)[0]
            payload["spec"]["nodeName"] = name
            payload["status"]["phase"] = "Running"
            payload["status"].pop("conditions", None)
            kube.add_pod(payload)
            pod_of[name] = payload["metadata"]["name"]
            replicas[name] = _new_replica(pod_name, name)

    def _adopt_scheduled(t: float) -> None:
        """Reactive mode: pending serving pods the toy scheduler bound
        become replicas."""
        for p in informer.pods():
            if p.namespace != "default" or not p.name.startswith(
                    "serve-web-"):
                continue
            if p.node_name and p.phase == "Running" \
                    and p.node_name not in replicas:
                retired.discard(p.node_name)
                pod_of[p.node_name] = p.name
                replicas[p.node_name] = _new_replica(p.name,
                                                     p.node_name)

    def _seed_baseline() -> None:
        """Warm fleet at t=0, identical in both modes."""
        from tpu_autoscaler.k8s.payloads import tpu_host_payload

        for i in range(cfg.baseline_replicas):
            kube.add_node(tpu_host_payload(
                shape, f"serve-seed-{i}", 0, 0.0, ready=True))

    def desired_replicas(backlog: float) -> int:
        import math

        per = cfg.slots_per_replica * cfg.target_utilization
        return min(cfg.max_replicas,
                   max(1, math.ceil(backlog / per)))

    def _reactive_submit(t: float, backlog: float) -> None:
        live = len(replicas)
        pending = sum(
            1 for p in informer.pods()
            if p.name.startswith("serve-web-") and p.node_name is None)
        want = desired_replicas(backlog)
        if want > live + pending:
            overload_streak[0] += 1
        else:
            overload_streak[0] = 0
            return
        if overload_streak[0] < cfg.reactive_hold_steps:
            return
        for _ in range(want - live - pending):
            seq[0] += 1
            for payload in gang_pods(REPLICA_SHAPE,
                                     f"serve-web-{seq[0]}"):
                kube.add_pod(payload)

    def _drain_surplus(t: float, surplus: int) -> None:
        """Mark the least-loaded replicas draining; their queues
        re-route NOW (serve.py drain contract: nothing is lost)."""
        candidates = sorted(
            ((node, r) for node, r in replicas.items()
             if not r.draining),
            key=lambda nr: nr[1].queued)
        for node, rep in candidates[:max(0, surplus)]:
            rep.draining = True
            if router is not None:
                router.mark_draining(node)
            for cohort in rep.reroute():
                if router is not None:
                    # Tag the handoff so its re-dispatch span-stamps
                    # as a migration, not a fresh arrival.
                    cohort.append("migrate")
                unassigned.append(cohort)

    def _reap_drained(t: float) -> None:
        for node, rep in list(replicas.items()):
            if rep.draining and rep.queued == 0:
                _kill_replica(node, t)

    def _route(t: float, n_new: int) -> None:
        nonlocal arrived
        arrived += n_new
        if n_new:
            unassigned.append([t, n_new])
        live = [r for r in replicas.values() if not r.draining]
        if not live:
            return
        if cfg.route_mode == "spread":
            while unassigned:
                cohort = unassigned.popleft()
                live.sort(key=lambda r: r.queued)
                # Spread the cohort over the emptiest third of the
                # fleet.
                k = max(1, len(live) // 3)
                share = -(-cohort[1] // k)
                for rep in live[:k]:
                    take = min(share, cohort[1])
                    if take <= 0:
                        break
                    rep.assign(cohort[0], take)
                    cohort[1] -= take
                if cohort[1] > 0:
                    unassigned.appendleft(cohort)
                    break
            return
        # Routed modes (ISSUE 18): arrivals split into dispatch units
        # of <= cohort_max requests (one unit ~ one conversation
        # burst), each unit landing whole on ONE replica — the
        # granularity at which real dispatch decisions happen.
        # "random"/"rr" are the gate baselines; "router" is the real
        # RouterCore over the adapter's score columns.
        limit = cfg.cohort_max if cfg.cohort_max > 0 else (1 << 30)
        while unassigned:
            cohort = unassigned.popleft()
            # Handoffs re-queued by a drain carry a forced decision
            # tag ("migrate") appended by _drain_surplus.
            forced = (cohort[2] if len(cohort) > 2
                      and isinstance(cohort[2], str) else None)
            t0 = cohort[0]
            while cohort[1] > 0:
                take = min(limit, cohort[1])
                session = None
                if (cfg.session_fraction > 0.0 and forced is None
                        and route_rng.random()
                        < cfg.session_fraction):
                    session = "s%d" % int(
                        route_rng.integers(cfg.session_pool))
                if router is not None:
                    d = router.dispatch(t, session=session,
                                        weight=float(take))
                    rep = (replicas.get(d.replica)
                           if d is not None else None)
                    if rep is None or rep.draining:
                        # No routable replica yet (first steps before
                        # any snapshot folded): hold the unit for the
                        # next pass.
                        unassigned.appendleft(cohort)
                        return
                    rep.assign(t0, take,
                               decision=forced or d.decision)
                elif cfg.route_mode == "random":
                    rep = live[int(route_rng.integers(len(live)))]
                    rep.assign(t0, take, decision=forced)
                else:  # rr
                    rep = live[rr_next[0] % len(live)]
                    rr_next[0] += 1
                    rep.assign(t0, take, decision=forced)
                cohort[1] -= take

    _seed_baseline()
    t = 0.0
    # Drain-out phase after the trace: arrivals stop, the fleet must
    # finish every queued request (the zero-lost assertion).
    horizon = cfg.until + 1200.0
    while t <= horizon:
        informer.pump()
        # Prune retired nodes the controller has reclaimed (or that a
        # scheduled pod re-occupied, in reactive mode).
        live_nodes = {n["metadata"]["name"] for n in kube.list_nodes()}
        retired &= live_nodes
        if mode == "signal":
            # Outstanding scale-out demand re-enlists retired warm
            # slices before the DaemonSet pass (free-slice reuse).
            advice = controller.serving_advice
            need = len(advice.advisory) if advice is not None else 0
            while need > 0 and retired:
                retired.pop()
                need -= 1
            _bind_daemonset(t)
        else:
            _adopt_scheduled(t)
        rate = cfg.rate(t) if t < cfg.until else 0.0
        n_new = traffic.arrivals_in_step(rng, rate, cfg.step)
        _route(t, n_new)
        for rep in replicas.values():
            rep.step(t, cfg, score)
        if t >= scored_from and len(replicas) >= 2:
            occ = np.fromiter(
                (r.active * cfg.tokens_per_request
                 / (r.recorder.slots * 256.0)
                 for r in replicas.values()),
                float, len(replicas))
            kv_var_sum += float(occ.var())
            kv_var_n += 1
        # Load signal AFTER serving: persistent queues + occupancy —
        # the same quantity the replicas' recorders just exported.
        backlog = (sum(r.queued + r.active for r in replicas.values())
                   + sum(c[1] for c in unassigned))
        _reap_drained(t)
        peak = max(peak, len(replicas))
        # Export: staggered snapshot ingest (signal mode, and always
        # when the router is on — its score columns feed off the same
        # snapshots whatever drives scaling).
        if mode == "signal" or router is not None:
            for i, (node, rep) in enumerate(replicas.items()):
                if (passes + i) % cfg.report_every_steps:
                    continue
                adapter.ingest(node, "web", accel, REPLICA_SHAPE,
                               rep.recorder.snapshot(), now=t)
        if router is not None:
            # Fold + candidate refresh once per step (the scaler's
            # pass folds again when attached; an empty-dirty fold is
            # O(1), so the double is free).
            adapter.fold(t)
            router.refresh(t)
        # Scale decisions.  The reactive platform gets the SAME target
        # math, deadband, and drain caps as the scaler — the measured
        # difference is the advisory/forecast lead, not a handicapped
        # baseline.
        if mode == "reactive":
            import math as _math

            _reactive_submit(t, backlog)
            floor_target = max(
                desired_replicas(backlog),
                _math.ceil(backlog
                           / (cfg.slots_per_replica
                              * cfg.scalein_utilization)))
            surplus = len(replicas) - floor_target
            if surplus > 0:
                if reactive_surplus_since[0] is None:
                    reactive_surplus_since[0] = t
                elif (t - reactive_surplus_since[0]
                      >= cfg.scalein_hold_seconds):
                    _drain_surplus(
                        t, min(surplus,
                               max(1, len(replicas)
                                   // cfg.scalein_step_div)))
                    reactive_surplus_since[0] = t
            else:
                reactive_surplus_since[0] = None
        informer.pump()
        controller.reconcile_once(now=t)
        passes += 1
        if mode == "signal" and controller.serving_advice is not None:
            surplus = controller.serving_advice.scale_in.get("web", 0)
            if surplus:
                _drain_surplus(t, surplus)
        kube.schedule_step()
        if probe is not None:
            probe(t, len(replicas), backlog, score)
        if t >= cfg.until and score.served >= arrived:
            break
        t += cfg.step

    if artifacts is not None:
        artifacts["controller"] = controller
        artifacts["score"] = score
        artifacts["samplers"] = samplers
        artifacts["router"] = router
        artifacts["adapter"] = adapter
    snap = controller.metrics.snapshot()
    counters = snap["counters"]
    unserved = arrived - score.served
    return ServingReplayResult(
        mode=mode, arrived=arrived, served=score.served,
        unserved=unserved,
        attainment=(score.ok / score.served) if score.served else 0.0,
        tail_attainment=(score.tail_ok / score.tail_served
                         if score.tail_served else 0.0),
        tail_miss_rate=(1.0 - score.tail_ok / score.tail_served
                        if score.tail_served else 1.0),
        worst_window_attainment=score.worst_window,
        latency_p50_s=score.percentile(0.50),
        latency_p99_s=score.percentile(0.99),
        peak_replicas=peak,
        provisions=int(counters.get("provisions_submitted", 0)),
        scaleouts=int(counters.get(scaleouts_metric, 0)),
        passes=passes,
        kv_occ_variance=(kv_var_sum / kv_var_n) if kv_var_n else 0.0,
        route_mode=cfg.route_mode)


def compare(config: ServingReplayConfig) -> dict[str, Any]:
    """Reactive vs signal-driven scorecard for one traffic program."""
    reactive = replay(config, mode="reactive")
    signal = replay(config, mode="signal")
    r_miss = max(reactive.tail_miss_rate, 1e-6)
    s_miss = max(signal.tail_miss_rate, 1e-6)
    return {
        "trace": {
            "seed": config.seed,
            "day_seconds": config.day_seconds, "days": config.days,
            "peak_rps": config.peak_rps,
            "trough_rps": config.trough_rps,
            "spikes": list(config.spikes),
            "modeled_users": config.modeled_users,
            "slo_seconds": config.slo_seconds,
        },
        "reactive": reactive.as_dict(),
        "signal": signal.as_dict(),
        "tail_attainment_reactive": round(reactive.tail_attainment, 4),
        "tail_attainment_signal": round(signal.tail_attainment, 4),
        # >1 means the live-signal path beats pod-pending reactive.
        "miss_rate_ratio": round(r_miss / s_miss, 3),
    }


def route_compare_config(seed: int = 0, *, replicas: int = 84,
                         peak_rps: float = 600.0,
                         day_seconds: float = 1200.0,
                         days: int = 2) -> ServingReplayConfig:
    """The router gate's trace geometry (ISSUE 18): the 2.2M-user
    diurnal day-shape (``modeled_users`` derives from ``peak_rps``
    alone) over a FROZEN fleet sized to ~0.9 peak utilization — hot
    enough that dispatch quality is the p99, no spike (a frozen fleet
    under a 5x burst is a capacity problem in every mode, which would
    only blur the routing signal)."""
    return ServingReplayConfig(
        seed=seed, day_seconds=day_seconds, days=days, step=5.0,
        peak_rps=peak_rps, trough_rps=peak_rps * 0.1,
        spike_mult=1.0, spike_duration=0.0,
        baseline_replicas=replicas, max_replicas=replicas,
        freeze_fleet=True, cohort_max=8,
        session_fraction=0.3, route_mode="router")


def route_compare(config: ServingReplayConfig | None = None
                  ) -> dict[str, Any]:
    """Router vs random vs round-robin scorecard at equal provisions
    — the same traffic program and frozen fleet per mode, only the
    dispatch decision differs.  The ``bench.py router`` gates read
    ``miss_rate_ratio`` (router beats random >= 2x) and
    ``kv_variance_ratio`` (>= 2x flatter per-replica KV occupancy),
    plus zero lost requests in every mode."""
    cfg = config or route_compare_config()
    modes: dict[str, ServingReplayResult] = {}
    for rm in ("router", "random", "rr"):
        modes[rm] = replay(dataclasses.replace(cfg, route_mode=rm),
                           mode="signal")
    router_res, random_res = modes["router"], modes["random"]
    r_miss = max(router_res.tail_miss_rate, 1e-6)
    rand_miss = max(random_res.tail_miss_rate, 1e-6)
    r_var = max(router_res.kv_occ_variance, 1e-9)
    rand_var = max(random_res.kv_occ_variance, 1e-9)
    return {
        "trace": {
            "seed": cfg.seed, "modeled_users": cfg.modeled_users,
            "peak_rps": cfg.peak_rps, "replicas": cfg.baseline_replicas,
            "slo_seconds": cfg.slo_seconds,
            "cohort_max": cfg.cohort_max,
            "session_fraction": cfg.session_fraction,
        },
        "modes": {rm: res.as_dict() for rm, res in modes.items()},
        "lost_requests": max(res.unserved for res in modes.values()),
        # >1 means the router beats random dispatch.
        "miss_rate_ratio": round(rand_miss / r_miss, 3),
        "kv_variance_ratio": round(rand_var / r_var, 3),
    }
