"""Fleet request router (ISSUE 18): vectorized KV/queue-aware dispatch.

The control plane resizes the fleet (serving/scaler.py) but users hit
*replicas*: with per-replica queues and caller-pointed dispatch, one
hot replica burns p99 while its neighbors idle KV blocks.  This module
is the dispatch decision in front of the batcher family, built on the
adapter's row arrays so the fleet never gets scanned per request:

- **Dispatch core** — the adapter's dirty-fold refreshes a per-replica
  dispatch-score column (``adapter.dispatch_scores``: queue backlog
  per slot + KV occupancy + stall penalty).  The router keeps a small
  candidate heap over that column plus its *own* per-row in-flight
  delta (requests it dispatched since the last fold, which the
  replicas' snapshots can't see yet), so consecutive dispatches spread
  instead of piling onto one argmin row.  Amortized cost per decision:
  O(log K) heap ops, with one vectorized ``argpartition`` refill per
  score refresh — the ``bench.py router`` gate holds this at
  microseconds per request at 10k replicas.
- **Affinity** — a bounded session/prefix table sticks a conversation
  to the replica holding its KV blocks (``workloads/paged.py`` block
  accounting is the ground truth for why that matters: a hit skips
  prefill).  Entries are validated on every lookup against the row's
  current occupant, its snapshot **epoch** (a bump means the replica
  restarted and the cache is gone), liveness and drain state — a
  stale entry is dropped and re-routed, never trusted.
- **Tail defense** — ``maybe_hedge`` re-dispatches a request exactly
  once when its chosen replica stalls past a budget (dead, draining,
  epoch-bumped, or score-stalled).  ``absorb_drain`` turns the
  serve.py :class:`~tpu_autoscaler.serving.drain.DrainReceipt` into
  migration dispatches for the unserved remainder — the no-lost-
  requests half of the chaos ``router`` invariant.

Purity contract (analysis TAP1xx scope): no clocks, no randomness, no
I/O — every decision is a function of the adapter's arrays, the
router's own bounded state, and caller-injected timestamps.  Ties
break on row index, so replays are deterministic by construction
(TAD9xx).  Single-consumer threading like the adapter: dispatch and
refresh run on the owning loop.
"""

from __future__ import annotations

import dataclasses
import heapq
import typing
from typing import Any

import numpy as np

from tpu_autoscaler.serving.adapter import (
    SCORE_STALL_PENALTY,
    ServingMetricsAdapter,
)
from tpu_autoscaler.serving.drain import DrainReceipt

#: Tolerance for "the heap entry's priority still matches the row's
#: effective score" — entries off by more are lazily re-priced.
_HEAP_SLACK = 1e-12


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Knobs for one RouterCore (docs/SERVING.md "Request routing")."""

    #: Candidate-heap size: the refill keeps the K cheapest rows; the
    #: hot path never touches the other fleet rows until the next
    #: refresh.  Must exceed the dispatches expected per refresh
    #: divided by how much spread is wanted; 128 is ample at per-pass
    #: folding cadence (and measured fastest at 10k replicas — wide
    #: enough that watermark re-partitions stay amortized, small
    #: enough that the refill listcomp stays trivial).
    candidates: int = 128
    #: Score cost one locally-dispatched request adds to its row until
    #: the next refresh re-prices from real snapshots (~1/slots of a
    #: typical replica — one more request's worth of backlog).
    inflight_penalty: float = 1.0 / 16.0
    #: Bounded affinity-table capacity (FIFO eviction).
    affinity_capacity: int = 65536
    #: Effective score past which a sticky replica is too hot to
    #: honor affinity — the conversation spills to the fleet-best row
    #: and re-sticks there (KV re-prefills once; p99 doesn't burn).
    #: 1.0 = one full backlog-per-slot above empty: loose enough that
    #: steady-state sessions essentially always stick, tight enough
    #: that sticky traffic cannot pile a replica past saturation
    #: (measured on the route_compare trace: spill at 4.0 lets
    #: affinity carry whole bursts and costs ~6% fleet KV balance).
    affinity_spill_score: float = 1.0
    #: Seconds a dispatched request may sit unfinished before
    #: ``maybe_hedge`` considers its replica stalled.
    hedge_after_s: float = 5.0
    #: Effective score at or past which a tracked replica counts as
    #: stalled for hedging (the adapter's stall penalty lands here).
    hedge_score: float = SCORE_STALL_PENALTY
    #: Bounded in-flight tracking for hedging (FIFO eviction: a
    #: runaway submit rate degrades hedge coverage, never memory).
    max_outstanding: int = 65536


class Dispatch(typing.NamedTuple):
    """One routing decision — span-stamped by callers (PR 14).

    A NamedTuple, not a dataclass: one of these is built per routed
    request, and tuple construction is what keeps the per-decision
    bench gate honest."""

    replica: str
    row: int
    sticky: bool = False
    hedged: bool = False
    migrated: bool = False

    @property
    def decision(self) -> str:
        """The reqtrace attribute value: stick/hedge/migrate/dispatch."""
        if self.hedged:
            return "hedge"
        if self.migrated:
            return "migrate"
        if self.sticky:
            return "stick"
        return "dispatch"


class RouterCore:
    """Masked-argmin dispatch over one adapter's score column.

    Owns three bounded pieces of state beside the adapter references:
    the per-row in-flight delta (cleared every refresh), the affinity
    table, and the outstanding-dispatch map that backs hedging.  All
    are dicts/arrays with explicit caps — fleet growth resizes the
    delta column, nothing else grows with traffic.
    """

    def __init__(self, adapter: ServingMetricsAdapter,
                 config: RouterConfig | None = None,
                 metrics: Any = None) -> None:
        self._adapter = adapter
        self._cfg = config if config is not None else RouterConfig()
        self._metrics = metrics
        self._delta = np.zeros(adapter.capacity())
        self._stamp_seen = 0
        self._draining_names: set[str] = set()
        self._drain_mask = np.zeros(adapter.capacity(), bool)
        #: session/prefix key -> (replica, row, epoch)
        self._affinity: dict[str, tuple[str, int, int]] = {}
        #: rid -> [row, epoch, t_dispatch, hedged]
        self._outstanding: dict[str, list[Any]] = {}
        self._heap: list[tuple[float, int]] = []
        self._watermark = float("inf")
        # Hot-path caches, rebuilt by every _refill (via _effective):
        # the effective-score vector (kept incrementally true by
        # _commit), the static validity mask (drain/pool snapshot),
        # and a reference to the adapter's live column so deaths are
        # seen without re-fetching the view tuple per decision.
        self._eff_vec = np.full(adapter.capacity(), np.inf)
        self._valid_mask = np.zeros(adapter.capacity(), bool)
        self._live_ref = np.zeros(adapter.capacity(), bool)
        self._names_ref = adapter.name_column()
        #: Staleness drain credit from the last refresh (None until a
        #: refresh with an injected clock; see :meth:`refresh`).
        self._credit: np.ndarray | None = None
        self._pool_filter: int = -1
        # Lifetime counters (debug_state / metric mirrors).
        self.dispatches_total = 0
        self.affinity_hits_total = 0
        self.affinity_stale_total = 0
        self.affinity_evictions_total = 0
        self.hedges_total = 0
        self.migrated_total = 0
        self.refreshes_total = 0
        # Control-plane profiler hook (ISSUE 20): when bound by the
        # owning loop, refresh cost lands in the ``router_refresh``
        # phase ledger (out-of-pass when driven between reconcile
        # passes).  Injected like the clock — the router itself never
        # measures time.
        self.profiler: Any = None

    # -- metrics ------------------------------------------------------

    def _inc(self, name: str, by: float = 1.0) -> None:
        if self._metrics is not None:
            self._metrics.inc(name, by)

    # -- refresh (call after every adapter.fold) ----------------------

    def refresh(self, now: float = 0.0,
                pool: str | None = None) -> None:
        """Re-price the candidate heap from the adapter's freshly
        folded score column — one vectorized masked ``argpartition``,
        O(fleet) numpy but zero Python per row.  Two staleness
        corrections make argmin-on-snapshots stable where the raw
        column oscillates:

        - the local in-flight delta is cleared ONLY on rows whose own
          snapshot re-folded since the last refresh — those scores now
          carry the load the dispatches created.  Rows still reporting
          a stale snapshot keep their delta; clearing it would revert
          them to a pre-dispatch "empty" score and re-create the
          classic join-the-shortest-stale-queue herd;
        - symmetric problem, symmetric fix: a row whose last snapshot
          said "busy" keeps that score for a whole report period even
          though it typically drains in a fraction of one, so it is
          starved, empties, then gets slammed on its next report.  The
          adapter's :meth:`drain_credit` (expected completions since
          the snapshot) is subtracted, deliberately unfloored (see
          :meth:`_effective`).

        ``now`` is the injected clock (purity: the router never reads
        wall time); 0.0 disables the drain credit.  ``pool`` restricts
        dispatch to one pool's rows (None = whole fleet)."""
        prof = self.profiler
        if prof is not None:
            with prof.phase("router_refresh"):
                self._refresh_impl(now, pool)
            return
        self._refresh_impl(now, pool)

    def _refresh_impl(self, now: float, pool: str | None) -> None:
        cap = self._adapter.capacity()
        if cap != self._delta.shape[0]:
            self._delta = np.zeros(cap)
            self._drain_mask = np.zeros(cap, bool)
        else:
            folded = self._adapter.fold_stamps > self._stamp_seen
            self._delta[folded] = 0.0
        self._stamp_seen = self._adapter.folds_done
        self._credit = (self._adapter.drain_credit(now) if now > 0.0
                        else None)
        self._drain_mask[:] = False
        for name in self._draining_names:
            row = self._adapter.row_of(name)
            if row >= 0:
                self._drain_mask[row] = True
        self._pool_filter = (-1 if pool is None
                             else self._adapter.pool_index(pool))
        self._refill()
        self.refreshes_total += 1

    def _effective(self) -> np.ndarray:
        """Full effective-score vector, and the hot-path caches as a
        side effect: ``_eff_vec`` (raw effective scores, which
        ``_commit`` keeps true between refills by adding its penalty
        in place), ``_valid_mask`` (drain/pool snapshot), and
        ``_live_ref`` (a *reference* to the adapter's live column, so
        in-place deaths are visible to ``_valid_row`` without
        re-fetching the view per decision)."""
        scores, live, pool_of_row = self._adapter.router_view()
        eff = scores + self._delta
        if self._credit is not None:
            # Credit applies to score AND delta: the replica serves
            # its reported backlog and our since-report dispatches
            # alike, so a row two report-periods stale with delta
            # accrued is NOT (score + delta) loaded — it drained
            # ~credit of the total in the meantime.  Crediting only
            # the score term re-creates the stagger-cohort banding
            # (just-folded rows, delta freshly cleared, soak every
            # dispatch while stale cohorts sit on unserved deltas).
            # No floor: a mildly negative estimate still ranks
            # correctly (the credit is bounded by real completions),
            # while flooring collapses every drained row into a tie
            # broken by row index — a deterministic hot spot.
            eff = eff - self._credit
        mask = live & ~self._drain_mask
        if self._pool_filter >= 0:
            mask = mask & (pool_of_row == self._pool_filter)
        self._eff_vec = eff
        self._valid_mask = mask
        self._live_ref = live
        self._names_ref = self._adapter.name_column()
        return np.where(mask, eff, np.inf)

    def _refill(self) -> None:
        eff = self._effective()
        k = min(self._cfg.candidates, eff.size)
        if k < eff.size:
            # One argpartition with kth=k yields both the candidate
            # band (indices [:k]) and the watermark (index k is in
            # sorted position): the cheapest EXCLUDED row's score.
            # Once in-flight deltas push every candidate past this,
            # rows outside the band are now the better choice and the
            # heap must re-partition — without the watermark the
            # excluded band (typically stale-busy replicas that have
            # long since drained) receives nothing until the next
            # refresh, which shows up as bimodal fleet occupancy.
            part = np.argpartition(eff, k)
            cand = part[:k]
            self._watermark = float(eff[part[k]])
        else:
            cand = np.arange(eff.size)
            self._watermark = float("inf")
        inf = float("inf")
        self._heap = [(e, r) for e, r in zip(eff[cand].tolist(),
                                             cand.tolist())
                      if e != inf]
        heapq.heapify(self._heap)

    # -- the hot path -------------------------------------------------

    def _eff_row(self, row: int) -> float:
        """Scalar effective score for one row (score minus staleness
        drain credit plus local in-flight delta), read off the cached
        vector the last refill computed and ``_commit`` keeps true."""
        return float(self._eff_vec[row])

    def _valid_row(self, row: int) -> bool:
        # _valid_mask is the drain/pool snapshot from the last refill;
        # _live_ref is the adapter's own live column, so a replica
        # that died since then is rejected immediately.
        if not (0 <= row < self._valid_mask.shape[0]):
            return False
        return bool(self._valid_mask[row] and self._live_ref[row])

    def _pick(self, exclude: int = -1) -> int:
        """Cheapest valid candidate row (never ``exclude`` — hedge
        re-dispatch leaves the original replica out).  Entries whose
        stored priority drifted are lazily re-priced (our own
        in-flight deltas are the only drift source between refreshes,
        and they only grow, so the loop terminates).  Refills when the
        heap drains or the whole candidate band has drifted past the
        refill watermark (rows outside the band are now cheaper); an
        empty fleet returns -1."""
        pop, push = heapq.heappop, heapq.heappush
        slack = _HEAP_SLACK
        for _attempt in range(3):
            heap = self._heap
            eff_vec = self._eff_vec
            valid = self._valid_mask
            live = self._live_ref
            wall = self._watermark + slack
            held: tuple[float, int] | None = None
            found = -1
            while heap:
                prio, row = pop(heap)
                if row == exclude:
                    # Keep the excluded row available for OTHER
                    # requests; just never return it here.
                    held = (prio, row)
                    continue
                if not (valid[row] and live[row]):
                    continue
                eff = eff_vec.item(row)
                push(heap, (eff, row))
                if eff > prio + slack:
                    continue
                if eff > wall:
                    # Best candidate is worse than the cheapest row
                    # OUTSIDE the band: re-partition (found stays -1
                    # so the attempt loop refills).  After a refill
                    # the best candidate is <= the new watermark by
                    # construction, so this fires at most once per
                    # band saturation, amortized over the ~K * gap /
                    # penalty dispatches that saturated it.
                    break
                found = row
                break
            if held is not None:
                push(heap, held)
            if found >= 0:
                return found
            self._refill()
        return -1

    def _commit(self, row: int, weight: float = 1.0) -> str:
        pen = self._cfg.inflight_penalty * weight
        self._delta[row] += pen
        self._eff_vec[row] += pen
        self.dispatches_total += 1
        m = self._metrics
        if m is not None:
            m.inc("router_dispatches", 1.0)
        name = self._names_ref[row]
        assert name is not None  # _valid_row checked live
        return name

    def dispatch(self, now: float, *, session: str | None = None,
                 rid: str | None = None,
                 weight: float = 1.0) -> Dispatch | None:
        """Route one request.  ``session``: affinity key (conversation
        / prefix hash) — a valid entry sticks, a stale one is dropped
        and re-routed.  ``rid``: track this request for hedging and
        exactly-once completion.  ``weight``: request count this
        decision covers (a cohort dispatch scales the local in-flight
        penalty).  Returns None only when no live, non-draining
        replica exists."""
        sticky = False
        row = -1
        if session is not None:
            ent = self._affinity.get(session)
            if ent is not None:
                a_name, a_row, a_epoch = ent
                if (self._valid_row(a_row)
                        and self._adapter.replica_of_row(a_row) == a_name
                        and self._adapter.row_epoch(a_row) == a_epoch):
                    eff = self._eff_row(a_row)
                    if eff <= self._cfg.affinity_spill_score:
                        row, sticky = a_row, True
                        self.affinity_hits_total += 1
                        self._inc("router_affinity_hits")
                    else:
                        del self._affinity[session]
                else:
                    del self._affinity[session]
                    self.affinity_stale_total += 1
                    self._inc("router_affinity_stale")
        if row < 0:
            row = self._pick()
            if row < 0:
                return None
        name = self._commit(row, weight)
        if session is not None and not sticky:
            self._remember(session, name, row)
        if rid is not None:
            self._track(rid, row, now)
        return Dispatch(replica=name, row=row, sticky=sticky)

    def _remember(self, session: str, name: str, row: int) -> None:
        if session not in self._affinity \
                and len(self._affinity) >= self._cfg.affinity_capacity:
            self._affinity.pop(next(iter(self._affinity)))
            self.affinity_evictions_total += 1
            self._inc("router_affinity_evictions")
        self._affinity[session] = (name, row,
                                   self._adapter.row_epoch(row))

    def _track(self, rid: str, row: int, now: float) -> None:
        if rid not in self._outstanding \
                and len(self._outstanding) >= self._cfg.max_outstanding:
            self._outstanding.pop(next(iter(self._outstanding)))
        self._outstanding[rid] = [row, self._adapter.row_epoch(row),
                                  now, False]

    # -- tail defense -------------------------------------------------

    def maybe_hedge(self, rid: str, now: float) -> Dispatch | None:
        """Hedged re-dispatch, exactly once per tracked request: fires
        iff the request has waited past ``hedge_after_s`` AND its
        replica looks wedged — dead, draining, restarted (epoch bump:
        the request died with the old incarnation), or score-stalled.
        The re-dispatch excludes the original replica.  Returns the
        hedge Dispatch, or None (not tracked / not due / already
        hedged / nowhere else to go)."""
        ent = self._outstanding.get(rid)
        if ent is None or ent[3]:
            return None
        row, epoch, t0, _ = ent
        if now - t0 < self._cfg.hedge_after_s:
            return None
        stalled = (not self._valid_row(row)
                   or self._adapter.row_epoch(row) != epoch)
        if not stalled:
            stalled = self._eff_row(row) >= self._cfg.hedge_score
        if not stalled:
            return None
        new_row = self._pick(exclude=row)
        if new_row < 0 or new_row == row:
            return None
        ent[3] = True  # exactly-once, even if the hedge also stalls
        name = self._commit(new_row)
        ent[0] = new_row
        ent[1] = self._adapter.row_epoch(new_row)
        self.hedges_total += 1
        self._inc("router_hedges")
        return Dispatch(replica=name, row=new_row, hedged=True)

    def complete(self, rid: str) -> bool:
        """Mark a tracked request finished.  True iff it was still
        outstanding — a second completion for the same rid returns
        False, which is the chaos no-double-completion hook."""
        return self._outstanding.pop(rid, None) is not None

    # -- drain handoff ------------------------------------------------

    def mark_draining(self, replica: str) -> None:
        """Stop routing NEW requests at a replica the scaler advised
        for scale-in; its queue keeps draining (serve.py contract)."""
        self._draining_names.add(replica)
        row = self._adapter.row_of(replica)
        if 0 <= row < self._drain_mask.shape[0]:
            self._drain_mask[row] = True
            if row < self._valid_mask.shape[0]:
                self._valid_mask[row] = False

    def clear_draining(self, replica: str) -> None:
        self._draining_names.discard(replica)
        row = self._adapter.row_of(replica)
        if 0 <= row < self._drain_mask.shape[0]:
            self._drain_mask[row] = False
            if row < self._valid_mask.shape[0]:
                # Restore validity from the live view (the row is back
                # in rotation at its next heap visit or refill).
                scores, live, pool_of_row = self._adapter.router_view()
                ok = bool(live[row]) and (
                    self._pool_filter < 0
                    or int(pool_of_row[row]) == self._pool_filter)
                self._valid_mask[row] = ok

    def absorb_drain(self, receipt: DrainReceipt,
                     now: float) -> list[Dispatch]:
        """Migrate a drained replica's unserved remainder: one typed
        receipt in (the serve.py final-stats contract), one migration
        Dispatch out per unserved request — the caller re-submits
        each to its new replica.  The drained replica leaves the
        draining set (its name may be reused by a future incarnation
        with a fresh epoch)."""
        self.clear_draining(receipt.replica)
        out: list[Dispatch] = []
        for i in range(receipt.unserved):
            row = self._pick()
            if row < 0:
                break
            name = self._commit(row)
            d = Dispatch(replica=name, row=row, migrated=True)
            out.append(d)
            self._track(f"{receipt.replica}/migrate-{i}", row, now)
        self.migrated_total += len(out)
        if out:
            self._inc("router_migrated_requests", float(len(out)))
        return out

    # -- introspection ------------------------------------------------

    @property
    def outstanding(self) -> int:
        return len(self._outstanding)

    @property
    def affinity_size(self) -> int:
        return len(self._affinity)

    def best_row(self) -> int:
        """The row the next affinity-free dispatch would take — the
        oracle hook for the property suite (compare against a naive
        Python argmin over the effective scores)."""
        return self._pick()

    def debug_state(self) -> dict[str, Any]:
        return {
            "dispatches": self.dispatches_total,
            "affinity_size": len(self._affinity),
            "affinity_hits": self.affinity_hits_total,
            "affinity_stale": self.affinity_stale_total,
            "affinity_evictions": self.affinity_evictions_total,
            "hedges": self.hedges_total,
            "migrated": self.migrated_total,
            "outstanding": len(self._outstanding),
            "draining": sorted(self._draining_names),
            "refreshes": self.refreshes_total,
            "candidates": len(self._heap),
        }
