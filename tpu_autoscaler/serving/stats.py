"""Per-tick serving statistics: fixed numpy rings, O(1) snapshots.

The batcher family (``workloads/serving.py``, ``paged.py``,
``spec_serving.py``) owns one :class:`ServingStatsRecorder` each and
calls ``end_tick`` once per engine tick and ``note_*`` from the
admission/preemption/completion bookkeeping it already does.  The
design constraint is the decode hot path: every write is an int
increment or one row-assignment into a preallocated numpy ring — no
per-request Python objects, no device sync (engines pass host-side
mirrors, never ``jax.Array`` reads), no allocation after construction.

``snapshot()`` is the export surface: a frozen dataclass of plain
scalars whose cost is a handful of fixed-width ring reductions —
independent of how many requests or ticks the engine has served.  The
``(epoch, seq)`` pair orders snapshots fleet-wide: ``seq`` is the tick
counter (monotone within a process), ``epoch`` changes when a recorder
is rebuilt (replica restart), which is how the aggregation adapter
(``serving/adapter.py``) tells a counter reset from a stale delivery.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from typing import Any

import numpy as np

#: Tick-series ring width: the throughput/queue window a snapshot
#: summarizes.  Fixed so snapshot cost never grows with uptime.
TICK_WINDOW = 256

#: Completed-request latency ring width (per-request SLO attainment is
#: measured over the last this-many completions).
LATENCY_WINDOW = 512

#: Epoch source: a rebuilt recorder (replica restart) gets a fresh,
#: LARGER epoch, so downstream consumers can tell "counters restarted"
#: (epoch advanced) from "stale snapshot re-delivered" (epoch or seq
#: regressed).  Epochs must stay increasing ACROSS process restarts —
#: a counter alone would restart at 1 and the aggregation adapter
#: would drop the reborn replica's snapshots as stale for its whole
#: catch-up window — so the base is a millisecond timestamp taken at
#: import (fresh per process), with a per-process counter in the low
#: bits for uniqueness inside one process.
_EPOCH_BASE = (time.time_ns() // 1_000_000) << 12
_EPOCHS = itertools.count(1)


def _next_epoch() -> int:
    return _EPOCH_BASE + next(_EPOCHS)


@dataclasses.dataclass(frozen=True)
class ServingSnapshot:
    """One engine's exported state at a tick: cumulative counters (the
    adapter differences them into rates) plus windowed summaries."""

    epoch: int                  # recorder incarnation (restart marker)
    seq: int                    # tick count at snapshot time
    queue_depth: int            # requests queued, not yet admitted
    active: int                 # slots holding live requests
    slots: int                  # concurrent-sequence capacity
    kv_used: int                # KV token-slots (or block tokens) live
    kv_capacity: int            # KV token-slot capacity
    admitted_total: int
    preempted_total: int
    finished_total: int
    slo_ok_total: int           # finished within the latency target
    decode_tokens_total: int
    queue_depth_mean: float     # over the tick window
    tokens_per_tick: float      # over the tick window
    latency_p50_ticks: float    # over the latency window (0 if none)
    latency_p95_ticks: float
    # Queue-wait vs execute split (ISSUE 14 satellite): submitted_tick
    # is preserved across preemption re-queues, so end-to-end latency
    # alone cannot say whether time went to waiting or to serving.
    first_scheduled_total: int = 0   # requests that reached a slot
    queue_wait_ticks_total: int = 0  # submit -> FIRST admission
    requeue_wait_ticks_total: int = 0  # preempt -> re-admission
    queue_wait_p95_ticks: float = 0.0  # over the wait window
    # Request-trace sampler counters (serving/reqtrace.py), riding the
    # same cumulative-counter delta path as the admission counters.
    trace_sampled_total: int = 0
    trace_tail_total: int = 0
    trace_dropped_total: int = 0
    # Latest promoted request-trace exemplar: (trace_id, latency) the
    # aggregation layer forwards into the TSDB so latency series
    # resolve to a concrete sampled trace.  ``exemplar_seq`` is
    # monotone per recorder so the adapter never re-takes one.
    exemplar_trace_id: str | None = None
    exemplar_value: float = 0.0
    exemplar_seq: int = 0

    @property
    def slo_attainment(self) -> float:
        """Lifetime fraction of completions inside the target (1.0
        when nothing finished yet, or no target was configured)."""
        if self.finished_total <= 0:
            return 1.0
        return self.slo_ok_total / self.finished_total

    @property
    def kv_occupancy(self) -> float:
        if self.kv_capacity <= 0:
            return 0.0
        return self.kv_used / self.kv_capacity

    def as_dict(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        d["slo_attainment"] = round(self.slo_attainment, 4)
        d["kv_occupancy"] = round(self.kv_occupancy, 4)
        return d


class ServingStatsRecorder:
    """Fixed-ring tick statistics for one serving engine.

    ``slo_ticks``: completions within this many engine ticks of
    submission count as SLO-attained (None = no target; everything
    attains).  All state is host-side numpy + ints; nothing here ever
    touches a device array.
    """

    def __init__(self, slots: int, slo_ticks: int | None = None,
                 tick_window: int = TICK_WINDOW,
                 latency_window: int = LATENCY_WINDOW) -> None:
        self.epoch = _next_epoch()
        self.slots = int(slots)
        self.slo_ticks = slo_ticks
        self._seq = 0
        # Cumulative counters (plain ints: the cheapest possible write).
        self.admitted_total = 0
        self.preempted_total = 0
        self.finished_total = 0
        self.slo_ok_total = 0
        self._decode_tokens_total = 0
        # Tick rings (per-tick instantaneous series).
        self._w = int(tick_window)
        self._q_ring = np.zeros(self._w, np.int64)
        self._tok_ring = np.zeros(self._w, np.int64)
        # Completed-request latency ring (ticks from submit to done).
        self._lw = int(latency_window)
        self._lat_ring = np.zeros(self._lw, np.int64)
        self._lat_n = 0
        # Queue-wait split (ISSUE 14 satellite): first-schedule +
        # requeue waits, cumulative and windowed.
        self.first_scheduled_total = 0
        self.queue_wait_ticks_total = 0
        self.requeue_wait_ticks_total = 0
        self._wait_ring = np.zeros(self._lw, np.int64)
        self._wait_n = 0
        # Request-trace sampler mirror (serving/reqtrace.py).
        self.trace_sampled_total = 0
        self.trace_tail_total = 0
        self.trace_dropped_total = 0
        self._exemplar: tuple[str, float] | None = None
        self._exemplar_seq = 0
        # Last gauge values (the snapshot's instantaneous fields).
        self._queue_depth = 0
        self._active = 0
        self._kv_used = 0
        self._kv_capacity = 0

    # -- engine-side hooks (all O(1)) -------------------------------------

    def note_admit(self, n: int = 1) -> None:
        self.admitted_total += n

    def note_preempt(self, n: int = 1) -> None:
        self.preempted_total += n

    def note_finish(self, latency_ticks: int) -> None:
        self.finished_total += 1
        if self.slo_ticks is None or latency_ticks <= self.slo_ticks:
            self.slo_ok_total += 1
        self._lat_ring[self._lat_n % self._lw] = latency_ticks
        self._lat_n += 1

    def note_first_scheduled(self, wait_ticks: int) -> None:
        """Request reached a slot for the FIRST time: the submit→admit
        wait lands in the queue-wait split (end-to-end latency minus
        these waits is pure execute time)."""
        self.first_scheduled_total += 1
        self.queue_wait_ticks_total += wait_ticks
        self._wait_ring[self._wait_n % self._lw] = wait_ticks
        self._wait_n += 1

    def note_requeue_wait(self, wait_ticks: int) -> None:
        """A preempted request re-reached a slot: the preempt→re-admit
        wait is attributed separately (it previously lumped invisibly
        into end-to-end latency)."""
        self.requeue_wait_ticks_total += wait_ticks
        self._wait_ring[self._wait_n % self._lw] = wait_ticks
        self._wait_n += 1

    def note_trace(self, tail: bool = False) -> None:
        """One request trace promoted by the sampler."""
        self.trace_sampled_total += 1
        if tail:
            self.trace_tail_total += 1

    def note_trace_drop(self) -> None:
        self.trace_dropped_total += 1

    def note_exemplar(self, trace_id: str, value: float) -> None:
        """Latest promoted-trace exemplar (last wins: the sampler only
        promotes head samples and the slow tail, so during a burn the
        exemplar is a current slow request)."""
        self._exemplar = (trace_id, float(value))
        self._exemplar_seq += 1

    def end_tick(self, *, queue_depth: int, active: int, kv_used: int,
                 kv_capacity: int, decode_tokens_total: int) -> None:
        """Close one engine tick.  ``decode_tokens_total`` is the
        engine's existing cumulative counter — the ring stores the
        per-tick delta so throughput windows need no second counter."""
        i = self._seq % self._w
        self._q_ring[i] = queue_depth
        self._tok_ring[i] = decode_tokens_total - self._decode_tokens_total
        self._decode_tokens_total = decode_tokens_total
        self._queue_depth = queue_depth
        self._active = active
        self._kv_used = kv_used
        self._kv_capacity = kv_capacity
        self._seq += 1

    # -- export -----------------------------------------------------------

    def snapshot(self) -> ServingSnapshot:
        """O(1) export: fixed-width ring reductions + scalar reads."""
        n = min(self._seq, self._w)
        if n:
            q_mean = float(self._q_ring[:n].mean())
            tok_rate = float(self._tok_ring[:n].mean())
        else:
            q_mean = tok_rate = 0.0
        ln = min(self._lat_n, self._lw)
        if ln:
            lat = self._lat_ring[:ln]
            p50 = float(np.percentile(lat, 50))
            p95 = float(np.percentile(lat, 95))
        else:
            p50 = p95 = 0.0
        wn = min(self._wait_n, self._lw)
        wait_p95 = float(np.percentile(self._wait_ring[:wn], 95)) \
            if wn else 0.0
        ex_id, ex_val = (self._exemplar if self._exemplar is not None
                         else (None, 0.0))
        return ServingSnapshot(
            epoch=self.epoch, seq=self._seq,
            queue_depth=self._queue_depth, active=self._active,
            slots=self.slots, kv_used=self._kv_used,
            kv_capacity=self._kv_capacity,
            admitted_total=self.admitted_total,
            preempted_total=self.preempted_total,
            finished_total=self.finished_total,
            slo_ok_total=self.slo_ok_total,
            decode_tokens_total=self._decode_tokens_total,
            queue_depth_mean=q_mean, tokens_per_tick=tok_rate,
            latency_p50_ticks=p50, latency_p95_ticks=p95,
            first_scheduled_total=self.first_scheduled_total,
            queue_wait_ticks_total=self.queue_wait_ticks_total,
            requeue_wait_ticks_total=self.requeue_wait_ticks_total,
            queue_wait_p95_ticks=wait_p95,
            trace_sampled_total=self.trace_sampled_total,
            trace_tail_total=self.trace_tail_total,
            trace_dropped_total=self.trace_dropped_total,
            exemplar_trace_id=ex_id, exemplar_value=ex_val,
            exemplar_seq=self._exemplar_seq)
