"""Request-level data-plane tracing (ISSUE 14): sampled request spans.

The control plane is fully traced (obs/trace.py: one ``scaleup-*``
trace per gang) but the data plane that users actually feel was a
black box: the batcher family exports only aggregate rings
(serving/stats.py), so when ``serving_slo_attainment`` burns nothing
says *which* requests missed or *where* their time went.  This module
is the missing per-request decomposition, built to the same discipline
as :class:`~tpu_autoscaler.serving.stats.ServingStatsRecorder`:

- **zero device syncs** — every hook is called from the host-side
  scheduling bookkeeping the engines already do (submit / admit /
  seeded / preempt / finish);
- **O(1) amortized on the tick path** — while a request is in flight
  the sampler only appends ``(event, tick)`` int pairs to a bounded
  per-request list; span objects are built once, at completion, and
  only for requests that get promoted;
- **bounded memory** — pending tracking, events-per-request and the
  retained trace store (a :class:`FlightRecorder` ring) are all capped
  by construction, so a replica restart or an unbounded queue can
  never grow the sampler.

Sampling policy (docs/OBSERVABILITY.md "Request spans & exemplars"):

- **head sampling** — a deterministic hash of the request id
  (``crc32 % 10000``) against ``sample_rate``: the same request id
  samples identically on every replica and every replay, so offline
  re-runs see the same trace set;
- **always-on tail capture** — any request whose latency exceeds
  ``slo_ticks``, any request that was preempted, and any request lost
  to a drain handoff is promoted regardless of the head decision.
  The slow tail is never invisible, whatever the sampling rate.

A promoted request becomes one ``request-<replica>-<rid>`` trace:

```
request                       submit → finish   [latency, slo_miss, …]
├─ queue_wait                 submit → first admission
├─ prefill                    admission → prompt seeded
├─ decode                     seeded → finish/preempt  (batched ticks
│                             annotated — NEVER a span per token)
├─ preempt_requeue            preempt → re-admission (per requeue)
│   └─ (prefill/decode again after each requeue)
└─ drain_handoff              last progress → drain exit (lost only)
```

``obs.recorder.trace_gaps`` knows this shape (the chaos `serving`
profile asserts gap-free trees for every tail capture), and promotion
feeds the owning stats recorder an **exemplar** ``(trace_id,
latency)`` — the hook that lets ``serving_request_latency_ticks`` p99
on ``/debugz/tsdb`` resolve to a concrete slow-request trace
(obs/tsdb.py exemplars).
"""

from __future__ import annotations

import zlib
from typing import Any

from tpu_autoscaler.obs.recorder import FlightRecorder
from tpu_autoscaler.obs.trace import Tracer

#: Head-sampling hash denominator (rate quantum = 0.01%).
SAMPLE_DENOM = 10_000

#: Default bounds (FlightRecorder-shaped: fixed rings, never grows).
DEFAULT_MAX_TRACES = 256
DEFAULT_MAX_PENDING = 2048
DEFAULT_MAX_EVENTS = 64

#: Event codes in a pending request's compact journal.
_SUBMIT, _ADMIT, _SEEDED, _PREEMPT, _FINISH, _DRAIN = range(6)


def head_sampled(rid: str, sample_rate: float) -> bool:
    """Deterministic head-sampling verdict for one request id: stable
    across replicas, processes and offline replays (the offline
    tail-report must see the same head set the live sampler kept)."""
    if sample_rate <= 0.0:
        return False
    bar = int(sample_rate * SAMPLE_DENOM)
    return zlib.crc32(rid.encode()) % SAMPLE_DENOM < bar


class RequestTraceSampler:
    """Per-replica request-span sampler for one serving engine.

    ``slo_ticks``: latency bound (in the caller's tick/clock units)
    past which a finished request is tail-captured (None = head
    sampling only, plus preempted/lost capture).  ``stats``: the
    engine's ServingStatsRecorder — promotion counters and the latest
    exemplar are mirrored into it so they ride the existing snapshot
    export path.  ``recorder``: span sink; pass a shared
    FlightRecorder (e.g. the controller's) to land request traces in
    the same ``/debugz`` dumps and incident bundles as the
    control-plane traces, or leave None for a private bounded ring.

    Single-threaded like the engines that own it (the batcher tick
    loop); nothing here takes a device sync or an unbounded
    allocation.
    """

    def __init__(self, replica_id: str, *, sample_rate: float = 0.01,
                 slo_ticks: float | None = None,
                 max_traces: int = DEFAULT_MAX_TRACES,
                 max_pending: int = DEFAULT_MAX_PENDING,
                 max_events: int = DEFAULT_MAX_EVENTS,
                 stats: Any = None,
                 recorder: FlightRecorder | None = None) -> None:
        self.replica_id = replica_id
        self.sample_rate = float(sample_rate)
        self.slo_ticks = slo_ticks
        self.max_pending = int(max_pending)
        self.max_events = int(max_events)
        self.stats = stats
        self.recorder = recorder if recorder is not None else \
            FlightRecorder(max_spans=max_traces * 8, max_passes=16)
        # Spans carry explicit engine-tick times; the tracer clock is
        # never consulted (clock=0 would stamp garbage loudly if it
        # ever were).
        self._tracer = Tracer(recorder=self.recorder,
                              clock=lambda: 0.0)
        #: rid -> [head_sampled, preempts, [(event, tick), ...]]
        self._pending: dict[str, list] = {}
        self._cohort_seq = 0
        # Lifetime counters (mirrored into ``stats`` on change).
        self.sampled_total = 0        # promoted traces (head or tail)
        self.tail_captured_total = 0  # promoted by the tail rules
        self.dropped_total = 0        # pending/event-cap overflow
        self.rerouted_total = 0       # forwarded to another replica

    # -- engine hooks (all O(1) appends) ------------------------------

    def note_submit(self, rid: str, tick: float) -> None:
        """Request entered the queue.  Over ``max_pending`` the OLDEST
        tracked request is dropped (counted): a runaway queue degrades
        sampling coverage, never sampler memory."""
        if rid in self._pending:
            return
        if len(self._pending) >= self.max_pending:
            victim = next(iter(self._pending))
            del self._pending[victim]
            self._drop()
        self._pending[rid] = [head_sampled(rid, self.sample_rate), 0,
                              [(_SUBMIT, tick)]]

    def note_admit(self, rid: str, tick: float) -> None:
        self._event(rid, _ADMIT, tick)

    def note_seeded(self, rid: str, tick: float) -> None:
        """Prompt fully prefilled; first token sampled."""
        self._event(rid, _SEEDED, tick)

    def note_preempt(self, rid: str, tick: float) -> None:
        ent = self._pending.get(rid)
        if ent is not None:
            ent[1] += 1
        self._event(rid, _PREEMPT, tick)

    def note_forward(self, rid: str) -> None:
        """The request re-routed to another replica (drain handoff of
        a QUEUED request — it is not lost; the receiving replica's
        sampler owns it from its original submit time)."""
        if self._pending.pop(rid, None) is not None:
            self.rerouted_total += 1

    def note_finish(self, rid: str, tick: float, *, tokens: int = 0,
                    attrs: dict[str, Any] | None = None) -> str | None:
        """Request completed; returns the trace id iff promoted."""
        return self._close(rid, _FINISH, tick, tokens=tokens,
                           attrs=attrs)

    def note_drain_lost(self, rid: str, tick: float) -> str | None:
        """Request still queued when the engine exited its drain: the
        caller re-dispatches it elsewhere, but THIS replica's story
        ends in a drain handoff — always captured (a lost request is
        tail by definition)."""
        return self._close(rid, _DRAIN, tick)

    def note_cohort(self, rid: str, *, arrival: float, finish: float,
                    n: int = 1, exec_time: float = 0.0,
                    head: bool | None = None,
                    attrs: dict[str, Any] | None = None) -> str | None:
        """Whole-lifecycle convenience for queueing-model replicas
        (serving/replay.py): one call per scored completion cohort —
        submit at ``arrival``, execution over the trailing
        ``exec_time``, finish at ``finish``.  ``rid`` keys the head-
        sampling hash (one verdict per cohort however it splits); the
        minted trace id is made unique per call.

        Unlike the event-driven engine path (where tail is unknown
        until completion, so every request journals), the whole
        lifecycle is known HERE — an unpromoted cohort costs one hash
        and one compare, nothing else (the traced-vs-untraced bench
        gate rides on this fast path).  ``head``: pass the cohort's
        precomputed ``head_sampled`` verdict to skip even the hash
        (callers that score one cohort over many completion chunks
        hash once at assignment)."""
        latency = finish - arrival
        if head is None:
            head = head_sampled(rid, self.sample_rate)
        slo_miss = (self.slo_ticks is not None
                    and latency > self.slo_ticks)
        if not (head or slo_miss):
            return None
        self._cohort_seq += 1
        unique = f"{rid}.{self._cohort_seq}"
        exec_start = max(arrival, finish - exec_time)
        events = [(_SUBMIT, arrival), (_ADMIT, exec_start),
                  (_SEEDED, exec_start), (_FINISH, finish)]
        return self._emit(unique, events, latency=latency,
                          lost=False, slo_miss=slo_miss, preempts=0,
                          head=head, tail=slo_miss, tokens=0,
                          truncated=False, end=finish,
                          attrs={"n": n, **(attrs or {})})

    # -- internals ----------------------------------------------------

    def _drop(self) -> None:
        self.dropped_total += 1
        if self.stats is not None:
            self.stats.note_trace_drop()

    def _event(self, rid: str, kind: int, tick: float) -> None:
        ent = self._pending.get(rid)
        if ent is None:
            return
        events = ent[2]
        if len(events) >= self.max_events:
            # Journal full: keep the entry (the close still promotes
            # and emits a truncated trace) but stop appending.
            return
        events.append((kind, tick))

    def _close(self, rid: str, kind: int, tick: float, *,
               tokens: int = 0,
               attrs: dict[str, Any] | None = None) -> str | None:
        ent = self._pending.pop(rid, None)
        if ent is None:
            return None
        head, preempts, events = ent
        truncated = len(events) >= self.max_events
        if not truncated:
            events.append((kind, tick))
        submit = events[0][1]
        latency = tick - submit
        lost = kind == _DRAIN
        slo_miss = (self.slo_ticks is not None
                    and latency > self.slo_ticks)
        tail = slo_miss or lost or preempts > 0
        if not (head or tail):
            return None
        return self._emit(rid, events, latency=latency, lost=lost,
                          slo_miss=slo_miss, preempts=preempts,
                          head=head, tail=tail, tokens=tokens,
                          truncated=truncated, end=tick,
                          attrs=attrs)

    def _emit(self, rid: str, events: list, *, latency: float,
              lost: bool, slo_miss: bool, preempts: int, head: bool,
              tail: bool, tokens: int, truncated: bool, end: float,
              attrs: dict[str, Any] | None) -> str:
        """Build the span tree for one promoted request — the only
        non-O(1) step, bounded by ``max_events`` and paid once per
        PROMOTED request, never on the tick path."""
        trace_id = f"request-{self.replica_id}-{rid}"
        submit = events[0][1]
        sampled = ("head+tail" if head and tail
                   else "tail" if tail else "head")
        root_attrs: dict[str, Any] = {
            "rid": rid, "replica": self.replica_id,
            "latency_ticks": latency, "slo_miss": slo_miss,
            "preemptions": preempts, "sampled": sampled,
        }
        if tokens:
            root_attrs["tokens"] = tokens
        if lost:
            root_attrs["lost"] = True
        if truncated:
            root_attrs["truncated"] = True
        if attrs:
            root_attrs.update(attrs)
        root = self._tracer.start("request", trace_id=trace_id,
                                  parent=None, t=submit,
                                  attrs=root_attrs)
        if not truncated:
            self._child_spans(root, events, end)
        self._tracer.end(root, t=end)
        self.sampled_total += 1
        if tail:
            self.tail_captured_total += 1
        if self.stats is not None:
            self.stats.note_trace(tail=tail)
            self.stats.note_exemplar(trace_id, float(latency))
        return trace_id

    def _child_spans(self, root, events: list, end: float) -> None:
        """Phase children from the event journal.  Decode is one span
        per (seeded → preempt/finish) window with the batched tick
        count as an attr — never per-token."""
        rec = self._tracer.record
        wait_from = events[0][1]          # submit (or last preempt)
        wait_kind = "first_schedule"
        admit_at: float | None = None
        seeded_at: float | None = None
        progress_at = events[0][1]
        for kind, t in events[1:]:
            if kind == _ADMIT:
                rec("queue_wait" if wait_kind == "first_schedule"
                    else "preempt_requeue",
                    start=wait_from, end=t, parent=root,
                    attrs={"wait_ticks": t - wait_from})
                admit_at = t
                progress_at = t
            elif kind == _SEEDED:
                rec("prefill",
                    start=admit_at if admit_at is not None else t,
                    end=t, parent=root)
                seeded_at = t
                progress_at = t
            elif kind in (_PREEMPT, _FINISH):
                if seeded_at is not None:
                    rec("decode", start=seeded_at, end=t, parent=root,
                        attrs={"ticks": t - seeded_at})
                    seeded_at = None
                if kind == _PREEMPT:
                    wait_from = t
                    wait_kind = "requeue"
                    admit_at = None
                progress_at = t
            elif kind == _DRAIN:
                rec("drain_handoff", start=progress_at, end=t,
                    parent=root,
                    attrs={"stalled_ticks": t - progress_at})

    # -- export -------------------------------------------------------

    @property
    def pending(self) -> int:
        return len(self._pending)

    def dump(self) -> dict[str, Any]:
        """The retained request traces, FlightRecorder dump shape —
        ``trace_gaps`` and the render helpers consume it directly."""
        return self.recorder.dump()

    def debug_state(self) -> dict[str, Any]:
        return {
            "replica": self.replica_id,
            "sample_rate": self.sample_rate,
            "slo_ticks": self.slo_ticks,
            "pending": len(self._pending),
            "sampled_total": self.sampled_total,
            "tail_captured_total": self.tail_captured_total,
            "dropped_total": self.dropped_total,
            "rerouted_total": self.rerouted_total,
        }
