"""Metrics adapter: fold replica snapshots into per-pool demand signals.

The fleet problem: thousands of serving replicas each export a
:class:`~tpu_autoscaler.serving.stats.ServingSnapshot` every few
seconds, and the reconcile pass needs per-pool / per-accelerator-class
aggregates — queue depth, token throughput, completion + SLO rates, KV
occupancy — WITHOUT scanning every replica every pass.  This is the
informer's CapacityView problem wearing serving clothes, so the same
design applies (k8s/informer.py):

- ``ingest`` is the watch-delta analog: it stores the replica's latest
  snapshot row into preallocated numpy arrays and marks the row dirty —
  O(1), called from whatever transport delivers snapshots;
- ``fold`` is the per-pass ``CapacityView.refresh``: it differences the
  dirty rows' cumulative counters into rates and replaces exactly those
  rows' contributions in the per-pool running sums — O(churn),
  vectorized (one numpy pass over the dirty set, however large the
  fleet);
- ``rebuild``/``drift`` are the relist analog: a from-scratch re-sum
  for verification and periodic float-drift repair.

Fault tolerance is the adapter's job, not the replicas' (ISSUE 9 chaos
profile): a restarted replica re-registers with a fresh snapshot
``epoch`` and its counters restart from zero — the fold treats the new
totals as the delta (``serving_counter_resets``).  A raw backwards
counter with an unchanged epoch (buggy exporter) clamps the same way:
**rates are non-negative by construction**, the invariant the chaos
corpus asserts per step.  Stale or out-of-order deliveries (same epoch,
non-advancing seq) are dropped and counted
(``serving_stale_snapshots``).

Threading: single-consumer like CapacityView — ingest and fold run on
the same thread (the reconcile loop, a bench, or the chaos driver).

The same dirty-fold also refreshes a per-replica **dispatch score**
column (ISSUE 18): the request router's hot path is a masked argmin
over this precomputed column (serving/router.py), so routing pays
O(churn) at fold time and ~O(1) per dispatch — never a per-request
Python scan over the fleet.  ``dispatch_scores`` is the score algebra
(docs/SERVING.md "Request routing"); ``rebuild_scores`` is its
from-scratch oracle, checked by the router property suite the same
way ``rebuild``/``drift`` check the pool sums.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Iterable

import numpy as np

from tpu_autoscaler.serving.stats import ServingSnapshot

#: Gauge columns copied straight from the latest snapshot.
_G_QUEUE, _G_ACTIVE, _G_SLOTS, _G_KV_USED, _G_KV_CAP = range(5)
_N_GAUGE = 5

#: Cumulative-counter columns differenced into rates.  The trace
#: columns (ISSUE 14) ride the same delta path: replica-side sampler
#: promotions become fleet rates with restart/reset handling for free.
(_C_FINISHED, _C_SLO_OK, _C_TOKENS, _C_ADMITTED, _C_PREEMPTED,
 _C_TRACE_SAMPLED, _C_TRACE_TAIL, _C_TRACE_DROPPED) = range(8)
_N_TOTAL = 8

#: Per-pool contribution vector: the gauges, then the rate EWMAs.
_N_CONTRIB = _N_GAUGE + _N_TOTAL

#: Rate-EWMA smoothing (per ingest of each replica).
_RATE_ALPHA = 0.5

#: Folds between automatic drift repairs (the running sums are floats
#: maintained by add/subtract; a periodic full re-sum bounds the error
#: at amortized O(replicas / period) per fold).
_REPAIR_PERIOD = 256

#: Dispatch-score algebra weights (ISSUE 18; docs/SERVING.md "Request
#: routing").  The score is a COST — lower routes sooner:
#:
#:   score = backlog/slots  +  KV_WEIGHT * kv_used/kv_capacity
#:           + STALL_PENALTY   iff busy and finishing nothing
#:
#: The load term is the replica's queueing delay proxy (requests per
#: service slot); the KV term breaks load ties toward replicas with
#: free cache blocks so long prompts don't land on a full pager; the
#: stall penalty pushes wedged replicas (slots occupied, completion
#: rate ~ 0) to the back of the line before the hedger even fires.
SCORE_KV_WEIGHT = 0.5
SCORE_STALL_PENALTY = 4.0
#: Completion rate (req/s, EWMA) at or below which a busy replica
#: counts as stalled for the score penalty.
SCORE_STALL_RATE = 1e-3

#: The histogram family request-latency exemplars attach to (ISSUE
#: 14): the reconciler observes the taken exemplar's value into this
#: family the same pass it hands the (trace_id, value) pair to the
#: TSDB, so the exemplar is always a member of the family's
#: observations.
EXEMPLAR_FAMILY = "serving_request_latency_ticks"


@dataclasses.dataclass(frozen=True)
class PoolSignal:
    """One pool's aggregated live demand signal (one fold's output)."""

    pool: str
    accel_class: str
    shape_name: str
    replicas: int
    queue_depth: float
    active: float
    slots: float
    kv_used: float
    kv_capacity: float
    finished_per_s: float
    slo_ok_per_s: float
    tokens_per_s: float
    admitted_per_s: float
    preempted_per_s: float
    trace_sampled_per_s: float = 0.0
    trace_tail_per_s: float = 0.0
    trace_dropped_per_s: float = 0.0

    @property
    def slo_attainment(self) -> float:
        if self.finished_per_s <= 0.0:
            return 1.0
        return min(1.0, self.slo_ok_per_s / self.finished_per_s)

    @property
    def utilization(self) -> float:
        if self.slots <= 0.0:
            return 0.0
        return self.active / self.slots

    @property
    def backlog(self) -> float:
        """Demand in request-slots: queued plus in-flight."""
        return self.queue_depth + self.active

    def as_dict(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        d["slo_attainment"] = round(self.slo_attainment, 4)
        d["utilization"] = round(self.utilization, 4)
        return d


def _snapshot_rows(snap: ServingSnapshot) -> tuple[list[float],
                                                   list[float]]:
    gauges = [float(snap.queue_depth), float(snap.active),
              float(snap.slots), float(snap.kv_used),
              float(snap.kv_capacity)]
    totals = [float(snap.finished_total), float(snap.slo_ok_total),
              float(snap.decode_tokens_total),
              float(snap.admitted_total), float(snap.preempted_total),
              float(snap.trace_sampled_total),
              float(snap.trace_tail_total),
              float(snap.trace_dropped_total)]
    return gauges, totals


def dispatch_scores(gauges: np.ndarray, rates: np.ndarray) -> np.ndarray:
    """Vectorized dispatch-score algebra over gauge/rate rows (the
    last axis is the column axis).  Pure function of the row data —
    the fold applies it to dirty rows; ``rebuild_scores`` and the
    router property suite apply it from scratch as the oracle."""
    slots = np.maximum(gauges[..., _G_SLOTS], 1.0)
    load = (gauges[..., _G_QUEUE] + gauges[..., _G_ACTIVE]) / slots
    kv = gauges[..., _G_KV_USED] / np.maximum(gauges[..., _G_KV_CAP],
                                              1.0)
    busy = gauges[..., _G_ACTIVE] >= 0.5 * slots
    stalled = busy & (rates[..., _C_FINISHED] <= SCORE_STALL_RATE)
    return (load + SCORE_KV_WEIGHT * kv
            + np.where(stalled, SCORE_STALL_PENALTY, 0.0))


class ServingMetricsAdapter:
    """Incremental per-pool folds over a fleet of replica snapshots."""

    def __init__(self, metrics: Any = None,
                 rate_alpha: float = _RATE_ALPHA,
                 repair_period: int = _REPAIR_PERIOD,
                 capacity: int = 64) -> None:
        self._metrics = metrics
        self._alpha = rate_alpha
        self._repair_period = repair_period
        # Replica registry: id -> row index; freed rows are recycled.
        self._rows: dict[str, int] = {}
        self._free: list[int] = []
        cap = max(4, capacity)
        self._gauges = np.zeros((cap, _N_GAUGE))
        self._tot_new = np.zeros((cap, _N_TOTAL))
        self._tot_old = np.zeros((cap, _N_TOTAL))
        self._t_new = np.zeros(cap)
        self._t_old = np.zeros(cap)
        self._rates = np.zeros((cap, _N_TOTAL))
        self._epoch = np.zeros(cap, np.int64)
        self._seq = np.full(cap, -1, np.int64)
        self._pool_of_row = np.zeros(cap, np.int64)
        self._contrib = np.zeros((cap, _N_CONTRIB))
        self._live = np.zeros(cap, bool)
        # Router-facing columns (ISSUE 18): the dispatch-score cost
        # per row (+inf on dead rows so an unmasked argmin can never
        # resurrect one) and the row -> replica-id reverse map the
        # argmin result resolves through.
        self._score = np.full(cap, np.inf)
        self._name_of_row: list[str | None] = [None] * cap
        # Fold stamp per row: which fold last re-priced it.  The
        # router clears its local in-flight delta for a row ONLY once
        # that row's own snapshot re-folded — clearing on stale rows
        # re-creates the join-the-shortest-stale-queue herd.
        self._fold_stamp = np.zeros(cap, np.int64)
        self._dirty: set[int] = set()
        # Pool registry (pools are never recycled; fleets have few).
        self._pool_idx: dict[str, int] = {}
        self._pool_meta: dict[str, tuple[str, str]] = {}  # accel, shape
        self._pool_sums = np.zeros((0, _N_CONTRIB))
        self._pool_replicas: list[int] = []
        self._folds = 0
        # Exemplar plumbing (ISSUE 14): per-replica last-taken
        # exemplar seq (so a re-delivered snapshot never re-takes the
        # same exemplar) and the pending per-family best — drained
        # once per pass by ``take_exemplars``.  A plain Python list on
        # purpose: the ingest fast path reads one element per
        # delivery, and a list index is ~4x cheaper than a numpy
        # scalar read (the traced-vs-untraced ingest gate rides on
        # it).  Trace ids are strings and live beside the rows.
        self._exemplar_seq: list[int] = [0] * cap
        self._pending_exemplars: dict[str, tuple[str, float]] = {}
        # Control-plane profiler hook (ISSUE 20): bound by the
        # Controller so fold cost nests under the serving phase even
        # when the scaler drives the fold from inside advise().
        self.profiler: Any = None

    # -- metrics ----------------------------------------------------------

    def _inc(self, name: str, by: float = 1.0) -> None:
        if self._metrics is not None:
            self._metrics.inc(name, by)

    # -- registry ---------------------------------------------------------

    def _grow(self) -> None:
        cap = self._gauges.shape[0]
        new = cap * 2

        def grow2(a):
            out = np.zeros((new,) + a.shape[1:], a.dtype)
            out[:cap] = a
            return out

        self._gauges = grow2(self._gauges)
        self._tot_new = grow2(self._tot_new)
        self._tot_old = grow2(self._tot_old)
        self._t_new = grow2(self._t_new)
        self._t_old = grow2(self._t_old)
        self._rates = grow2(self._rates)
        self._epoch = grow2(self._epoch)
        seq = np.full(new, -1, np.int64)
        seq[:cap] = self._seq
        self._seq = seq
        self._pool_of_row = grow2(self._pool_of_row)
        self._contrib = grow2(self._contrib)
        self._live = grow2(self._live)
        score = np.full(new, np.inf)
        score[:cap] = self._score
        self._score = score
        self._fold_stamp = grow2(self._fold_stamp)
        self._name_of_row.extend([None] * (new - cap))
        self._exemplar_seq.extend([0] * (new - cap))

    def _pool(self, pool: str, accel_class: str, shape_name: str) -> int:
        idx = self._pool_idx.get(pool)
        if idx is None:
            idx = len(self._pool_idx)
            self._pool_idx[pool] = idx
            self._pool_meta[pool] = (accel_class, shape_name)
            self._pool_sums = np.vstack(
                [self._pool_sums, np.zeros((1, _N_CONTRIB))])
            self._pool_replicas.append(0)
        return idx

    @property
    def replicas(self) -> int:
        return len(self._rows)

    @property
    def pools(self) -> list[str]:
        """Every pool ever registered — including ones whose replica
        census has dropped to zero (they vanish from ``signals()``
        but must stay reachable for scale-from-zero decisions)."""
        return list(self._pool_idx)

    def pool_meta(self, pool: str) -> tuple[str, str]:
        """(accel_class, shape_name) a pool registered with."""
        return self._pool_meta[pool]

    # -- the delta path ---------------------------------------------------

    def ingest(self, replica_id: str, pool: str, accel_class: str,
               shape_name: str, snap: ServingSnapshot,
               now: float) -> bool:
        """Store one replica's snapshot; True iff accepted.  O(1):
        one row write + a dirty mark — the fold does the math."""
        row = self._rows.get(replica_id)
        gauges, totals = _snapshot_rows(snap)
        if row is None:
            if self._free:
                row = self._free.pop()
            else:
                row = len(self._rows)
                while row >= self._gauges.shape[0]:
                    self._grow()
            self._rows[replica_id] = row
            pidx = self._pool(pool, accel_class, shape_name)
            self._pool_of_row[row] = pidx
            self._pool_replicas[pidx] += 1
            self._live[row] = True
            self._contrib[row] = 0.0
            self._rates[row] = 0.0
            # A fresh replica dispatches at cost zero until its first
            # fold prices it — it is empty, so that IS its score.
            self._score[row] = 0.0
            self._name_of_row[row] = replica_id
            # First sight: no history, so rates start at zero (the
            # totals become the baseline, not a burst).
            self._tot_old[row] = totals
            self._t_old[row] = now
            self._epoch[row] = snap.epoch
            self._seq[row] = -1
            self._exemplar_seq[row] = 0
        elif snap.epoch < self._epoch[row] or (
                snap.epoch == self._epoch[row]
                and snap.seq <= self._seq[row]):
            # Stale or duplicate delivery: the fleet transport may
            # reorder — including a PRE-restart snapshot arriving
            # after the restart's (epochs are increasing, so an older
            # epoch is always stale; counting it as a fresh restart
            # would re-ingest the dead incarnation's lifetime totals
            # as one giant delta).
            self._inc("serving_stale_snapshots")
            return False
        elif snap.epoch > self._epoch[row]:
            # Replica restarted: counters restarted from zero.  The new
            # totals ARE the delta since the restart.
            self._inc("serving_counter_resets")
            self._epoch[row] = snap.epoch
            self._tot_old[row] = 0.0
            # A rebuilt recorder's exemplar_seq restarts too: the old
            # high-water mark would suppress every post-restart
            # exemplar forever.
            self._exemplar_seq[row] = 0
        self._seq[row] = snap.seq
        self._gauges[row] = gauges
        self._tot_new[row] = totals
        self._t_new[row] = now
        self._dirty.add(row)
        if snap.exemplar_seq > self._exemplar_seq[row] \
                and snap.exemplar_trace_id is not None:
            # New promoted-trace exemplar from this replica: keep the
            # fleet's SLOWEST candidate this pass (p99 links to a slow
            # trace, not an arbitrary one).  O(1), no per-pass scan —
            # and the seq compare comes FIRST, so untraced snapshots
            # (seq 0) and re-deliveries reject on one int compare.
            self._exemplar_seq[row] = snap.exemplar_seq
            cur = self._pending_exemplars.get(EXEMPLAR_FAMILY)
            if cur is None or snap.exemplar_value >= cur[1]:
                self._pending_exemplars[EXEMPLAR_FAMILY] = (
                    snap.exemplar_trace_id,
                    float(snap.exemplar_value))
        self._inc("serving_snapshots_ingested")
        return True

    def take_exemplars(self) -> dict[str, tuple[str, float]]:
        """Drain this pass's pending exemplars — at most one
        (trace_id, value) per family.  The reconciler's ``_obs_pass``
        observes each value into its histogram family and forwards
        the pair to ``TimeSeriesDB.ingest``."""
        out = self._pending_exemplars
        self._pending_exemplars = {}
        return out

    def remove(self, replica_id: str) -> None:
        """Forget a replica (scale-in / death): its contribution leaves
        the pool sums immediately."""
        row = self._rows.pop(replica_id, None)
        if row is None:
            return
        pidx = int(self._pool_of_row[row])
        self._pool_sums[pidx] -= self._contrib[row]
        self._pool_replicas[pidx] -= 1
        self._live[row] = False
        self._dirty.discard(row)
        self._seq[row] = -1
        self._contrib[row] = 0.0
        self._score[row] = np.inf
        self._name_of_row[row] = None
        self._free.append(row)

    def fold(self, now: float) -> int:
        """Fold pending churn into the pool sums — one vectorized pass
        over the dirty rows, O(churn).  Returns rows folded."""
        prof = self.profiler
        if prof is not None:
            with prof.phase("adapter_fold"):
                return self._fold_impl(now)
        return self._fold_impl(now)

    def _fold_impl(self, now: float) -> int:
        n = len(self._dirty)
        if n:
            idx = np.fromiter(self._dirty, np.int64, len(self._dirty))
            self._dirty.clear()
            dt = self._t_new[idx] - self._t_old[idx]
            dt = np.maximum(dt, 1e-9)
            delta = self._tot_new[idx] - self._tot_old[idx]
            # Counter reset with an unchanged epoch (buggy exporter):
            # clamp to "the new total is the delta" — NEVER negative.
            resets = delta < 0.0
            if resets.any():
                self._inc("serving_counter_resets",
                          float(resets.any(axis=1).sum()))
                delta = np.where(resets, self._tot_new[idx], delta)
            inst = delta / dt[:, None]
            a = self._alpha
            self._rates[idx] = a * inst + (1 - a) * self._rates[idx]
            contrib = np.concatenate(
                [self._gauges[idx], self._rates[idx]], axis=1)
            np.add.at(self._pool_sums, self._pool_of_row[idx],
                      contrib - self._contrib[idx])
            self._contrib[idx] = contrib
            self._tot_old[idx] = self._tot_new[idx]
            self._t_old[idx] = self._t_new[idx]
            # Router score refresh rides the same dirty set (ISSUE
            # 18): one more vectorized expression over exactly the
            # rows whose signals changed — O(churn), never O(fleet).
            self._score[idx] = dispatch_scores(self._gauges[idx],
                                               self._rates[idx])
            self._fold_stamp[idx] = self._folds + 1
        self._folds += 1
        if self._repair_period and self._folds % self._repair_period == 0:
            self._repair()
        return n

    def _repair(self) -> None:
        """Re-sum the pool totals from the live contributions (bounds
        add/subtract float drift; amortized O(replicas/period))."""
        sums = np.zeros_like(self._pool_sums)
        live = np.flatnonzero(self._live)
        if live.size:
            np.add.at(sums, self._pool_of_row[live], self._contrib[live])
        self._pool_sums = sums

    # -- reads ------------------------------------------------------------

    def signals(self) -> dict[str, PoolSignal]:
        """Per-pool aggregates from the running sums — O(pools)."""
        out: dict[str, PoolSignal] = {}
        for pool, pidx in self._pool_idx.items():
            if self._pool_replicas[pidx] <= 0:
                continue
            s = self._pool_sums[pidx]
            accel, shape = self._pool_meta[pool]
            out[pool] = PoolSignal(
                pool=pool, accel_class=accel, shape_name=shape,
                replicas=self._pool_replicas[pidx],
                queue_depth=max(0.0, float(s[_G_QUEUE])),
                active=max(0.0, float(s[_G_ACTIVE])),
                slots=max(0.0, float(s[_G_SLOTS])),
                kv_used=max(0.0, float(s[_G_KV_USED])),
                kv_capacity=max(0.0, float(s[_G_KV_CAP])),
                finished_per_s=max(0.0, float(
                    s[_N_GAUGE + _C_FINISHED])),
                slo_ok_per_s=max(0.0, float(s[_N_GAUGE + _C_SLO_OK])),
                tokens_per_s=max(0.0, float(s[_N_GAUGE + _C_TOKENS])),
                admitted_per_s=max(0.0, float(
                    s[_N_GAUGE + _C_ADMITTED])),
                preempted_per_s=max(0.0, float(
                    s[_N_GAUGE + _C_PREEMPTED])),
                trace_sampled_per_s=max(0.0, float(
                    s[_N_GAUGE + _C_TRACE_SAMPLED])),
                trace_tail_per_s=max(0.0, float(
                    s[_N_GAUGE + _C_TRACE_TAIL])),
                trace_dropped_per_s=max(0.0, float(
                    s[_N_GAUGE + _C_TRACE_DROPPED])))
        return out

    def burning_pools(self, floor: float = 0.95) -> set[str]:
        """Pools whose live SLO attainment sits below ``floor`` — the
        repacker's do-not-touch list (ISSUE 12): a pool already
        missing its SLO needs its replicas where they are; a drain
        for cost savings would turn a burn into an outage."""
        return {pool for pool, sig in self.signals().items()
                if sig.slo_attainment < floor}

    def fleet_summary(self) -> dict[str, Any]:
        """O(pools) serving census for the cost surfaces (ISSUE 11):
        ``/debugz/cost`` and the cost-report CLI show the serving
        share of the bill next to its live context — replicas,
        utilization, SLO attainment per pool."""
        out: dict[str, Any] = {"replicas": self.replicas, "pools": {}}
        for pool, sig in self.signals().items():
            out["pools"][pool] = {
                "replicas": sig.replicas,
                "shape": sig.shape_name,
                "utilization": round(sig.utilization, 4),
                "slo_attainment": round(sig.slo_attainment, 4),
            }
        return out

    # -- router views (ISSUE 18) ------------------------------------------

    def router_view(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(scores, live, pool_of_row) — live references into the row
        arrays for :class:`~tpu_autoscaler.serving.router.RouterCore`.
        The router reads them between folds (same single-consumer
        thread) and must never write them; its own per-dispatch
        in-flight deltas live in router-owned columns."""
        return self._score, self._live, self._pool_of_row

    def name_column(self) -> list[str | None]:
        """The row -> replica-id column, as a live reference (mutated
        in place on ingest/retire; replaced only by :meth:`_grow`).
        Same read-only contract as :meth:`router_view` — the router
        caches it so the per-decision commit is one list index, not a
        method call."""
        return self._name_of_row

    def drain_credit(self, now: float) -> np.ndarray:
        """Expected score drain since each row's last folded snapshot:
        ``finished_rate * age / slots`` — the optimistic estimate of
        how much of the reported (queue+active)/slots load the replica
        has served in the meantime.  Snapshots age up to a full report
        period while service times are typically shorter, so a score
        column read raw makes busy-reported-but-since-drained replicas
        look loaded for the whole period; routers subtract this credit
        to kill the resulting starve/slam oscillation.  Fresh array,
        O(fleet) vectorized."""
        age = np.maximum(now - self._t_old, 0.0)
        slots = np.maximum(self._gauges[:, _G_SLOTS], 1.0)
        return self._rates[:, _C_FINISHED] * age / slots

    def row_of(self, replica_id: str) -> int:
        """Row index of a registered replica, or -1."""
        return self._rows.get(replica_id, -1)

    def replica_of_row(self, row: int) -> str | None:
        """Replica id currently occupying ``row`` (None if freed)."""
        if 0 <= row < len(self._name_of_row):
            return self._name_of_row[row]
        return None

    def row_epoch(self, row: int) -> int:
        """The recorder epoch last ingested for ``row`` — the router's
        affinity table keys staleness off this (an epoch bump means
        the replica restarted and its KV cache is gone)."""
        return int(self._epoch[row])

    def pool_index(self, pool: str) -> int:
        """Dense pool index for router pool-masking, or -1."""
        return self._pool_idx.get(pool, -1)

    def capacity(self) -> int:
        """Current row-array capacity (routers size their delta
        columns to this and regrow when it changes)."""
        return int(self._gauges.shape[0])

    @property
    def fold_stamps(self) -> np.ndarray:
        """Per-row fold stamps (see ``_fold_stamp``) — read-only."""
        return self._fold_stamp

    @property
    def folds_done(self) -> int:
        return self._folds

    # -- verification (tests, chaos, bench baseline) ----------------------

    def rebuild(self) -> dict[str, list[float]]:
        """From-scratch pool sums (math.fsum over live contributions) —
        the property-suite oracle the incremental path is checked
        against (tests/test_serving_adapter.py, chaos serving)."""
        out: dict[str, list[float]] = {}
        rows_by_pool: dict[int, list[int]] = {}
        for row in (self._rows.values()):
            rows_by_pool.setdefault(
                int(self._pool_of_row[row]), []).append(row)
        for pool, pidx in self._pool_idx.items():
            rows = rows_by_pool.get(pidx, [])
            out[pool] = [
                math.fsum(float(self._contrib[r, c]) for r in rows)
                for c in range(_N_CONTRIB)]
        return out

    def rebuild_scores(self) -> np.ndarray:
        """From-scratch dispatch scores for every live row (dead rows
        +inf) — the router property suite's oracle for the fold-time
        incremental refresh."""
        out = np.full(self._score.shape[0], np.inf)
        live = np.flatnonzero(self._live)
        if live.size:
            out[live] = dispatch_scores(self._gauges[live],
                                        self._rates[live])
        return out

    def drift(self) -> float:
        """Max |incremental - rebuilt| over every pool sum (the
        consistency invariant; bounded by the periodic repair)."""
        rebuilt = self.rebuild()
        worst = 0.0
        for pool, pidx in self._pool_idx.items():
            diff = np.abs(self._pool_sums[pidx]
                          - np.asarray(rebuilt[pool]))
            if diff.size:
                worst = max(worst, float(diff.max()))
        return worst


def scan_aggregate(snapshots: Iterable[tuple[str, str, str, str,
                                             ServingSnapshot, float,
                                             float]]
                   ) -> dict[str, dict[str, Any]]:
    """The naive per-pass baseline the fold replaces: a Python loop
    over EVERY replica's latest snapshot, re-deriving each pool's
    aggregates from scratch.  ``snapshots`` yields (replica, pool,
    accel, shape, snapshot, prev_finished_like_window_seconds, dt) —
    the bench drives both paths with the same data and gates the
    fold's advantage (>= 10x at fleet scale)."""
    out: dict[str, dict[str, float]] = {}
    for (_rid, pool, accel, shape, snap, prev_tokens, dt) in snapshots:
        agg = out.setdefault(pool, {
            "accel_class": accel, "shape_name": shape, "replicas": 0.0,
            "queue_depth": 0.0, "active": 0.0, "slots": 0.0,
            "kv_used": 0.0, "kv_capacity": 0.0, "tokens_per_s": 0.0,
            "finished_total": 0.0, "slo_ok_total": 0.0})
        agg["replicas"] += 1
        agg["queue_depth"] += snap.queue_depth
        agg["active"] += snap.active
        agg["slots"] += snap.slots
        agg["kv_used"] += snap.kv_used
        agg["kv_capacity"] += snap.kv_capacity
        agg["tokens_per_s"] += max(
            0.0, (snap.decode_tokens_total - prev_tokens)
            / max(dt, 1e-9))
        agg["finished_total"] += snap.finished_total
        agg["slo_ok_total"] += snap.slo_ok_total
    return out
