"""Typed drain receipt: the serve.py scale-in handoff contract.

The drain contract (docs/SERVING.md): scale-in never reclaims a
serving replica's slice out from under it — the platform stops
admission, the replica finishes its queue, and its LAST stdout line is
one machine-readable ``final_stats`` JSON object.  Until ISSUE 18 that
object was an untyped dict three consumers re-parsed by hand — serve.py
emitting it, the reclaim tests asserting ``unserved == 0``, and the
scaler's scale-in advice documenting it — so a renamed field would
drift silently.  :class:`DrainReceipt` is now the one definition:

- ``serve.py`` *builds* its final-stats payload through it;
- the router (serving/router.py ``absorb_drain``) *consumes* it to
  migrate the unserved remainder — the no-lost-requests half of the
  chaos ``router`` invariant;
- the scaler (``ServingScaler.confirm_scale_in``) *consumes* it to
  retire the drained replica from the adapter census and account
  clean vs dirty drains.

``from_payload`` validates structurally (event tag, types, counts,
aligned per-request arrays) and raises ``ValueError`` with the field
name on any mismatch — a malformed receipt fails loudly at the
boundary, never as a KeyError three layers deeper.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Mapping

#: The payload's event tag — the discriminator consumers match on
#: when scanning mixed stdout lines.
EVENT = "final_stats"


@dataclasses.dataclass(frozen=True)
class DrainReceipt:
    """One replica's end-of-life accounting (see module docstring).

    ``request_*_ticks`` are aligned per submitted request; ``None``
    entries are requests that never reached that milestone (an
    unserved request has no latency).  ``replica`` is the emitting
    replica's id — empty when the server wasn't told one (standalone
    CLI runs), required by the router migration path.
    """

    served: int
    unserved: int
    drained: bool
    elapsed_s: float
    ticks: int
    decode_tokens: int
    request_latency_ticks: tuple[float | None, ...]
    request_wait_ticks: tuple[float | None, ...]
    request_exec_ticks: tuple[float | None, ...]
    stats: Mapping[str, Any]
    replica: str = ""

    @property
    def clean(self) -> bool:
        """A clean drain served everything it admitted."""
        return self.drained and self.unserved == 0

    def to_payload(self) -> dict[str, Any]:
        """The wire dict — exactly the historical final-stats key set
        (older consumers keep working) plus ``replica``."""
        return {
            "event": EVENT,
            "served": self.served,
            "unserved": self.unserved,
            "drained": self.drained,
            "elapsed_s": self.elapsed_s,
            "ticks": self.ticks,
            "decode_tokens": self.decode_tokens,
            "request_latency_ticks": list(self.request_latency_ticks),
            "request_wait_ticks": list(self.request_wait_ticks),
            "request_exec_ticks": list(self.request_exec_ticks),
            "stats": dict(self.stats),
            "replica": self.replica,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_payload())

    @classmethod
    def from_payload(cls, payload: Mapping[str, Any]) -> "DrainReceipt":
        """Parse + validate one receipt dict; ValueError names the
        offending field."""
        if not isinstance(payload, Mapping):
            raise ValueError("drain receipt: payload is not a mapping")
        if payload.get("event") != EVENT:
            raise ValueError(
                f"drain receipt: event != {EVENT!r} "
                f"(got {payload.get('event')!r})")

        def _int(key: str) -> int:
            v = payload.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                raise ValueError(
                    f"drain receipt: {key} must be a non-negative "
                    f"int (got {v!r})")
            return v

        def _ticks(key: str) -> tuple[float | None, ...]:
            v = payload.get(key)
            if not isinstance(v, (list, tuple)):
                raise ValueError(
                    f"drain receipt: {key} must be a list")
            out: list[float | None] = []
            for x in v:
                if x is None:
                    out.append(None)
                elif isinstance(x, (int, float)) \
                        and not isinstance(x, bool):
                    out.append(float(x))
                else:
                    raise ValueError(
                        f"drain receipt: {key} entries must be "
                        f"numbers or null (got {x!r})")
            return tuple(out)

        served = _int("served")
        unserved = _int("unserved")
        drained = payload.get("drained")
        if not isinstance(drained, bool):
            raise ValueError("drain receipt: drained must be a bool")
        elapsed = payload.get("elapsed_s")
        if not isinstance(elapsed, (int, float)) \
                or isinstance(elapsed, bool) or elapsed < 0:
            raise ValueError(
                "drain receipt: elapsed_s must be a non-negative "
                "number")
        lat = _ticks("request_latency_ticks")
        wait = _ticks("request_wait_ticks")
        exe = _ticks("request_exec_ticks")
        if not (len(lat) == len(wait) == len(exe)):
            raise ValueError(
                "drain receipt: request_*_ticks arrays are not "
                f"aligned ({len(lat)}/{len(wait)}/{len(exe)})")
        # Aggregate-only receipts (empty per-request arrays) are
        # legal — queueing-model replicas account cohorts, not
        # requests; when the arrays ARE present they must cover
        # every submitted request.
        if lat and served + unserved != len(lat):
            raise ValueError(
                "drain receipt: served + unserved != request count "
                f"({served} + {unserved} != {len(lat)})")
        stats = payload.get("stats")
        if not isinstance(stats, Mapping):
            raise ValueError("drain receipt: stats must be a mapping")
        replica = payload.get("replica", "")
        if not isinstance(replica, str):
            raise ValueError("drain receipt: replica must be a string")
        return cls(served=served, unserved=unserved, drained=drained,
                   elapsed_s=float(elapsed), ticks=_int("ticks"),
                   decode_tokens=_int("decode_tokens"),
                   request_latency_ticks=lat, request_wait_ticks=wait,
                   request_exec_ticks=exe, stats=dict(stats),
                   replica=replica)

    @classmethod
    def parse_line(cls, line: str) -> "DrainReceipt":
        """Parse one stdout line (the server's last line)."""
        try:
            payload = json.loads(line)
        except json.JSONDecodeError as exc:
            raise ValueError(
                f"drain receipt: line is not JSON ({exc})") from exc
        return cls.from_payload(payload)
