"""Simulation harness: BASELINE eval configs as executable scenarios.

Drives the controller + fake scheduler over simulated time — the same loop
the e2e tests use, packaged for the ``demo`` CLI command and ``bench.py``.
The reference's only integration story was `--dry-run` by hand (SURVEY.md
§5); here every eval config in BASELINE.md is a named, runnable scenario.
"""

from __future__ import annotations

import dataclasses

from tpu_autoscaler.controller import Controller
from tpu_autoscaler.k8s.fake import FakeKube
from tpu_autoscaler.topology.catalog import (
    ACCELERATOR_LABEL,
    TOPOLOGY_LABEL,
    TPU_RESOURCE,
    shape_by_name,
)


def pod_payload(name: str, requests: dict, selectors: dict | None = None,
                labels: dict | None = None,
                owner_kind: str | None = None) -> dict:
    """Pending-pod payload builder, shared with the chaos engine
    (tpu_autoscaler/chaos) so scenario programs seed demand exactly the
    way the named BASELINE scenarios do."""
    return _pod(name, requests, selectors, labels, owner_kind)


def gang_pods(shape_name: str, job: str, jobset: str | None = None,
              job_index: int | None = None,
              namespace: str = "default",
              pin_topology: bool = True) -> list[dict]:
    """One slice-shaped gang's pod payloads (public twin of
    ``_gang_pods`` for the chaos engine's workload model).

    ``pin_topology=False`` drops the gke-tpu-topology selector,
    modeling jobs that pin only the accelerator — the fitter then
    sizes from observed chip demand, which is exactly the surface the
    lone-host-backfill bug class lives on (chaos coverage wants both).
    """
    pods = _gang_pods(shape_name, job, jobset=jobset, job_index=job_index)
    for p in pods:
        if namespace != "default":
            p["metadata"]["namespace"] = namespace
        if not pin_topology:
            # The gang shares one selectors dict; pop is idempotent.
            p["spec"]["nodeSelector"].pop(TOPOLOGY_LABEL, None)
    return pods


def _pod(name: str, requests: dict, selectors: dict | None = None,
         labels: dict | None = None, owner_kind: str | None = None) -> dict:
    tolerations = ([{"key": TPU_RESOURCE, "operator": "Exists",
                     "effect": "NoSchedule"}]
                   if TPU_RESOURCE in requests else [])
    payload: dict = {
        "metadata": {"name": name, "namespace": "default",
                     "labels": labels or {},
                     "creationTimestamp": "1970-01-01T00:00:00Z"},
        "spec": {
            "containers": [{"name": "main",
                            "resources": {"requests": requests}}],
            "nodeSelector": selectors or {},
            "tolerations": tolerations,
        },
        "status": {"phase": "Pending", "conditions": [
            {"type": "PodScheduled", "status": "False",
             "reason": "Unschedulable"}]},
    }
    if owner_kind:
        payload["metadata"]["ownerReferences"] = [
            {"kind": owner_kind, "name": f"{name}-owner"}]
    return payload


def _gang_pods(shape_name: str, job: str, jobset: str | None = None,
               job_index: int | None = None) -> list[dict]:
    shape = shape_by_name(shape_name)
    selectors = {ACCELERATOR_LABEL: shape.accelerator_type,
                 TOPOLOGY_LABEL: shape.topology_label}
    labels = {"batch.kubernetes.io/job-name": job}
    if jobset is not None:
        labels["jobset.sigs.k8s.io/jobset-name"] = jobset
        labels["jobset.sigs.k8s.io/job-index"] = str(job_index or 0)
    return [
        _pod(f"{job}-{i}", {TPU_RESOURCE: str(shape.chips_per_host)},
             selectors, dict(labels), owner_kind="Job")
        for i in range(shape.hosts)
    ]


def seed_scenario(kube: FakeKube, scenario: str) -> int:
    """Seed pending demand for one BASELINE eval config; returns the chip
    count requested."""
    if scenario == "cpu":
        kube.add_pod(_pod("web", {"cpu": "2"}))
        return 0
    if scenario == "v5e-8":
        shape = shape_by_name("v5e-8")
        kube.add_pod(_pod(
            "jax", {TPU_RESOURCE: "8"},
            {ACCELERATOR_LABEL: shape.accelerator_type,
             TOPOLOGY_LABEL: shape.topology_label},
            {"batch.kubernetes.io/job-name": "jax"}, owner_kind="Job"))
        return 8
    if scenario == "v5e-64":
        for p in _gang_pods("v5e-64", "trainer"):
            kube.add_pod(p)
        return 64
    if scenario == "2xv5p-128":
        for idx in range(2):
            for p in _gang_pods("v5p-128", f"ms-{idx}", jobset="ms",
                                job_index=idx):
                kube.add_pod(p)
        return 256
    if scenario == "v5p-256":
        for p in _gang_pods("v5p-256", "north-star"):
            kube.add_pod(p)
        return 256
    raise ValueError(f"unknown scenario {scenario!r}")


@dataclasses.dataclass
class SimResult:
    scenario: str
    all_running: bool
    latency_seconds: float | None
    nodes: int
    chips_provisioned: int
    chips_requested: int
    snapshot: dict
    peak_nodes: int | None = None

    @property
    def stranded_chips(self) -> int:
        return max(0, self.chips_provisioned - self.chips_requested)

    def describe(self) -> str:
        if not self.all_running:
            return (f"[{self.scenario}] FAILED: pods still pending "
                    f"(nodes={self.nodes})")
        if self.peak_nodes is not None:
            reclaimed = "all reclaimed" if self.nodes == 0 else \
                f"{self.nodes} nodes LEFT"
            return (f"[{self.scenario}] Unschedulable→Running in "
                    f"{self.latency_seconds:.1f}s sim-time; peak "
                    f"{self.peak_nodes} nodes, then job completed → "
                    f"{reclaimed} (units_deleted="
                    f"{int(self.snapshot['counters'].get('units_deleted', 0))})")
        return (f"[{self.scenario}] Unschedulable→Running in "
                f"{self.latency_seconds:.1f}s sim-time; nodes={self.nodes}, "
                f"chips={self.chips_provisioned} "
                f"(requested {self.chips_requested}, "
                f"stranded {self.stranded_chips})")


def simulate_churn(kube: FakeKube, controller: Controller, *,
                   until: float, step: float = 5.0, seed: int = 0,
                   arrival_rate: float = 0.02,
                   completion_rate: float = 0.004) -> str:
    """Randomized fleet churn: gangs of mixed shapes arrive, run, and
    complete while the controller scales both ways.  Returns a summary —
    the whole-system demo (`demo --scenario churn`).
    """
    import random

    rng = random.Random(seed)
    shapes = ["v5e-8", "v5e-16", "v5e-64"]
    active: dict[str, list[str]] = {}
    served = 0
    jid = 0
    peak_nodes = 0
    t = 0.0
    while t <= until:
        if rng.random() < arrival_rate and len(active) < 10:
            jid += 1
            shape = shape_by_name(rng.choice(shapes))
            names = []
            for p in _gang_pods(shape.name, f"job-{jid}"):
                kube.add_pod(p)
                names.append(p["metadata"]["name"])
            active[f"job-{jid}"] = names
        for job, names in list(active.items()):
            running = all(
                (kube.get_pod("default", n) or {}).get("status", {})
                .get("phase") == "Running" for n in names)
            if running and rng.random() < completion_rate:
                for n in names:
                    kube.delete_pod("default", n)
                del active[job]
                served += 1
        controller.reconcile_once(now=t)
        kube.schedule_step()
        peak_nodes = max(peak_nodes, len(kube.list_nodes()))
        t += step

    snap = controller.metrics.snapshot()
    lat = snap["summaries"].get("scale_up_latency_seconds", {})
    pending = sum(1 for p in kube.list_pods()
                  if p["status"]["phase"] == "Pending")
    counters = snap["counters"]
    return (f"[churn] {served} jobs served, {len(active)} running, "
            f"{pending} pods pending at cutoff; "
            f"scale-up latency avg {lat.get('avg', 0):.0f}s "
            f"max {lat.get('max', 0):.0f}s over {lat.get('count', 0)} "
            f"gangs; peak {peak_nodes} nodes, "
            f"{int(counters.get('provisions_submitted', 0))} provisions, "
            f"{int(counters.get('units_deleted', 0))} reclaims, "
            f"{int(counters.get('chip_seconds_provisioned', 0))} "
            f"chip-seconds")


def simulate(kube: FakeKube, controller: Controller, *, until: float,
             step: float = 5.0, scenario: str = "",
             chips_requested: int = 0,
             scale_down: bool = False) -> SimResult:
    """Run the loop in simulated time until all pods run (or time out).

    With ``scale_down``, the workload then "completes" (pods deleted) and
    the loop keeps running until the cluster reclaims every node — the
    demo for the full lifecycle including slice-atomic scale-down.
    """
    if step <= 0:
        raise ValueError(f"simulation step must be > 0, got {step}")

    def all_running() -> bool:
        pods = kube.list_pods()
        return bool(pods) and all(
            p["status"]["phase"] == "Running" for p in pods)

    t, finished = 0.0, None
    while t <= until:
        controller.reconcile_once(now=t)
        kube.schedule_step()
        if finished is None and all_running():
            finished = t
            controller.reconcile_once(now=t)  # record latency metric
            break
        t += step

    if scale_down and finished is not None:
        peak_nodes = len(kube.list_nodes())
        for p in list(kube.list_pods()):
            kube.delete_pod(p["metadata"].get("namespace", "default"),
                            p["metadata"]["name"])
        idle = controller.config.idle_threshold_seconds
        deadline = t + idle + 20 * step + 300.0
        while t <= deadline and kube.list_nodes():
            controller.reconcile_once(now=t)
            t += step
        snap = controller.metrics.snapshot()
        return SimResult(
            scenario=f"{scenario}+scale-down", all_running=True,
            latency_seconds=snap["summaries"].get(
                "scale_up_latency_seconds", {}).get("max", finished),
            nodes=len(kube.list_nodes()), chips_provisioned=0,
            chips_requested=chips_requested, snapshot=snap,
            peak_nodes=peak_nodes)

    chips = sum(
        int(float(n["status"]["allocatable"].get(TPU_RESOURCE, 0)))
        for n in kube.list_nodes())
    snap = controller.metrics.snapshot()
    lat = snap["summaries"].get("scale_up_latency_seconds", {}).get("max")
    return SimResult(
        scenario=scenario, all_running=all_running(),
        latency_seconds=lat if lat is not None else finished,
        nodes=len(kube.list_nodes()), chips_provisioned=chips,
        chips_requested=chips_requested, snapshot=snap)
