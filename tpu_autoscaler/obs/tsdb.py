"""In-process time-series store for the controller's own metrics
(ISSUE 10).

The metrics registry answers "what is the value NOW"; nothing retained
*history* — an operator could not ask "when did p99 scale-up start
degrading?", and the alert engine (obs/alerts.py) needs windows, not
instants.  This module is the retention layer: a fixed-size
numpy-ring-per-series store fed once per reconcile pass from the
existing ``Metrics.snapshot()`` (the same emission path every exporter
already rides — no new instrumentation seams, no second source of
truth).

Threading model (the load-bearing part):

- **writes** happen ONLY on the reconcile thread — ``ingest()`` is
  called from ``reconcile_once`` — so the hot path takes ZERO new
  locks;
- **reads** (``/debugz/tsdb``, the ``metrics-history`` CLI, incident
  bundles, the alert engine) come from other threads and use a
  seqlock: ``ingest`` bumps ``_wseq`` to odd before mutating and back
  to even after, and readers copy-then-recheck with a bounded retry —
  the established ``debug_dump`` bounded-retry pattern, generalized.
  A torn read is *detected and retried*, never returned.  (The alert
  engine actually runs on the reconcile thread too and could read
  bare; it goes through the same guarded reads so there is exactly
  one read path to verify.)

Retention model (docs/OBSERVABILITY.md):

- **raw** tier: one point per ingest pass in which the value changed
  (plus a heartbeat so flat series still anchor window queries),
  ``raw_points`` deep;
- **mid** tier: 10 s buckets aggregated (last/min/max/mean) as raw
  points age, ``mid_points`` deep (~2 h at the defaults);
- **coarse** tier: 5 min buckets, ``coarse_points`` deep (~7 days).

Append is O(1) (ring write + two bucket folds); a range query is
O(window) — it walks only the retained points inside ``[start, end]``,
picking the finest tier that still covers each sub-range.

Series naming: counters and gauges keep their metric name; summaries
contribute ``name:count`` and ``name:sum`` (windows give rate and
mean); declared histograms contribute one cumulative ``name:le:<le>``
series per bucket — exactly what a multi-window burn rate needs
(good/total over a window = two deltas).
"""

from __future__ import annotations

import collections
import math
from typing import Any, Callable, Iterable, TypeVar

import numpy as np

#: Default tier geometry (docs/OBSERVABILITY.md "Time-series history").
RAW_POINTS = 720
MID_SECONDS = 10.0
MID_POINTS = 720
COARSE_SECONDS = 300.0
COARSE_POINTS = 2016
#: Flat series still get a point this often, so "last value at-or-
#: before t" stays answerable across the whole retention window.
HEARTBEAT_SECONDS = 60.0
#: Hard series-count bound: a runaway dynamic family must degrade
#: (drop new series, count them) instead of growing without bound.
MAX_SERIES = 20_000

#: Exemplar retention (ISSUE 14): per histogram family, a small ring
#: of (t, value, trace_id) triples linking aggregate series to a
#: concrete sampled trace.  Exemplars live OUTSIDE the tier pipeline
#: (a trace id cannot be downsampled), so they survive raw-ring
#: eviction — a p99 from coarse history still resolves to its trace.
EXEMPLAR_RING = 32
#: Family-count bound, same degrade-don't-grow discipline as
#: MAX_SERIES (counted in ``exemplars_dropped``).
MAX_EXEMPLAR_FAMILIES = 256

#: Aggregate row columns for the downsampled tiers.
_T, _LAST, _MIN, _MAX, _SUM, _N = range(6)

#: Result type of a seqlock-guarded read thunk (``TimeSeriesDB._guarded``).
_R = TypeVar("_R")


class _Ring:
    """Fixed-capacity append-only ring of (t, value) float64 pairs.

    Storage grows geometrically up to ``capacity`` (a new series costs
    a 32-slot allocation, not the full ring — creating ~100 series on
    a controller's first pass was eating milliseconds of np.zeros);
    wrap-around only begins once the arrays reach full capacity, so
    growth never reorders retained points."""

    __slots__ = ("t", "v", "n", "capacity")

    def __init__(self, capacity: int) -> None:
        self.capacity = capacity
        size = min(32, capacity)
        self.t = np.zeros(size, dtype=np.float64)
        self.v = np.zeros(size, dtype=np.float64)
        self.n = 0  # total appended (retained = min(n, capacity))

    def append(self, t: float, v: float) -> None:
        size = len(self.t)
        if self.n == size and size < self.capacity:
            grown = min(self.capacity, size * 4)
            nt = np.zeros(grown, dtype=np.float64)
            nv = np.zeros(grown, dtype=np.float64)
            nt[:size] = self.t
            nv[:size] = self.v
            self.t, self.v = nt, nv
            size = grown
        i = self.n % size
        self.t[i] = t
        self.v[i] = v
        self.n += 1

    def ordered(self) -> tuple[np.ndarray, np.ndarray]:
        """Retained points oldest→newest.  VIEWS while the ring has
        not wrapped (the common case — callers run inside the seqlock
        guard and copy anything they keep), copies after the wrap."""
        cap = len(self.t)
        if self.n <= cap:
            return self.t[:self.n], self.v[:self.n]
        i = self.n % cap
        return (np.concatenate((self.t[i:], self.t[:i])),
                np.concatenate((self.v[i:], self.v[:i])))

    def last_at(self, t: float) -> float | None:
        """Value of the newest point at-or-before ``t`` without
        materializing the ordered view — the alert engine's per-pass
        window-edge lookup (O(log n), zero copies)."""
        cap = len(self.t)
        if self.n == 0:
            return None
        if self.n <= cap:
            tv = self.t[:self.n]
            i = int(np.searchsorted(tv, t, side="right")) - 1
            return float(self.v[i]) if i >= 0 else None
        i0 = self.n % cap
        newer_t = self.t[:i0]   # the i0 most recent points
        if i0 and t >= newer_t[0]:
            j = int(np.searchsorted(newer_t, t, side="right")) - 1
            return float(self.v[j])
        older_t = self.t[i0:]   # the cap - i0 older points
        j = int(np.searchsorted(older_t, t, side="right")) - 1
        return float(self.v[i0 + j]) if j >= 0 else None


class _AggRing:
    """Ring of closed downsample buckets: rows (t, last, min, max,
    sum, count); ``t`` is the bucket START.  Open buckets are plain
    Python lists (scalar float math beats numpy at this size); rows
    land in the numpy ring only when the bucket closes."""

    __slots__ = ("rows", "n", "capacity")

    def __init__(self, capacity: int) -> None:
        self.capacity = capacity
        self.rows: np.ndarray | None = None  # lazy: first bucket close
        self.n = 0

    def append(self, row: list[float]) -> None:
        if self.rows is None:
            self.rows = np.zeros((min(32, self.capacity), 6),
                                 dtype=np.float64)
        size = len(self.rows)
        if self.n == size and size < self.capacity:
            grown = np.zeros((min(self.capacity, size * 4), 6),
                             dtype=np.float64)
            grown[:size] = self.rows
            self.rows = grown
            size = len(grown)
        self.rows[self.n % size] = row
        self.n += 1

    def ordered(self) -> np.ndarray:
        """Oldest→newest rows: a VIEW until the ring wraps (callers
        run inside the seqlock guard and copy what they keep)."""
        if self.rows is None:
            return np.zeros((0, 6), dtype=np.float64)
        cap = len(self.rows)
        if self.n <= cap:
            return self.rows[:self.n]
        i = self.n % cap
        return np.concatenate((self.rows[i:], self.rows[:i]))


class _Series:
    __slots__ = ("raw", "mid", "coarse", "open_mid", "open_coarse",
                 "last_t", "last_v")

    def __init__(self, raw_points: int, mid_points: int,
                 coarse_points: int) -> None:
        self.raw = _Ring(raw_points)
        self.mid = _AggRing(mid_points)
        self.coarse = _AggRing(coarse_points)
        # Open (not-yet-closed) bucket accumulators, or None.
        self.open_mid: list[float] | None = None
        self.open_coarse: list[float] | None = None
        self.last_t = -math.inf
        self.last_v = math.nan


def _fold(open_row: list[float] | None, ring: _AggRing,
          bucket_start: float, t: float, v: float) -> list[float]:
    """Fold one point into an open bucket, closing it into ``ring``
    first if ``t`` has advanced past it."""
    if open_row is not None and open_row[_T] != bucket_start:
        ring.append(open_row)
        open_row = None
    if open_row is None:
        return [bucket_start, v, v, v, v, 1.0]
    open_row[_LAST] = v
    if v < open_row[_MIN]:
        open_row[_MIN] = v
    if v > open_row[_MAX]:
        open_row[_MAX] = v
    open_row[_SUM] += v
    open_row[_N] += 1.0
    return open_row


class TornRead(RuntimeError):
    """A guarded read raced the reconcile-thread writer past the retry
    budget (pathological; readers degrade, never return torn data)."""


class TimeSeriesDB:
    """Ring-per-series metric history.  Single writer (the reconcile
    thread), seqlock-guarded readers — see module docstring."""

    def __init__(self, raw_points: int = RAW_POINTS,
                 mid_seconds: float = MID_SECONDS,
                 mid_points: int = MID_POINTS,
                 coarse_seconds: float = COARSE_SECONDS,
                 coarse_points: int = COARSE_POINTS,
                 heartbeat_seconds: float = HEARTBEAT_SECONDS,
                 max_series: int = MAX_SERIES) -> None:
        self.raw_points = raw_points
        self.mid_seconds = mid_seconds
        self.mid_points = mid_points
        self.coarse_seconds = coarse_seconds
        self.coarse_points = coarse_points
        self.heartbeat_seconds = heartbeat_seconds
        self.max_series = max_series
        self._series: dict[str, _Series] = {}
        #: family -> bounded list of (t, value, trace_id) exemplars.
        self._exemplars: dict[str, collections.deque] = {}
        #: Seqlock: odd while the writer mutates, even when stable.
        self._wseq = 0
        self.points_appended = 0
        self.series_dropped = 0
        self.exemplars_appended = 0
        self.exemplars_dropped = 0

    # -- write path (reconcile thread ONLY) ---------------------------

    def ingest(self, snapshot: dict[str, Any], now: float,
               exemplars: dict[str, tuple[str, float]] | None = None
               ) -> int:
        """Fold one ``Metrics.snapshot()`` into the store; returns the
        number of points appended.  Unchanged values are skipped (flat
        series re-anchor every ``heartbeat_seconds``), so a pass costs
        O(changed series), not O(all series).

        ``exemplars``: optional ``{family: (trace_id, value)}`` — at
        most one exemplar per histogram family per pass (ISSUE 14),
        linking that family's series to a concrete sampled trace.
        The caller must have observed ``value`` into the family this
        same pass (the exemplar-membership property the test suite
        asserts)."""
        self._wseq += 1  # odd: mutation in progress
        try:
            appended = 0
            if exemplars:
                for family, (trace_id, value) in exemplars.items():
                    self._append_exemplar(family, now, float(value),
                                          str(trace_id))
            for name, value in snapshot.get("counters", {}).items():
                appended += self._append(name, now, float(value))
            for name, value in snapshot.get("gauges", {}).items():
                appended += self._append(name, now, float(value))
            for name, s in snapshot.get("summaries", {}).items():
                # Zero-count summaries ingest too: every cumulative
                # series must be born at the SAME pass as its
                # histogram-bucket siblings, or a window whose start
                # precedes both births computes good/total against
                # asymmetric baselines and can mask a miss.
                appended += self._append(f"{name}:count", now,
                                         float(s.get("count", 0)))
                appended += self._append(f"{name}:sum", now,
                                         float(s.get("sum", 0.0)))
            summaries = snapshot.get("summaries", {})
            for name, h in snapshot.get("histograms", {}).items():
                for le, cum in h.get("buckets", ()):
                    appended += self._append(f"{name}:le:{le:g}", now,
                                             float(cum))
                if name not in summaries:
                    # A declared-but-unobserved histogram has bucket
                    # series but no summary yet: anchor :count/:sum at
                    # 0 from the SAME pass, or a burn window spanning
                    # the series' birth computes good/total against
                    # asymmetric baselines and can mask a miss.
                    appended += self._append(f"{name}:count", now, 0.0)
                    appended += self._append(f"{name}:sum", now, 0.0)
            self.points_appended += appended
            return appended
        finally:
            self._wseq += 1  # even: stable

    def append(self, name: str, t: float, value: float) -> None:
        """Direct single-point append (tests, offline rebuild).  Same
        single-writer contract as ``ingest``."""
        self._wseq += 1
        try:
            self._append(name, t, value, force=True)
            self.points_appended += 1
        finally:
            self._wseq += 1

    def _append_exemplar(self, family: str, t: float, v: float,
                         trace_id: str) -> None:
        """Keyed by FAMILY NAME in a dedicated map — exemplars can
        never be misattributed to another series however the 20k
        series cap churns (the no-cross-series-leak property)."""
        ring = self._exemplars.get(family)
        if ring is None:
            if len(self._exemplars) >= MAX_EXEMPLAR_FAMILIES:
                self.exemplars_dropped += 1
                return
            ring = collections.deque(maxlen=EXEMPLAR_RING)
            self._exemplars[family] = ring
        ring.append((float(t), float(v), trace_id))
        self.exemplars_appended += 1

    def append_exemplar(self, family: str, t: float, v: float,
                        trace_id: str) -> None:
        """Direct exemplar append (tests, ``from_dump`` rebuild).
        Same single-writer contract as ``append``."""
        self._wseq += 1
        try:
            self._append_exemplar(family, t, v, trace_id)
        finally:
            self._wseq += 1

    def _append(self, name: str, t: float, v: float,
                force: bool = False) -> int:
        series = self._series.get(name)
        if series is None:
            if len(self._series) >= self.max_series:
                self.series_dropped += 1
                return 0
            series = _Series(self.raw_points, self.mid_points,
                             self.coarse_points)
            self._series[name] = series
        if (not force and v == series.last_v
                and t - series.last_t < self.heartbeat_seconds):
            return 0
        series.last_t = t
        series.last_v = v
        series.raw.append(t, v)
        mid_start = math.floor(t / self.mid_seconds) * self.mid_seconds
        series.open_mid = _fold(series.open_mid, series.mid,
                                mid_start, t, v)
        coarse_start = (math.floor(t / self.coarse_seconds)
                        * self.coarse_seconds)
        series.open_coarse = _fold(series.open_coarse, series.coarse,
                                   coarse_start, t, v)
        return 1

    # -- guarded read path --------------------------------------------

    def _guarded(self, fn: Callable[[], _R], retries: int = 16) -> _R:
        """Copy-then-recheck under the seqlock; bounded retry.  Failed
        attempts SLEEP briefly before retrying: a no-yield loop would
        burn every retry in microseconds inside one multi-ms ingest
        (the writer's critical section at 10k-series scale) and
        spuriously degrade exactly when a pass is running — the
        moment the debug endpoints exist for (review-found).  The
        reconcile thread's own reads (the alert engine) never race
        the writer — same thread — so they always hit the first,
        sleep-free attempt."""
        import time as _time

        for attempt in range(retries):
            if attempt:
                _time.sleep(0.002)  # analysis: allow=TAB803 bounded reader backoff BY DESIGN (docstring above): a retry only happens when the writer is mid-mutation, and yielding 2 ms beats spinning the whole retry budget inside one multi-ms ingest; the reconcile thread never reaches this branch
            s0 = self._wseq
            if s0 % 2:
                continue  # writer mid-mutation
            try:
                out = fn()
            except (RuntimeError, KeyError, IndexError, ValueError):
                continue  # mutated mid-copy; retry
            if self._wseq == s0:
                return out
        raise TornRead("tsdb read raced the writer past the retry "
                       "budget")

    def series_count(self) -> int:
        return len(self._series)

    def exemplar_latest(self, family: str
                        ) -> tuple[float, float, str] | None:
        """Most recent (t, value, trace_id) exemplar for ``family`` —
        the alert engine's "which trace is burning" lookup."""
        def read() -> tuple[float, float, str] | None:
            ring = self._exemplars.get(family)
            return ring[-1] if ring else None
        return self._guarded(read)

    def exemplars(self, family: str, start: float = -math.inf,
                  end: float = math.inf
                  ) -> list[tuple[float, float, str]]:
        """Retained exemplars for ``family`` inside ``[start, end]``,
        oldest first."""
        def read() -> list[tuple[float, float, str]]:
            ring = self._exemplars.get(family)
            if not ring:
                return []
            return [e for e in ring if start <= e[0] <= end]
        return self._guarded(read)

    def series_names(self, prefix: str = "") -> list[str]:
        def read() -> list[str]:
            return sorted(n for n in self._series if n.startswith(prefix))
        return self._guarded(read)

    def points(self, name: str, start: float = -math.inf,
               end: float = math.inf) -> tuple[np.ndarray, np.ndarray]:
        """Range query: (ts, values) inside ``[start, end]``, oldest
        first — raw resolution where raw retention covers, downsampled
        ``last`` values (bucket start time) for the older remainder."""
        def rows_of(ring: _AggRing,
                    open_row: list[float] | None) -> np.ndarray:
            rows = ring.ordered()
            if open_row is not None:
                rows = np.concatenate(
                    (rows, np.asarray(open_row)[None, :]))
            return rows

        def read() -> tuple[np.ndarray, np.ndarray]:
            series = self._series.get(name)
            if series is None:
                return (np.empty(0), np.empty(0))
            rt, rv = series.raw.ordered()
            wrapped = series.raw.n > series.raw.capacity
            if len(rt) and (not wrapped or rt[0] <= start):
                # Raw retention covers the whole window — either the
                # ring never evicted anything (the downsample tiers
                # only DUPLICATE raw history then; bucket starts
                # truncate below the true birth, so they must not
                # leak in) or the window starts inside it.  One
                # binary-searched slice, no tier merge.
                i0 = int(np.searchsorted(rt, start, side="left"))
                i1 = int(np.searchsorted(rt, end, side="right"))
                return rt[i0:i1].copy(), rv[i0:i1].copy()
            # Coverage boundaries: raw answers [raw_oldest, ∞); mid
            # answers [mid_oldest, raw_oldest); coarse the remainder.
            # Segments are disjoint and time-ordered by construction,
            # so concatenation needs no sort.
            raw_oldest = rt[0] if len(rt) else math.inf
            mid = rows_of(series.mid, series.open_mid)
            mid_oldest = mid[0, _T] if len(mid) else raw_oldest
            coarse = rows_of(series.coarse, series.open_coarse)
            ts_parts, vs_parts = [], []
            if len(coarse) and start < mid_oldest:
                keep = ((coarse[:, _T] >= start)
                        & (coarse[:, _T] < mid_oldest)
                        & (coarse[:, _T] <= end))
                ts_parts.append(coarse[keep][:, _T])
                vs_parts.append(coarse[keep][:, _LAST])
            if len(mid) and start < raw_oldest:
                keep = ((mid[:, _T] >= start)
                        & (mid[:, _T] >= mid_oldest)
                        & (mid[:, _T] < raw_oldest)
                        & (mid[:, _T] <= end))
                ts_parts.append(mid[keep][:, _T])
                vs_parts.append(mid[keep][:, _LAST])
            keep = (rt >= start) & (rt <= end)
            ts_parts.append(rt[keep])
            vs_parts.append(rv[keep])
            return np.concatenate(ts_parts), np.concatenate(vs_parts)
        return self._guarded(read)

    def value_at(self, name: str, t: float) -> float | None:
        """Last recorded value at-or-before ``t`` (None: series unknown
        or born after ``t``).

        Hot path for the per-pass alert evaluation, so it avoids the
        full merged-tier assembly wherever it can: O(1) when ``t`` is
        at-or-after the newest point (every window END is), one raw
        binary search while raw retention covers ``t`` (every window
        START within ~raw_points passes is)."""
        def read() -> float | None:
            series = self._series.get(name)
            if series is None:
                return None
            if t >= series.last_t:
                return None if math.isinf(series.last_t) else series.last_v
            hit = series.raw.last_at(t)
            if hit is not None:
                return hit
            return None  # fall through to the merged-tier view below
        fast = self._guarded(read)
        if fast is not None:
            return fast
        ts, vs = self.points(name, end=t)
        if not len(ts):
            return None
        return float(vs[-1])

    def _first_value(self, name: str) -> float | None:
        """Oldest retained value across tiers (the series-birth
        baseline for ``delta``): the value at the EARLIEST retained
        timestamp — a raw point while the raw ring hasn't wrapped,
        else the oldest downsampled bucket."""
        def read() -> float | None:
            series = self._series.get(name)
            if series is None:
                return None
            rt, rv = series.raw.ordered()
            if len(rt) and series.raw.n <= series.raw.capacity:
                # Raw never evicted: its first point IS the birth
                # (tier buckets only duplicate raw history here).
                return float(rv[0])
            best: tuple[float, float] | None = None
            if len(rt):
                best = (float(rt[0]), float(rv[0]))
            for ring in (series.coarse, series.mid):
                if ring.n:
                    row = ring.ordered()[0]
                    if best is None or row[_T] < best[0]:
                        best = (float(row[_T]), float(row[_LAST]))
            return best[1] if best is not None else None
        return self._guarded(read)

    def delta(self, name: str, start: float, end: float) -> float | None:
        """Cumulative-series delta over ``[start, end]``: value at
        ``end`` minus value at ``start``.  A series born inside the
        window uses its first retained point as the baseline (series
        birth counts as the start of history, not as a jump from 0 —
        a freshly-restarted controller must not alert on its own
        catch-up).  None: no data at-or-before ``end``."""
        v_end = self.value_at(name, end)
        if v_end is None:
            return None
        v_start = self.value_at(name, start)
        if v_start is None:
            v_start = self._first_value(name)
            if v_start is None:
                return None
        return v_end - v_start

    # -- dump / load (bundles, /debugz/tsdb, offline replay) ----------

    def dump(self, prefix: str = "", window_seconds: float | None = None,
             now: float | None = None) -> dict[str, Any]:
        """JSON-able snapshot of the store (the ``/debugz/tsdb`` body
        and the incident bundle's ``tsdb`` section).  ``prefix``
        filters series; ``window_seconds`` (with ``now``) trims to
        recent history."""
        start = -math.inf
        if window_seconds is not None and now is not None:
            start = now - window_seconds

        def read() -> dict[str, Any]:
            out: dict[str, Any] = {}
            for name in sorted(self._series):
                if not name.startswith(prefix):
                    continue
                series = self._series[name]
                rt, rv = series.raw.ordered()
                keep = rt >= start
                # Full float precision on purpose: a rounded
                # timestamp can land PAST a replay's query instant
                # and silently shift window edges offline.
                tiers: dict[str, Any] = {
                    "raw": [[float(t), float(v)]
                            for t, v in zip(rt[keep], rv[keep])],
                    # True while the raw ring never evicted: the tier
                    # rows below then only duplicate raw history and
                    # a rebuild must skip them.
                    "raw_complete": bool(
                        series.raw.n <= series.raw.capacity)}
                for tier_name, ring, open_row in (
                        ("mid", series.mid, series.open_mid),
                        ("coarse", series.coarse, series.open_coarse)):
                    rows = ring.ordered()
                    if open_row is not None:
                        rows = np.concatenate(
                            (rows, np.asarray(open_row)[None, :]))
                    rows = rows[rows[:, _T] >= start]
                    tiers[tier_name] = [
                        [float(r[_T]), float(r[_LAST]),
                         float(r[_MIN]), float(r[_MAX]), float(r[_SUM]),
                         int(r[_N])] for r in rows]
                out[name] = tiers
            return out

        def read_exemplars() -> dict[str, list[list[Any]]]:
            return {fam: [[float(t), float(v), tid]
                          for t, v, tid in ring if t >= start]
                    for fam, ring in sorted(self._exemplars.items())
                    if fam.startswith(prefix)}

        try:
            series = self._guarded(read)
            exemplars = self._guarded(read_exemplars)
            unavailable = False
        except TornRead:
            series, exemplars, unavailable = {}, {}, True
        body: dict[str, Any] = {
            "tiers": {"raw_points": self.raw_points,
                      "mid_seconds": self.mid_seconds,
                      "coarse_seconds": self.coarse_seconds,
                      "heartbeat_seconds": self.heartbeat_seconds},
            "series_count": len(self._series),
            "points_appended": self.points_appended,
            "series_dropped": self.series_dropped,
            "exemplars_dropped": self.exemplars_dropped,
            "series": series,
            "exemplars": exemplars,
        }
        if unavailable:
            body["unavailable"] = "mutating"
        return body

    @classmethod
    def from_dump(cls, dump: dict[str, Any]) -> "TimeSeriesDB":
        """Rebuild a queryable store from a ``dump()`` body — the
        offline-replay path (``python -m tpu_autoscaler.obs replay``).
        Downsampled history is replayed as bucket-last points, so
        window queries over the rebuilt store answer like the live one
        did wherever raw retention covered."""
        tiers = dump.get("tiers", {})
        db = cls(raw_points=int(tiers.get("raw_points", RAW_POINTS)),
                 mid_seconds=float(tiers.get("mid_seconds", MID_SECONDS)),
                 coarse_seconds=float(tiers.get("coarse_seconds",
                                                COARSE_SECONDS)),
                 heartbeat_seconds=float(tiers.get("heartbeat_seconds",
                                                   HEARTBEAT_SECONDS)))
        for name, body in dump.get("series", {}).items():
            raw = body.get("raw", [])
            raw_oldest = raw[0][0] if raw else math.inf
            seen: list[tuple[float, float]] = []
            if not body.get("raw_complete", False):
                # Mirror the live query path's coverage boundaries:
                # mid answers [mid_oldest, raw_oldest), coarse only
                # the remainder BELOW mid — replaying a coarse bucket
                # inside mid's range would inject its end-of-bucket
                # value up to 300 s early among 10 s-resolution rows.
                mid_rows = [r for r in body.get("mid", ())
                            if r[0] < raw_oldest]
                mid_oldest = mid_rows[0][0] if mid_rows else raw_oldest
                for row in body.get("coarse", ()):
                    if row[0] < mid_oldest:
                        seen.append((float(row[0]), float(row[1])))
                for row in mid_rows:
                    seen.append((float(row[0]), float(row[1])))
            seen.extend((float(t), float(v)) for t, v in raw)
            for t, v in sorted(seen):
                db.append(name, t, v)
        for family, rows in dump.get("exemplars", {}).items():
            for t, v, trace_id in rows:
                db.append_exemplar(family, float(t), float(v),
                                   str(trace_id))
        return db


def iter_latest(db: TimeSeriesDB, names: Iterable[str],
                now: float) -> dict[str, float]:
    """Convenience: latest value per series (None-valued omitted)."""
    out: dict[str, float] = {}
    for name in names:
        v = db.value_at(name, now)
        if v is not None:
            out[name] = v
    return out
