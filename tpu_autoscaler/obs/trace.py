"""In-process tracing for the detection→actuation path (ISSUE 5).

The north-star metric (``scale_up_latency_seconds``) is a single opaque
summary; when a scale-up is slow nothing says whether the time went to
observation, planning, dispatch, cloud provisioning, node registration
or scheduler binding.  This module is the missing decomposition: a
dependency-free tracer whose spans mirror OpenTelemetry's shape (name,
trace_id, span_id, parent, start/end, attrs, events) without the SDK —
the controller must not grow a third-party runtime dep for its own
introspection.

Model (docs/OBSERVABILITY.md):

- a **trace** is one gang scale-up: the reconciler mints a trace_id the
  first time a gang is seen Unschedulable and ends the root span when
  its last pod runs, so the whole story renders as ONE tree;
- **spans** carry explicit timestamps.  Call sites pass the injected
  reconcile clock (``now``) so simulated-time runs produce coherent
  traces; ``seq`` (a global monotonic counter) breaks ties between
  spans recorded at the same timestamp — recording order IS causal
  order within a thread;
- spans can be recorded **retroactively** (``record``): a reconcile
  pass serves many gangs, so its observe/plan timings are emitted into
  a gang's trace only when that pass actually dispatches work for it;
- **context**: the active span lives in a ``contextvars.ContextVar``.
  It deliberately does NOT leak across the actuation pool boundary —
  worker thunks never touch the tracer (docs/ACTUATION.md thread
  model); instead ``ActuationExecutor.submit`` captures the submitting
  span on the reconcile thread and the drain-time completion ends it
  there, so TAT2xx/TAR5xx stay clean by construction;
- **metrics**: ending a span with ``metric=`` feeds the duration (or an
  explicit ``value``) into the wired :class:`Metrics` registry — the
  phase histograms (reconciler.PHASE_LATENCY_METRICS) are fed by the
  same span ends that build the trace, so the two can never disagree.

Thread-safety: the tracer is called from the reconcile thread AND the
informer watch threads; every mutation of shared tracer state
(the active-span registry, the seq counters) happens under one
``concurrency.Lock``.  Span objects themselves are single-writer: the
thread that starts a span is the thread that ends it.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import time
import uuid
from typing import Any, Iterator

from tpu_autoscaler import concurrency

#: The active span for the calling thread/context (see module docstring).
_CURRENT: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "tpu_autoscaler_current_span", default=None)


def current_span() -> "Span | None":
    """The span active in this context (None outside any ``use()``)."""
    return _CURRENT.get()


def current_trace_id() -> str | None:
    span = _CURRENT.get()
    return span.trace_id if span is not None else None


@dataclasses.dataclass
class Span:
    """One timed phase.  ``end is None`` means still open (a stuck
    controller's ``/debugz`` dump shows exactly which phase is stuck)."""

    name: str
    trace_id: str
    span_id: str
    parent_id: str | None
    start: float
    end: float | None = None
    seq: int = 0
    attrs: dict[str, Any] = dataclasses.field(default_factory=dict)
    events: list[dict[str, Any]] = dataclasses.field(default_factory=list)

    @property
    def duration(self) -> float | None:
        return None if self.end is None else self.end - self.start

    def as_dict(self) -> dict[str, Any]:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start": self.start,
            "end": self.end,
            "duration_s": self.duration,
            "seq": self.seq,
            "attrs": dict(self.attrs),
            "events": list(self.events),
        }


class Tracer:
    """Span factory + sink.  ``recorder=None`` still produces spans (so
    trace ids propagate and ``metric=`` feeds still fire) but retains
    nothing — the zero-retention mode the overhead bench compares
    against is ``tracer=None`` at each instrumentation seam, which
    skips span work entirely."""

    def __init__(self, recorder: Any = None, metrics: Any = None,
                 clock: Any = time.time) -> None:
        self.recorder = recorder
        self._metrics = metrics
        self.clock = clock
        self._lock = concurrency.Lock()
        self._active: dict[str, Span] = {}
        self._seq = 0
        self._trace_seq = 0
        # Distinguishes traces across controller restarts in aggregated
        # log stores (trace ids repeat their counter after a crash-only
        # restart; the run id keeps them globally unique).
        self._run_id = uuid.uuid4().hex[:6]  # analysis: allow=TAD902 the run id exists to be unique ACROSS restarts BY DESIGN (see comment above); replay oracles compare span structure and attribution, never trace-id bytes

    # -- wiring -----------------------------------------------------------

    def bind_metrics(self, metrics: Any) -> None:
        """Adopt a metrics registry if none was injected (the Controller
        calls this so ``metric=`` span feeds land in ITS registry)."""
        with self._lock:
            if self._metrics is None:
                self._metrics = metrics

    # -- ids --------------------------------------------------------------

    def new_trace(self, prefix: str = "trace") -> str:
        with self._lock:
            self._trace_seq += 1
            return f"{prefix}-{self._run_id}-{self._trace_seq}"

    def _next_seq(self) -> int:
        with self._lock:
            self._seq += 1
            return self._seq

    # -- span lifecycle ---------------------------------------------------

    def start(self, name: str, *, trace_id: str | None = None,
              parent: Span | None = None, t: float | None = None,
              attrs: dict[str, Any] | None = None) -> Span:
        """Open a span.  Parent defaults to the context's current span;
        trace_id defaults to the parent's (or a fresh anonymous one)."""
        if parent is None:
            parent = _CURRENT.get()
        if trace_id is None:
            trace_id = (parent.trace_id if parent is not None
                        else self.new_trace())
        seq = self._next_seq()
        span = Span(name=name, trace_id=trace_id,
                    span_id=f"s{seq}",
                    parent_id=parent.span_id if parent is not None else None,
                    start=self.clock() if t is None else t,
                    seq=seq, attrs=dict(attrs or {}))
        with self._lock:
            self._active[span.span_id] = span
        return span

    def end(self, span: Span | None, *, t: float | None = None,
            attrs: dict[str, Any] | None = None,
            metric: str | None = None,
            value: float | None = None) -> None:
        """Close ``span``; with ``metric=`` also observe its duration
        (or the explicit ``value``) on the wired metrics registry —
        the phase-histogram feed."""
        if span is None:
            return
        # Span fields are single-writer by construction — the thread
        # that starts a span is the only one that ends it — and readers
        # on other threads only ever see (a) ring entries AFTER this
        # write completes (published through the recorder's lock) or
        # (b) lock-guarded COPIES of still-open spans (active_spans).
        # The lockset model cannot express that handoff, hence the
        # waivers (same shape as the informer pump() waiver).
        with self._lock:
            span.end = self.clock() if t is None else t  # analysis: allow=TAR503 single-writer; published via recorder/active_spans locks
            if attrs:
                span.attrs.update(attrs)  # analysis: allow=TAR503 single-writer; published via recorder/active_spans locks
            self._active.pop(span.span_id, None)
            metrics = self._metrics
        if metric is not None and metrics is not None:
            metrics.observe(
                metric, value if value is not None else (span.duration or 0.0))
        if self.recorder is not None:
            self.recorder.record_span(span)

    def record(self, name: str, *, start: float, end: float,
               trace_id: str | None = None, parent: Span | None = None,
               attrs: dict[str, Any] | None = None,
               metric: str | None = None,
               value: float | None = None) -> Span:
        """Emit a retroactive span with explicit start/end — how a
        reconcile pass's shared observe/plan timings land in each served
        gang's trace after the fact."""
        span = self.start(name, trace_id=trace_id, parent=parent, t=start,
                          attrs=attrs)
        self.end(span, t=end, metric=metric, value=value)
        return span

    def annotate(self, span: Span | None, **attrs: Any) -> None:
        """Attach attrs to a still-open span, under the tracer lock —
        the only safe way to decorate a span that ``active_spans()``
        may be copying concurrently (e.g. from the /debugz thread)."""
        if span is None:
            return
        with self._lock:
            span.attrs.update(attrs)

    def event(self, span: Span | None, name: str,
              attrs: dict[str, Any] | None = None,
              t: float | None = None) -> None:
        """Append a point-in-time event (e.g. a retry) to ``span``.
        Single-writer contract: call only from the thread that owns the
        span."""
        if span is None:
            return
        span.events.append({"name": name,
                            "t": self.clock() if t is None else t,
                            **(attrs or {})})

    def event_current(self, name: str,
                      attrs: dict[str, Any] | None = None) -> None:
        """Event on the context's current span (no-op outside a span —
        notably on executor worker threads, where the context var is
        deliberately unset)."""
        self.event(_CURRENT.get(), name, attrs)

    # -- context ----------------------------------------------------------

    @contextlib.contextmanager
    def use(self, span: Span | None) -> Iterator[Span | None]:
        """Make ``span`` the context's current span for the block."""
        token = _CURRENT.set(span)
        try:
            yield span
        finally:
            _CURRENT.reset(token)

    # -- introspection ----------------------------------------------------

    def active_spans(self) -> list[Span]:
        """Lock-guarded COPIES of still-open spans (the "what is the
        pass stuck on" view): the owning thread may end the originals
        at any moment, so readers never touch the live objects."""
        with self._lock:
            return [dataclasses.replace(s, attrs=dict(s.attrs),
                                        events=list(s.events))
                    for s in self._active.values()]


@contextlib.contextmanager
def maybe_span(tracer: Tracer | None, name: str,
               attrs: dict[str, Any] | None = None) -> Iterator[Span | None]:
    """Span-if-traced: the pattern for optional instrumentation seams
    (actuators, informer).  ``tracer=None`` costs one ``if`` — the
    untraced baseline the overhead gate (bench.py trace) holds the
    traced path to.  The span is also made current, so nested calls
    (and log records) attach to it; an exception is recorded on the
    span and re-raised."""
    if tracer is None:
        yield None
        return
    span = tracer.start(name, attrs=attrs)
    with tracer.use(span):
        try:
            yield span
        except Exception as e:
            tracer.end(span, attrs={"error": f"{e.__class__.__name__}: {e}"})
            raise
        else:
            tracer.end(span)
