"""Black-box incident capture (ISSUE 10): when something goes wrong,
keep the evidence.

A production incident used to leave only whatever happened to still be
in the bounded rings by the time a human looked.  The black box flips
that: the moment an alert FIRES (or on operator demand — SIGUSR1, the
``/debugz`` surfaces), the controller atomically dumps a
self-contained **incident bundle**: the flight-recorder dump (spans,
decision records, still-open spans), the metrics snapshot, the TSDB
windows behind the alert verdict, the alert engine's rules + state,
informer store digests, policy/serving debug state, and a config
summary.  ``python -m tpu_autoscaler.obs replay <bundle>`` then
re-renders the traces and re-evaluates the alert rules offline — any
chaos seed or production incident becomes a deterministic artifact.

Write discipline:

- **atomic**: the bundle is written to ``<name>.tmp`` and
  ``os.replace``d into place — a reader never sees a half bundle;
- **unique**: names carry a UTC timestamp, the pid and a monotonic
  counter, so two captures in the same second never clobber each
  other (the bug the SIGUSR1 dump path had — fixed alongside);
- **bounded**: at most ``max_bundles`` are retained; older ones are
  pruned oldest-first.  Capture is rate-limited (``min_interval``)
  per *reason* so a flapping alert cannot fill a disk;
- **crash-only**: a failing capture logs and counts, never takes a
  pass down.

Captures NEVER run on the reconcile thread: the alert-fire path
schedules them onto a throwaway thread (``capture_async``) just like
SIGUSR1 — building and serializing a full bundle is
O(series × retained points) and would stall the control loop exactly
during the incident it is documenting.  Every read a capture performs
goes through the guarded read paths (recorder lock, TSDB seqlock,
bounded-retry copies), so a capture can never deadlock the controller
either; BlackBox's own bookkeeping is lock-guarded because the writer
thread and the scheduler share it.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import time
from typing import Any, Callable

from tpu_autoscaler import concurrency

log = logging.getLogger(__name__)

#: Bundle format version (bumped on breaking layout changes; the
#: replay CLI refuses versions it does not know).
BUNDLE_VERSION = 1

_counter = itertools.count(1)


def unique_dump_path(prefix: str, now: float | None = None,
                     ext: str = ".json") -> str:
    """A dump path that is unique even for same-second captures:
    UTC timestamp + pid + process-lifetime counter."""
    now = time.time() if now is None else now
    stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime(now))
    return f"{prefix}-{stamp}-{os.getpid()}-{next(_counter):04d}{ext}"


def write_atomic(path: str, body: dict[str, Any]) -> str:
    """JSON-dump ``body`` to ``path`` atomically (tmp + rename).
    ``allow_nan=False``: an ``inf`` anywhere in a bundle is a bug and
    must fail at capture time, not in a strict parser later.  A
    FAILED write unlinks its tmp before re-raising: captures retry on
    the next firing (the rate-limit slot is only consumed by
    success), and uniquely-named half-written tmps would otherwise
    accumulate outside ``_prune``'s ``.json`` filter — an unbounded
    disk leak exactly when disk pressure is likeliest
    (review-found)."""
    tmp = f"{path}.tmp"
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(body, f, indent=2, default=str, allow_nan=False)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    os.replace(tmp, path)
    return path


class BlackBox:
    """Incident-bundle writer.  ``bundle_fn`` is the zero-arg producer
    (``Controller.incident_bundle``); everything else is file and
    thread discipline.  ``metrics``: optional registry — a successful
    capture counts ``incident_bundles_written``, wherever it ran."""

    def __init__(self, directory: str,
                 bundle_fn: Callable[[], dict[str, Any]],
                 clock: Callable[[], float] = time.time,
                 min_interval_seconds: float = 300.0,
                 max_bundles: int = 16,
                 prefix: str = "tpu-autoscaler-incident",
                 metrics: Any = None) -> None:
        self.directory = directory
        self.bundle_fn = bundle_fn
        self.clock = clock
        self.min_interval_seconds = min_interval_seconds
        self.max_bundles = max_bundles
        self.prefix = prefix
        self.metrics = metrics
        # Shared between the scheduling (reconcile) thread and the
        # throwaway writer threads.
        self._lock = concurrency.Lock()
        self._last_capture: dict[str, float] = {}
        self._in_flight: set[str] = set()
        self.captured = 0
        self.errors = 0

    def _limited(self, reason: str, now: float) -> bool:
        last = self._last_capture.get(reason)
        return (last is not None
                and now - last < self.min_interval_seconds)

    def capture(self, reason: str, force: bool = False) -> str | None:
        """Write one bundle SYNCHRONOUSLY (operator/SIGUSR1/test
        paths — never call from the reconcile thread; the alert-fire
        path uses ``capture_async``).  Returns the path, or None when
        rate-limited or failed.  ``force`` bypasses the rate limit."""
        now = self.clock()
        with self._lock:
            if not force and self._limited(reason, now):
                log.debug("incident capture for %r rate-limited",
                          reason)
                return None
        # The rate-limit slot is consumed only by a SUCCESSFUL write
        # (below): a transient failure (disk full, unwritable dir)
        # must not suppress the retry for min_interval — the one
        # artifact the black box exists to preserve would be lost
        # exactly during the incident (review-found).
        try:
            body = dict(self.bundle_fn())
            body.setdefault("bundle", {}).update(
                {"version": BUNDLE_VERSION, "reason": reason,
                 "captured_at": now})
            os.makedirs(self.directory, exist_ok=True)
            path = unique_dump_path(
                os.path.join(self.directory, self.prefix), now=now)
            write_atomic(path, body)
            with self._lock:
                self._last_capture[reason] = now
                self.captured += 1
            if self.metrics is not None:
                self.metrics.inc("incident_bundles_written")
            log.warning("incident bundle (%s) written to %s", reason,
                        path)
            self._prune()
            return path
        except Exception:  # noqa: BLE001 — diagnostics must not kill
            with self._lock:
                self.errors += 1
            log.exception("incident capture for %r failed", reason)
            return None

    def capture_async(self, reason: str) -> bool:
        """Schedule a capture on a throwaway thread (the alert-fire
        path): building + serializing a bundle is O(series × retained
        points) and must never stall a reconcile pass (review-found).
        Returns True when scheduled; False when rate-limited or a
        capture for the same reason is still in flight."""
        now = self.clock()
        with self._lock:
            if reason in self._in_flight or self._limited(reason, now):
                return False
            self._in_flight.add(reason)

        def _run() -> None:
            try:
                self.capture(reason)
            finally:
                with self._lock:
                    self._in_flight.discard(reason)

        concurrency.Thread(target=_run, daemon=True,
                           name="incident-capture").start()
        return True

    def _prune(self) -> None:
        try:
            mine = sorted(
                n for n in os.listdir(self.directory)
                if n.startswith(self.prefix) and n.endswith(".json"))
            for name in mine[:-self.max_bundles]:
                os.unlink(os.path.join(self.directory, name))
        except OSError:
            log.debug("incident-bundle prune failed", exc_info=True)


def load_bundle(path: str) -> dict[str, Any]:
    """Read + version-check one bundle (the replay CLI's loader).
    Plain flight-recorder dumps (no ``bundle`` key) load too — the
    replay degrades to trace rendering without alert re-evaluation."""
    with open(path, encoding="utf-8") as f:
        body = json.load(f)
    meta = body.get("bundle")
    if meta is not None and meta.get("version", 0) > BUNDLE_VERSION:
        raise ValueError(
            f"bundle {path!r} has version {meta.get('version')}; this "
            f"build reads <= {BUNDLE_VERSION}")
    return body
