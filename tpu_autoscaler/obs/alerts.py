"""SLO burn-rate alerting over the in-process TSDB (ISSUE 10).

The controller emits rich signals but nothing *watched* them: an
operator learned about a degrading scale-up p99 from a user, not from
the autoscaler.  This module closes that loop — a declarative rule set
evaluated once per reconcile pass by the Reconciler (crash-only
``_alerts_pass``): the autoscaler finally watches itself.

Rule kinds (all windows in the controller's injected clock, so
simulated-time runs — chaos, replay — evaluate identically):

- ``burn_rate`` — multi-window burn rate over a declared latency
  histogram, SRE-style: the miss fraction (observations above
  ``slo_bound``) over BOTH a fast and a slow window must burn the
  error budget (``1 - objective``) faster than ``burn_threshold``.
  The fast window makes firing prompt; the slow window keeps one
  blip from paging.
- ``rate`` — a counter's per-second rate over ``window`` crosses
  ``threshold`` (watch staleness, waste-budget spend).
- ``gauge_below`` — a gauge's window-average sits below ``threshold``
  (serving SLO attainment).
- ``pass_duration`` — mean pass duration over ``window`` (delta of
  ``reconcile_seconds:sum`` over delta of ``:count``) exceeds
  ``threshold`` — the control loop's own latency budget.

Hysteresis is pass-counted, not wall-clocked: ``for_passes``
consecutive breaching evaluations fire, ``clear_passes`` consecutive
clean ones resolve — a rule can never flap faster than the reconcile
interval.  Transitions land in the notifier, the flight recorder's
pass record, and a ``tpu_autoscaler_alerts_active_<rule>`` gauge
family (wired by the Reconciler); a new firing can also trigger a
black-box incident capture (obs/blackbox.py).

Engine state is reconcile-thread-only; ``debug_state()`` copies with
the bounded-retry pattern for the ``/debugz`` thread.  The TAO6xx
checker (analysis/metricsdoc.py) keeps every rule's ``metric``
pointing at a real exported family AND every rule present in
docs/OPERATIONS.md's alert catalog, both directions.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any

from tpu_autoscaler.obs.profiler import (
    PHASE_METRIC_PREFIX,
    PHASES as _PROFILE_PHASES,
)

log = logging.getLogger(__name__)

_KINDS = ("burn_rate", "rate", "gauge_below", "pass_duration",
          "phase_share_drift")


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """One declarative alert.  ``metric`` names the exported family
    the rule watches (the TAO6xx drift anchor); ``runbook`` points at
    the operator doc anchor rendered in notifications."""

    name: str
    metric: str
    kind: str
    # burn_rate
    slo_bound: float = 0.0       # histogram le-bound counted as good
    objective: float = 0.99      # fraction that must be good
    fast_window: float = 300.0
    slow_window: float = 1800.0
    burn_threshold: float = 2.0
    min_events: int = 1          # fewer observations in-window: no verdict
    # rate / gauge_below / pass_duration
    window: float = 300.0
    threshold: float = 0.0
    # hysteresis
    for_passes: int = 2
    clear_passes: int = 3
    severity: str = "page"
    runbook: str = "docs/OPERATIONS.md#alert-catalog"
    # Histogram family whose latest TSDB exemplar (trace_id, value)
    # rides the firing notification (ISSUE 14): the page names a
    # concrete sampled trace, not just a number ("" = none).
    exemplar_family: str = ""
    # phase_share_drift (ISSUE 20): the reconcile phases whose SHARE
    # of the pass the rule watches (fast window vs slow baseline);
    # ``threshold`` is the share-point drift that breaches.
    phases: tuple[str, ...] = ()

    def __post_init__(self) -> None:
        if self.kind not in _KINDS:
            raise ValueError(f"unknown alert kind {self.kind!r}")
        # A JSON round-trip (as_dict -> from_dict) hands back a list.
        object.__setattr__(self, "phases", tuple(self.phases))

    def as_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, body: dict[str, Any]) -> "AlertRule":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in body.items() if k in known})


def default_rules() -> tuple[AlertRule, ...]:
    """The production alert catalog (docs/OPERATIONS.md "Alert
    catalog" — the TAO6xx checker holds the two in lockstep)."""
    return (
        AlertRule(
            name="scaleup-latency-burn", metric="scale_up_latency_seconds",
            kind="burn_rate", slo_bound=360.0, objective=0.99,
            fast_window=600.0, slow_window=3600.0, burn_threshold=2.0,
            min_events=1, for_passes=2, clear_passes=5,
            runbook="docs/OPERATIONS.md#alert-catalog"),
        AlertRule(
            name="serving-slo-attainment", metric="serving_slo_attainment",
            kind="gauge_below", window=600.0, threshold=0.9,
            for_passes=3, clear_passes=5, severity="page",
            # The firing page carries a concrete sampled slow-request
            # trace (ISSUE 14) — the tail-report CLI's entry point.
            exemplar_family="serving_request_latency_ticks"),
        AlertRule(
            name="watch-staleness", metric="watch_failures",
            kind="rate", window=600.0, threshold=1.0 / 60.0,
            for_passes=3, clear_passes=5, severity="ticket"),
        AlertRule(
            name="policy-waste-budget",
            metric="wasted_prewarm_chip_seconds",
            kind="rate", window=3600.0, threshold=120_000.0 / 3600.0,
            for_passes=2, clear_passes=5, severity="ticket"),
        AlertRule(
            name="pass-duration-budget", metric="reconcile_seconds",
            kind="pass_duration", window=600.0, threshold=0.25,
            for_passes=3, clear_passes=5, severity="ticket"),
        # Cost ledger rules (ISSUE 11, docs/COST.md).  Both are rate
        # rules over cumulative ledger counters, so the per-second
        # rate reads directly: chip-seconds/s == average chips in the
        # state; $/s == average $-proxy burn.
        AlertRule(
            name="stranded-capacity-burn",
            metric="cost_chip_seconds_stranded",
            kind="rate", window=1800.0, threshold=8.0,
            for_passes=3, clear_passes=5, severity="ticket"),
        AlertRule(
            name="cost-budget-burn", metric="cost_dollar_proxy_total",
            kind="rate", window=3600.0, threshold=500.0 / 3600.0,
            for_passes=2, clear_passes=5, severity="ticket"),
        # Repack thrash (ISSUE 12, docs/REPACK.md): migrations are
        # background savings, not churn — more than ~12 an hour means
        # the repacker is chasing its own tail (gangs bouncing between
        # tiers, or aborts burning budget with nothing to show).
        AlertRule(
            name="repack-thrash",
            metric="repack_migrations_started",
            kind="rate", window=3600.0, threshold=12.0 / 3600.0,
            for_passes=3, clear_passes=5, severity="ticket"),
        # Shard imbalance (ISSUE 13, docs/SHARDING.md): shard_balance
        # is mean-load/max-load over busy shards (1.0 = even; the
        # serial path exports a constant 1.0, so the rule is defined
        # in every mode).  A sustained sub-0.25 balance means one
        # class/pool owns nearly all demand and the partition buys
        # little — repin workloads or lower --reconcile-shards.
        AlertRule(
            name="shard-imbalance", metric="shard_balance",
            kind="gauge_below", window=900.0, threshold=0.25,
            for_passes=5, clear_passes=5, severity="ticket"),
        # Control-plane phase drift (ISSUE 20, docs/OBSERVABILITY.md
        # "Control-plane profiling"): watches per-phase SHARES of the
        # reconcile pass (profiler self-time series), not absolutes —
        # a busier fleet is fine, a shifted mix is a regression.  The
        # transition summary names the drifting phase; the offline
        # twin is `tpu-autoscaler perf-report --against`.
        AlertRule(
            name="phase-share-drift", metric="reconcile_seconds",
            kind="phase_share_drift", fast_window=300.0,
            slow_window=3600.0, threshold=0.15, min_events=5,
            for_passes=3, clear_passes=5, severity="ticket",
            phases=_PROFILE_PHASES),
    )


@dataclasses.dataclass
class AlertState:
    firing: bool = False
    breach_streak: int = 0
    ok_streak: int = 0
    fired_at: float | None = None
    resolved_at: float | None = None
    fired_count: int = 0
    last_value: float | None = None


@dataclasses.dataclass(frozen=True)
class Transition:
    rule: str
    firing: bool           # True: fired this pass; False: resolved
    t: float
    value: float | None
    severity: str
    runbook: str
    summary: str
    #: Latest (t, value, trace_id) exemplar of the rule's
    #: ``exemplar_family`` at fire time, when the TSDB has one.
    exemplar: tuple[float, float, str] | None = None


@dataclasses.dataclass(frozen=True)
class AlertPassResult:
    transitions: tuple[Transition, ...]
    active: tuple[str, ...]
    evaluated: int


class AlertEngine:
    """Evaluates the rule set against a :class:`TimeSeriesDB` each
    pass.  Pure over (tsdb, now) except for the hysteresis state —
    which is exactly what the offline replay recomputes."""

    def __init__(self, rules: tuple[AlertRule, ...] | None = None) -> None:
        self.rules = tuple(rules) if rules is not None else default_rules()
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError("duplicate alert rule names")
        self._state: dict[str, AlertState] = {
            r.name: AlertState() for r in self.rules}
        # Last evaluation detail per rule (the drifting phase's name
        # for phase_share_drift) — what _summary renders so the page
        # says WHICH phase moved, not just that one did.
        self._detail: dict[str, str] = {}

    # -- rule evaluation ----------------------------------------------

    @staticmethod
    def _burn(rule: AlertRule, tsdb: Any, now: float,
              window: float) -> tuple[bool, float] | None:
        total = tsdb.delta(f"{rule.metric}:count", now - window, now)
        if total is None or total < rule.min_events:
            return None
        if total <= 0:
            return (False, 0.0)
        good = tsdb.delta(f"{rule.metric}:le:{rule.slo_bound:g}",
                          now - window, now)
        if good is None:
            # The :le: series does not exist — slo_bound matches no
            # declared histogram bucket bound.  A missing series is a
            # CONFIGURATION problem, not "zero good events": treating
            # it as 0 would page a guaranteed false positive on every
            # observation (review-found).  No verdict; the rule shows
            # last_value=None in debug_state forever, which is the
            # visible symptom to fix.
            return None
        miss = max(0.0, 1.0 - good / total)
        burn = miss / max(1e-9, 1.0 - rule.objective)
        return (burn >= rule.burn_threshold, burn)

    def _breaching(self, rule: AlertRule, tsdb: Any,
                   now: float) -> tuple[bool, float | None]:
        """One stateless evaluation: (condition breached, the measured
        value behind the verdict)."""
        if rule.kind == "burn_rate":
            fast = self._burn(rule, tsdb, now, rule.fast_window)
            slow = self._burn(rule, tsdb, now, rule.slow_window)
            if fast is None or slow is None:
                return (False, None)
            return (fast[0] and slow[0], fast[1])
        if rule.kind == "rate":
            d = tsdb.delta(rule.metric, now - rule.window, now)
            if d is None:
                return (False, None)
            rate = d / rule.window
            return (rate > rule.threshold, rate)
        if rule.kind == "gauge_below":
            ts, vs = tsdb.points(rule.metric, now - rule.window, now)
            if len(vs):
                mean = float(vs.mean())
            else:
                # A flat gauge appends only on change + heartbeat, so
                # a short window can be point-free while the value is
                # perfectly known: sparse is not absent.
                last = tsdb.value_at(rule.metric, now)
                if last is None:
                    return (False, None)
                mean = last
            return (mean < rule.threshold, mean)
        if rule.kind == "phase_share_drift":
            return self._phase_drift(rule, tsdb, now)
        # pass_duration
        count = tsdb.delta(f"{rule.metric}:count", now - rule.window, now)
        total = tsdb.delta(f"{rule.metric}:sum", now - rule.window, now)
        if not count or total is None:
            return (False, None)
        mean = total / count
        return (mean > rule.threshold, mean)

    def _phase_shares(self, rule: AlertRule, tsdb: Any, now: float,
                      window: float) -> dict[str, float] | None:
        """Per-phase share of attributed self time over the window
        (None: the window saw no phase data at all)."""
        seconds: dict[str, float] = {}
        for phase in rule.phases:
            d = tsdb.delta(f"{PHASE_METRIC_PREFIX}{phase}:sum",
                           now - window, now)
            if d is not None and d > 0.0:
                seconds[phase] = d
        total = sum(seconds.values())
        if total <= 0.0:
            return None
        return {p: s / total for p, s in seconds.items()}

    def _phase_drift(self, rule: AlertRule, tsdb: Any,
                     now: float) -> tuple[bool, float | None]:
        """Multi-window share comparison: breach when any phase's
        fast-window share exceeds its slow-window baseline by more
        than ``threshold`` share points.  Shares, not absolutes — the
        denominator is the same attributed total both sides, so load
        growth cancels and only a shifted mix registers."""
        passes = tsdb.delta(f"{PHASE_METRIC_PREFIX}other:count",
                            now - rule.fast_window, now)
        if passes is None or passes < rule.min_events:
            return (False, None)
        fast = self._phase_shares(rule, tsdb, now, rule.fast_window)
        slow = self._phase_shares(rule, tsdb, now, rule.slow_window)
        if fast is None or slow is None:
            return (False, None)
        worst, worst_phase = 0.0, None
        for phase in rule.phases:
            drift = fast.get(phase, 0.0) - slow.get(phase, 0.0)
            if worst_phase is None or drift > worst:
                worst, worst_phase = drift, phase
        if worst_phase is None:
            return (False, None)
        self._detail[rule.name] = (
            f"phase {worst_phase} share "
            f"{fast.get(worst_phase, 0.0):.1%} vs "
            f"{slow.get(worst_phase, 0.0):.1%} baseline "
            f"(drift {worst:+.1%}, threshold {rule.threshold:.0%})")
        return (worst > rule.threshold, worst)

    # -- the per-pass entry point -------------------------------------

    def evaluate(self, tsdb: Any, now: float) -> AlertPassResult:
        """Evaluate every rule once; returns this pass's transitions
        and the currently-active set.  Reconcile thread only."""
        transitions: list[Transition] = []
        for rule in self.rules:
            state = self._state[rule.name]
            breached, value = self._breaching(rule, tsdb, now)
            state.last_value = value
            if breached:
                state.breach_streak += 1
                state.ok_streak = 0
            else:
                state.ok_streak += 1
                state.breach_streak = 0
            if not state.firing \
                    and state.breach_streak >= rule.for_passes:
                state.firing = True
                state.fired_at = now
                state.fired_count += 1
                exemplar = None
                if rule.exemplar_family \
                        and hasattr(tsdb, "exemplar_latest"):
                    try:
                        exemplar = tsdb.exemplar_latest(
                            rule.exemplar_family)
                    except Exception:  # noqa: BLE001 — advisory only
                        exemplar = None
                transitions.append(Transition(
                    rule=rule.name, firing=True, t=now, value=value,
                    severity=rule.severity, runbook=rule.runbook,
                    summary=self._summary(rule, value, firing=True,
                                          exemplar=exemplar),
                    exemplar=exemplar))
            elif state.firing and state.ok_streak >= rule.clear_passes:
                state.firing = False
                state.resolved_at = now
                transitions.append(Transition(
                    rule=rule.name, firing=False, t=now, value=value,
                    severity=rule.severity, runbook=rule.runbook,
                    summary=self._summary(rule, value, firing=False)))
        active = tuple(sorted(n for n, s in self._state.items()
                              if s.firing))
        return AlertPassResult(transitions=tuple(transitions),
                               active=active,
                               evaluated=len(self.rules))

    def _summary(self, rule: AlertRule, value: float | None, firing: bool,
                 exemplar: tuple[float, float, str] | None = None) -> str:
        what = "FIRING" if firing else "resolved"
        shown = "n/a" if value is None else f"{value:.4g}"
        if rule.kind == "burn_rate":
            detail = (f"burn={shown} (threshold "
                      f"{rule.burn_threshold:g}, SLO {rule.objective:g} "
                      f"within {rule.slo_bound:g}s)")
        elif rule.kind == "rate":
            detail = f"rate={shown}/s (threshold {rule.threshold:g}/s)"
        elif rule.kind == "gauge_below":
            detail = f"avg={shown} (floor {rule.threshold:g})"
        elif rule.kind == "phase_share_drift":
            detail = self._detail.get(
                rule.name,
                f"share drift={shown} (threshold {rule.threshold:.0%})")
        else:
            detail = f"mean={shown}s (budget {rule.threshold:g}s)"
        tail = ""
        if exemplar is not None:
            # (t, value, trace_id): the page names a concrete trace.
            tail = f" — exemplar trace {exemplar[2]} ({exemplar[1]:g})"
        return (f"alert {rule.name} {what}: {rule.metric} {detail} — "
                f"{rule.runbook}{tail}")

    # -- introspection -------------------------------------------------

    def firing(self) -> tuple[str, ...]:
        return tuple(sorted(n for n, s in self._state.items() if s.firing))

    def state_of(self, name: str) -> AlertState:
        return self._state[name]

    def debug_state(self) -> dict[str, Any]:
        """JSON-able engine state for ``/debugz`` and incident
        bundles: the rule set (full params — what the offline replay
        re-instantiates) plus per-rule hysteresis state.  The /debugz
        thread reads reconcile-thread state concurrently, but the
        ``_state`` dict's KEYS are frozen at construction — only
        AlertState scalar attributes mutate — so a plain single-pass
        copy can never hit a resize mid-iteration (no bounded-retry
        needed here, unlike the variable-shape debug tables)."""
        return {
            "rules": [r.as_dict() for r in self.rules],
            "state": {
                name: {"firing": s.firing,
                       "breach_streak": s.breach_streak,
                       "ok_streak": s.ok_streak,
                       "fired_at": s.fired_at,
                       "resolved_at": s.resolved_at,
                       "fired_count": s.fired_count,
                       "last_value": s.last_value}
                for name, s in self._state.items()},
        }

    @classmethod
    def from_debug_state(cls, body: dict[str, Any]) -> "AlertEngine":
        """Fresh engine (zeroed hysteresis) with the bundle's rule
        set — the offline replay's starting point."""
        return cls(tuple(AlertRule.from_dict(r)
                         for r in body.get("rules", ())))
