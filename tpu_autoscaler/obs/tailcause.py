"""Tail-latency root-cause attribution (ISSUE 14): from "p99 is
burning" down to WHY, in one causal chain.

Input is any flight-recorder dump or incident bundle that carries
sampled request traces (serving/reqtrace.py).  The analyzer:

1. collects the **tail set** — every ``request-*`` trace whose root
   missed the SLO (``slo_miss``) or was lost to a drain handoff —
   optionally restricted to a time window (defaulting to the
   ``serving-slo-attainment`` alert's breach window when the bundle
   carries alert state);
2. decomposes each tail trace into its attributed phases
   (``queue_wait`` / ``prefill`` / ``decode`` / ``preempt_requeue`` /
   ``drain_handoff``) and sums them into per-phase totals and
   fractions — "where the tail's time went";
3. correlates with the bundle's TSDB over the same window: KV
   occupancy, queue depth, preemption rate, sampler drop rate — the
   aggregate context the per-request story sits in;
4. when the dominant term is queue wait — requests waiting for a
   replica that was not there — it **cross-links the control plane**:
   the ``scaleup-*`` trace overlapping the window whose provision
   would have absorbed the wait, with its own phase decomposition.
   The verdict then reads ``scaleup-lag``: user-visible p99 burn
   attributed through the data plane down to stockout / quota /
   actuation latency in one chain.

The analysis is a pure function of the dump, so the live capture
(``Controller.incident_bundle`` records it at alert-fire time) and
``python -m tpu_autoscaler.obs replay``'s offline re-run must agree —
the replay exits 2 when the recorded and recomputed dominant cause
diverge (the PR 10 alert-divergence discipline, extended to the data
plane).
"""

from __future__ import annotations

import math
from typing import Any

from tpu_autoscaler.obs.render import _all_spans

#: Phase span names, in render order.
PHASES = ("queue_wait", "prefill", "decode", "preempt_requeue",
          "drain_handoff")

#: TSDB series correlated next to the tail decomposition.
CORRELATES = ("serving_queue_depth", "serving_kv_occupancy",
              "serving_preempted_per_s", "serving_trace_dropped_per_s",
              "serving_slo_attainment")

#: The serving SLO alert whose breach window anchors the default
#: analysis window.
SERVING_ALERT = "serving-slo-attainment"


def _series_window(tsdb: dict[str, Any] | None, name: str,
                   start: float, end: float) -> dict[str, float] | None:
    """mean/max/last of one dumped series inside [start, end], read
    straight off the dump's tier rows (no TimeSeriesDB rebuild — the
    analyzer must work on a plain JSON bundle)."""
    if not tsdb:
        return None
    body = (tsdb.get("series") or {}).get(name)
    if not body:
        return None
    vals: list[float] = []
    for t, v in body.get("raw", ()):
        if start <= t <= end:
            vals.append(float(v))
    if not vals:
        # Fall back to downsampled last-values, then to the newest
        # retained point at-or-before the window (a flat gauge may
        # have no in-window points at all — sparse is not absent).
        for tier in ("mid", "coarse"):
            for row in body.get(tier, ()):
                if start <= row[0] <= end:
                    vals.append(float(row[1]))
    if not vals:
        before = [(t, v) for t, v in body.get("raw", ()) if t <= end]
        if before:
            vals = [float(before[-1][1])]
    if not vals:
        return None
    return {"mean": round(sum(vals) / len(vals), 4),
            "max": round(max(vals), 4),
            "last": round(vals[-1], 4)}


def tail_requests(dump: dict[str, Any],
                  start: float = -math.inf,
                  end: float = math.inf) -> list[dict[str, Any]]:
    """The tail set: per-trace phase decompositions of every sampled
    request that missed the SLO or was lost, rooted in [start, end]."""
    spans = _all_spans(dump)
    by_trace: dict[str, list[dict[str, Any]]] = {}
    for s in spans:
        tid = s["trace_id"]
        if tid.startswith("request-"):
            by_trace.setdefault(tid, []).append(s)
    out: list[dict[str, Any]] = []
    for tid, group in by_trace.items():
        roots = [s for s in group
                 if s["name"] == "request" and s["end"] is not None]
        if not roots:
            continue
        root = roots[0]
        attrs = root.get("attrs", {})
        if not (attrs.get("slo_miss") or attrs.get("lost")):
            continue
        if not (start <= root["start"] <= end):
            continue
        phases = {p: 0.0 for p in PHASES}
        for s in group:
            if s["name"] in phases and s["end"] is not None:
                phases[s["name"]] += s["end"] - s["start"]
        out.append({
            "trace_id": tid,
            "start": root["start"],
            "latency": attrs.get("latency_ticks",
                                 (root["end"] or root["start"])
                                 - root["start"]),
            "lost": bool(attrs.get("lost")),
            "preemptions": attrs.get("preemptions", 0),
            "n": attrs.get("n", 1),
            "phases": phases,
        })
    out.sort(key=lambda r: r["start"])
    return out


def _scaleup_link(dump: dict[str, Any], start: float,
                  end: float) -> dict[str, Any] | None:
    """The control-plane cross-link: the scale-up trace whose
    provision window overlaps the tail window — the capacity that,
    had it landed earlier, would have absorbed the queue wait.  Picks
    the overlapping scale-up with the LONGEST root duration (the
    slowest provision is the one that made users wait)."""
    spans = _all_spans(dump)
    best: dict[str, Any] | None = None
    best_dur = -1.0
    for s in spans:
        if s["name"] != "scale_up" or not \
                s["trace_id"].startswith("scaleup-"):
            continue
        s_end = s["end"] if s["end"] is not None else end
        if s_end < start or s["start"] > end:
            continue
        dur = s_end - s["start"]
        if dur > best_dur:
            best_dur = dur
            best = s
    if best is None:
        return None
    tid = best["trace_id"]
    phases: dict[str, float] = {}
    for s in spans:
        if s["trace_id"] == tid and s["span_id"] != best["span_id"] \
                and s["end"] is not None:
            phases[s["name"]] = round(
                phases.get(s["name"], 0.0)
                + (s["end"] - s["start"]), 4)
    return {
        "trace_id": tid,
        "start": best["start"],
        "end": best["end"],
        "duration_s": (None if best["end"] is None
                       else round(best_dur, 4)),
        "open": best["end"] is None,
        "gang": best.get("attrs", {}).get("gang"),
        "phases": phases,
    }


def _window(bundle: dict[str, Any]) -> tuple[float, float]:
    """Default analysis window: the serving-SLO alert's breach window
    when the bundle carries one (fired_at - rule window → capture),
    else unbounded."""
    alerts = bundle.get("alerts") or {}
    state = (alerts.get("state") or {}).get(SERVING_ALERT) or {}
    fired_at = state.get("fired_at")
    if fired_at is None:
        return (-math.inf, math.inf)
    window = 600.0
    for rule in alerts.get("rules", ()):
        if rule.get("name") == SERVING_ALERT:
            window = float(rule.get("window", 600.0))
            break
    end = math.inf
    captured = (bundle.get("bundle") or {}).get("captured_at")
    resolved = state.get("resolved_at")
    if resolved is not None and resolved > fired_at:
        end = resolved
    elif captured is not None:
        end = captured
    return (fired_at - window, end)


def analyze(bundle: dict[str, Any], *,
            window: tuple[float, float] | None = None
            ) -> dict[str, Any]:
    """The tail-report: tail set, phase attribution, TSDB correlates,
    and — when queue wait dominates — the scale-up cross-link.
    Deterministic over the bundle (the offline-divergence contract)."""
    start, end = window if window is not None else _window(bundle)
    tail = tail_requests(bundle, start, end)
    totals = {p: 0.0 for p in PHASES}
    weighted = {p: 0.0 for p in PHASES}
    for r in tail:
        n = max(1, int(r.get("n", 1)))
        for p in PHASES:
            totals[p] += r["phases"][p]
            weighted[p] += r["phases"][p] * n
    grand = sum(weighted.values())
    fractions = {p: (round(weighted[p] / grand, 4) if grand else 0.0)
                 for p in PHASES}
    dominant = max(PHASES, key=lambda p: weighted[p]) if grand \
        else None
    report: dict[str, Any] = {
        "window": [None if math.isinf(start) else start,
                   None if math.isinf(end) else end],
        "tail_requests": len(tail),
        "tail_cohort_weight": int(sum(max(1, int(r.get("n", 1)))
                                      for r in tail)),
        "phase_ticks": {p: round(totals[p], 4) for p in PHASES},
        "phase_fractions": fractions,
        "dominant_phase": dominant,
        "examples": [r["trace_id"] for r in
                     sorted(tail, key=lambda r: -r["latency"])[:5]],
    }
    correlates: dict[str, Any] = {}
    tsdb = bundle.get("tsdb")
    for name in CORRELATES:
        stats = _series_window(tsdb, name, start, end)
        if stats is not None:
            correlates[name] = stats
    report["correlates"] = correlates
    exemplars = (tsdb or {}).get("exemplars", {})
    if exemplars:
        report["exemplars"] = {
            fam: rows[-1] for fam, rows in exemplars.items() if rows}
    cause = dominant
    if dominant == "queue_wait":
        # Requests waited for capacity.  If a scale-up was in flight
        # (or landed late) over the same window, the wait IS the
        # provision latency: cross-link the control-plane trace.
        link = _scaleup_link(bundle, start, end)
        if link is not None:
            report["scaleup"] = link
            cause = "scaleup-lag"
        else:
            cause = "queue-wait"
    elif dominant == "preempt_requeue":
        cause = "kv-pressure"
    report["dominant_cause"] = cause
    return report


def render_report(report: dict[str, Any]) -> str:
    """Human rendering for the ``tail-report`` CLI."""
    if report.get("tail_requests", 0) == 0:
        return ("no tail-captured requests in the window — either the "
                "SLO held, or request tracing was off "
                "(serving/reqtrace.py)")
    lines = []
    w = report.get("window") or [None, None]
    wtxt = " (whole retention)" if w[0] is None else \
        f" over [{w[0]:g}, {w[1]:g}]" if w[1] is not None else \
        f" since {w[0]:g}"
    lines.append(
        f"{report['tail_requests']} tail-captured request trace(s), "
        f"cohort weight {report.get('tail_cohort_weight')}{wtxt}")
    lines.append("phase attribution (cohort-weighted):")
    fr = report["phase_fractions"]
    ticks = report["phase_ticks"]
    for p in PHASES:
        if ticks.get(p, 0.0) <= 0.0:
            continue
        mark = "  <-- dominant" if p == report.get("dominant_phase") \
            else ""
        lines.append(f"  {p:<16} {fr[p] * 100:6.1f}%  "
                     f"({ticks[p]:g} ticks){mark}")
    if report.get("correlates"):
        lines.append("aggregate context (TSDB, same window):")
        for name, stats in sorted(report["correlates"].items()):
            lines.append(f"  {name:<28} mean={stats['mean']:g} "
                         f"max={stats['max']:g}")
    lines.append(f"dominant cause: {report.get('dominant_cause')}")
    link = report.get("scaleup")
    if link:
        dur = ("still open" if link.get("open")
               else f"{link.get('duration_s'):g}s")
        lines.append(
            f"cross-link: scale-up {link['trace_id']} ({dur}) "
            f"overlapped the tail window — the wait is provision "
            f"latency; `tpu-autoscaler trace {link['trace_id']}` "
            f"decomposes it")
        if link.get("phases"):
            for name, secs in sorted(link["phases"].items(),
                                     key=lambda kv: -kv[1]):
                lines.append(f"    {name:<20} {secs:g}s")
    for tid in report.get("examples", ()):
        lines.append(f"  example: {tid}")
    return "\n".join(lines)
