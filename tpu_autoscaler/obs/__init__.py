"""Observability layer (ISSUE 5): end-to-end decision tracing.

- ``trace``    — dependency-free spans + tracer with context propagation
                 (one trace per gang scale-up; docs/OBSERVABILITY.md);
- ``recorder`` — bounded flight recorder of completed spans and
                 per-pass decision records, served on ``/debugz`` and
                 dumped on SIGUSR1;
- ``render``   — the ``trace`` / ``explain`` CLI's formatting layer.
"""

from tpu_autoscaler.obs.recorder import (
    FlightRecorder,
    install_sigusr1,
    trace_gaps,
)
from tpu_autoscaler.obs.trace import (
    Span,
    Tracer,
    current_span,
    current_trace_id,
    maybe_span,
)

__all__ = [
    "FlightRecorder",
    "Span",
    "Tracer",
    "current_span",
    "current_trace_id",
    "install_sigusr1",
    "maybe_span",
    "trace_gaps",
]
