"""Observability layer (ISSUE 5 tracing + ISSUE 10 retention/alerting).

- ``trace``    — dependency-free spans + tracer with context propagation
                 (one trace per gang scale-up; docs/OBSERVABILITY.md);
- ``recorder`` — bounded flight recorder of completed spans and
                 per-pass decision records, served on ``/debugz`` and
                 dumped on SIGUSR1;
- ``render``   — the ``trace`` / ``explain`` CLI's formatting layer;
- ``tsdb``     — in-process time-series store (ring-per-series, raw →
                 10 s → 5 min downsampling) fed per pass from the
                 metrics snapshot; served on ``/debugz/tsdb`` and the
                 ``metrics-history`` CLI;
- ``alerts``   — declarative SLO burn-rate alert engine evaluated each
                 reconcile pass (the autoscaler watches itself);
- ``blackbox`` — atomic incident bundles on alert fire / SIGUSR1;
                 replayed offline via ``python -m tpu_autoscaler.obs
                 replay``;
- ``tailcause`` — tail-latency root-cause attribution over sampled
                 request traces (ISSUE 14): phase decomposition, TSDB
                 correlation, and the scale-up cross-link behind the
                 ``tail-report`` CLI.
- ``profiler`` — continuous control-plane profiler (ISSUE 20): the
                 per-pass phase-tree self-time ledger with its
                 conservation identity, plus the optional collapsed-
                 stack sampler; served on ``/debugz/profile``;
- ``perfreport`` — windowed phase decomposition + two-window diff
                 over the profiler's TSDB series — the ``perf-report``
                 CLI's computation layer and the offline twin of the
                 ``phase-share-drift`` sentinel.
"""

from tpu_autoscaler.obs.alerts import (
    AlertEngine,
    AlertRule,
    default_rules,
)
from tpu_autoscaler.obs.blackbox import BlackBox, load_bundle
from tpu_autoscaler.obs.perfreport import (
    decompose as perf_decompose,
    diff as perf_diff,
    render_diff as render_perf_diff,
    render_report as render_perf_report,
)
from tpu_autoscaler.obs.profiler import (
    PassProfiler,
    StackSampler,
    rebuild_from_events,
)
from tpu_autoscaler.obs.recorder import (
    FlightRecorder,
    install_sigusr1,
    trace_gaps,
)
from tpu_autoscaler.obs.tailcause import (
    analyze as tail_analyze,
    render_report as render_tail_report,
)
from tpu_autoscaler.obs.trace import (
    Span,
    Tracer,
    current_span,
    current_trace_id,
    maybe_span,
)
from tpu_autoscaler.obs.tsdb import TimeSeriesDB

__all__ = [
    "AlertEngine",
    "AlertRule",
    "BlackBox",
    "FlightRecorder",
    "PassProfiler",
    "Span",
    "StackSampler",
    "TimeSeriesDB",
    "Tracer",
    "current_span",
    "current_trace_id",
    "default_rules",
    "install_sigusr1",
    "load_bundle",
    "maybe_span",
    "perf_decompose",
    "perf_diff",
    "rebuild_from_events",
    "render_perf_diff",
    "render_perf_report",
    "render_tail_report",
    "tail_analyze",
    "trace_gaps",
]
