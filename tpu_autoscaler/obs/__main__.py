"""Offline incident replay (ISSUE 10):
``python -m tpu_autoscaler.obs replay <bundle>``.

A black-box bundle is a deterministic artifact: it carries the flight
recorder's spans + decision records, the TSDB windows, and the alert
engine's rules + state as of capture.  Replay re-renders the traces
and re-evaluates the alert rules *offline* — rebuilding the TSDB from
the bundle, instantiating a fresh engine from the bundled rule set,
and stepping it over the recorded pass timestamps — then checks the
offline firing decision against what the live controller recorded.

ISSUE 14 extends the same discipline to the data plane: the replay
renders the bundle's sampled ``request-*`` traces and re-runs the
tail-report (obs/tailcause.py) offline; when the bundle recorded a
tail-report at capture time, the offline dominant-cause attribution
must match it — both ways (a recorded report the offline run cannot
reproduce AND an offline report the capture never recorded are
divergence).

ISSUE 20 adds the control-plane half: the replay recomputes the
perf-report (phase decomposition) from the bundle's TSDB and
re-verifies the profiler's conservation identity over the bundled
per-pass ring; divergence from the capture-time report — both ways —
exits 2 like the tail-report.  Pre-profiler bundles degrade
render-only.

Exit codes (tests and the chaos alert gate key on them):

- 0 — offline evaluation reproduces the live firing decision (and
      the capture-time tail-report and perf-report, when recorded);
- 2 — divergence (the bundle's recorded state and the offline
      re-evaluation disagree — evidence of nondeterminism or a rule
      evaluation bug);
- 1 — unreadable/unsupported bundle.

Caveat, stated rather than hidden: the recorder's pass ring and the
TSDB tiers are bounded, so a bundle captured long after a firing may
no longer retain the passes (or raw windows) that produced it; replay
compares only over the retained history and says so.
"""

from __future__ import annotations

import argparse
import sys
from typing import Any

from tpu_autoscaler.obs import perfreport, tailcause
from tpu_autoscaler.obs.alerts import AlertEngine
from tpu_autoscaler.obs.blackbox import load_bundle
from tpu_autoscaler.obs.profiler import (
    CONSERVATION_ABS,
    CONSERVATION_REL,
)
from tpu_autoscaler.obs.render import list_traces, render_passes
from tpu_autoscaler.obs.tsdb import TimeSeriesDB


def replay_alerts(bundle: dict[str, Any]) -> dict[str, Any]:
    """Re-evaluate the bundled alert rules over the bundled TSDB at
    every retained pass timestamp.  Returns a JSON-able report:
    per-rule offline transitions, the offline-vs-live verdict, and
    the retained-history bounds."""
    alerts = bundle.get("alerts")
    tsdb_dump = bundle.get("tsdb")
    if not alerts or not tsdb_dump:
        return {"skipped": "bundle carries no alerts/tsdb sections"}
    db = TimeSeriesDB.from_dump(tsdb_dump)
    engine = AlertEngine.from_debug_state(alerts)
    pass_times = sorted(p["t"] for p in bundle.get("passes", ())
                        if isinstance(p.get("t"), (int, float)))
    transitions: list[dict[str, Any]] = []
    for t in pass_times:
        result = engine.evaluate(db, t)
        for tr in result.transitions:
            transitions.append({"rule": tr.rule, "firing": tr.firing,
                                "t": tr.t, "value": tr.value})
    offline = {name: engine.state_of(name) for name
               in (r.name for r in engine.rules)}
    live = alerts.get("state", {})
    matches: dict[str, dict[str, Any]] = {}
    ok = True
    for name, state in offline.items():
        recorded = live.get(name)
        if not isinstance(recorded, dict):
            continue  # live state unavailable (mid-mutation copy)
        want_firing = bool(recorded.get("firing"))
        offline_fired = state.fired_count > 0
        # A live "ever fired" is only comparable when the firing
        # landed inside the retained pass history.
        fired_at = recorded.get("fired_at")
        comparable_fired = (
            fired_at is not None and pass_times
            and pass_times[0] <= fired_at <= pass_times[-1])
        entry: dict[str, Any] = {
            "live_firing": want_firing,
            "offline_firing": state.firing,
            "offline_fired": offline_fired,
            "firing_match": state.firing == want_firing,
        }
        if comparable_fired:
            entry["live_fired_at"] = fired_at
            entry["fired_match"] = offline_fired
        elif not recorded.get("fired_count", 0):
            # Live NEVER fired this rule: any offline firing across
            # the replayed passes is divergence too — the check must
            # cut both ways (review-found: a spurious offline
            # fire-and-resolve previously slipped through as
            # "reproduced").
            entry["fired_match"] = not offline_fired
        if not entry.get("fired_match", True):
            ok = False
        if not entry["firing_match"]:
            ok = False
        matches[name] = entry
    return {
        "passes_replayed": len(pass_times),
        "window": ([pass_times[0], pass_times[-1]] if pass_times
                   else None),
        "transitions": transitions,
        "rules": matches,
        "reproduced": ok,
    }


def replay_tailcause(bundle: dict[str, Any]) -> dict[str, Any]:
    """Re-run the tail-report offline and compare its dominant-cause
    attribution with the one recorded at capture time.  Both-ways:
    a recorded verdict the offline run contradicts AND an offline
    verdict where none was recorded (on a bundle that HAS request
    traces) are divergence."""
    offline = tailcause.analyze(bundle)
    recorded = bundle.get("tailcause")
    report: dict[str, Any] = {
        "offline_dominant": offline.get("dominant_cause"),
        "offline_tail_requests": offline.get("tail_requests", 0),
        "offline": offline,
    }
    if recorded is None:
        # A pre-ISSUE-14 bundle (or the analyzer crashed at capture):
        # comparable only when the offline run finds a tail — then
        # the capture SHOULD have recorded one.
        report["recorded_dominant"] = None
        report["reproduced"] = offline.get("tail_requests", 0) == 0
        return report
    report["recorded_dominant"] = recorded.get("dominant_cause")
    report["recorded_tail_requests"] = recorded.get(
        "tail_requests", 0)
    report["reproduced"] = (
        offline.get("dominant_cause")
        == recorded.get("dominant_cause")
        and offline.get("tail_requests", 0)
        == recorded.get("tail_requests", 0))
    return report


def replay_profile(bundle: dict[str, Any]) -> dict[str, Any]:
    """Re-run the control-plane phase decomposition offline (ISSUE 20)
    and compare it with the report recorded at capture time, plus
    re-verify the conservation identity over the bundled per-pass
    ring.  Both ways: a recorded report the offline run contradicts
    AND an offline decomposition on a bundle that recorded none are
    divergence.  A pre-profiler bundle (no ``profile`` section, no
    ``pass_phase_seconds_*`` series) degrades render-only: skipped,
    reproduced."""
    offline = perfreport.from_bundle(bundle)
    recorded_profile = bundle.get("profile")
    report: dict[str, Any] = {
        "offline_dominant": offline.get("dominant"),
        "offline": offline,
    }
    if not isinstance(recorded_profile, dict) \
            or "report" not in recorded_profile:
        # Comparable only when the offline run finds phase series —
        # then the capture SHOULD have recorded a profile.
        report["recorded_dominant"] = None
        report["reproduced"] = not offline.get("phases")
        if report["reproduced"]:
            report["skipped"] = "bundle carries no profile section"
        return report
    recorded = recorded_profile.get("report") or {}
    report["recorded_dominant"] = recorded.get("dominant")
    # Conservation re-check from the bundle alone: every retained
    # pass profile must still satisfy sum(self times) == window
    # within the tolerance the profiler declared at capture.
    conservation = recorded_profile.get("conservation") or {}
    tol_abs = conservation.get("tolerance_abs", CONSERVATION_ABS)
    tol_rel = conservation.get("tolerance_rel", CONSERVATION_REL)
    ring_violations = 0
    for p in recorded_profile.get("ring", ()):
        window = p.get("window_s")
        phases = p.get("phases") or {}
        if window is None or not phases:
            continue
        attributed = sum(phases.values())
        if abs(attributed - window) > tol_abs + tol_rel * abs(window):
            ring_violations += 1
    report["ring_violations"] = ring_violations
    report["recorded_violations"] = conservation.get("violations", 0)
    shares_match = True
    names = (set(offline.get("phases", {}))
             | set(recorded.get("phases", {})))
    for name in names:
        a = offline.get("phases", {}).get(name, {}).get("share", 0.0)
        b = recorded.get("phases", {}).get(name, {}).get("share", 0.0)
        if abs(a - b) > 1e-9:
            shares_match = False
    report["reproduced"] = (
        offline.get("dominant") == recorded.get("dominant")
        and shares_match and ring_violations == 0)
    return report


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpu_autoscaler.obs",
        description="Offline tooling over black-box incident bundles "
                    "(docs/OBSERVABILITY.md).")
    sub = parser.add_subparsers(dest="cmd", required=True)
    rp = sub.add_parser(
        "replay",
        help="re-render traces and re-evaluate alert rules offline")
    rp.add_argument("bundle", help="incident bundle path (or any "
                                   "flight-recorder dump)")
    rp.add_argument("--last", type=int, default=3,
                    help="recent decision records to print (0=all)")
    rp.add_argument("-q", "--quiet", action="store_true",
                    help="verdict only (no trace/pass rendering)")
    args = parser.parse_args(argv)

    try:
        bundle = load_bundle(args.bundle)
    except (OSError, ValueError) as e:
        print(f"cannot read bundle {args.bundle!r}: {e}",
              file=sys.stderr)
        return 1

    meta = bundle.get("bundle", {})
    if meta:
        print(f"bundle v{meta.get('version')} reason={meta.get('reason')} "
              f"captured_at={meta.get('captured_at')}")
    if not args.quiet:
        print("\n== traces")
        print(list_traces(bundle))
        req = list_traces(bundle, prefix="request-")
        if "(no traces" not in req:
            print("\n== sampled request traces")
            print(req)
        print("\n== recent decisions")
        print(render_passes(bundle, last=args.last))
        cost = bundle.get("cost")
        if cost and not cost.get("unavailable"):
            # The ledger snapshot rides every bundle (ISSUE 11): the
            # incident's bill renders next to its traces — the same
            # text `tpu-autoscaler cost-report --from <bundle>` emits.
            from tpu_autoscaler.cost import render_bill

            print("\n== cost")
            print(render_bill(cost))

    # Data-plane half (ISSUE 14): re-run the tail-report offline and
    # hold it to the capture-time verdict.
    tail = replay_tailcause(bundle)
    if tail["offline_tail_requests"] or tail.get(
            "recorded_tail_requests"):
        print("\n== tail-report (offline re-run)")
        print(tailcause.render_report(tail["offline"]))
        print(f"recorded dominant cause: "
              f"{tail.get('recorded_dominant')}  "
              f"[{'match' if tail['reproduced'] else 'MISMATCH'}]")

    # Control-plane half (ISSUE 20): re-run the perf-report offline
    # and hold it to the capture-time decomposition + conservation.
    prof = replay_profile(bundle)
    if "skipped" not in prof:
        print("\n== perf-report (offline re-run)")
        print(perfreport.render_report(prof["offline"]))
        print(f"recorded dominant phase: "
              f"{prof.get('recorded_dominant')}  "
              f"ring conservation violations: "
              f"{prof.get('ring_violations', 0)}  "
              f"[{'match' if prof['reproduced'] else 'MISMATCH'}]")

    report = replay_alerts(bundle)
    if "skipped" in report:
        print(f"\n== alerts: {report['skipped']}")
        if not tail["reproduced"]:
            print("OFFLINE TAIL-REPORT DIVERGED from the capture-time "
                  "attribution", file=sys.stderr)
            return 2
        if not prof["reproduced"]:
            print("OFFLINE PERF-REPORT DIVERGED from the capture-time "
                  "phase decomposition", file=sys.stderr)
            return 2
        return 0
    print(f"\n== alert replay: {report['passes_replayed']} passes over "
          f"window {report['window']}")
    for tr in report["transitions"]:
        what = "FIRING" if tr["firing"] else "resolved"
        print(f"  t={tr['t']:g}  {tr['rule']}  {what}  "
              f"value={tr['value']}")
    for name, entry in sorted(report["rules"].items()):
        verdict = "match" if entry["firing_match"] \
            and entry.get("fired_match", True) else "MISMATCH"
        print(f"  {name}: live_firing={entry['live_firing']} "
              f"offline_firing={entry['offline_firing']}  [{verdict}]")
    if not tail["reproduced"]:
        print("OFFLINE TAIL-REPORT DIVERGED from the capture-time "
              "attribution", file=sys.stderr)
        return 2
    if not prof["reproduced"]:
        print("OFFLINE PERF-REPORT DIVERGED from the capture-time "
              "phase decomposition", file=sys.stderr)
        return 2
    if report["reproduced"]:
        print("offline evaluation reproduces the live firing decision")
        return 0
    print("OFFLINE EVALUATION DIVERGED from the recorded alert state",
          file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
