"""Human rendering of flight-recorder dumps — the `trace` / `explain`
CLI subcommands' formatting layer, kept importable so tests and other
tools can render a dump without going through click.

Input is always the JSON shape ``FlightRecorder.dump`` produces (from
``/debugz``, a SIGUSR1 file, or ``Controller.debug_dump()`` directly).
"""

from __future__ import annotations

from typing import Any, Iterable


def _fmt_duration(seconds: float | None) -> str:
    if seconds is None:
        return "…open"
    if seconds >= 60:
        return f"{seconds / 60:.1f}m"
    if seconds >= 1:
        return f"{seconds:.2f}s"
    return f"{seconds * 1e3:.1f}ms"


def _fmt_attrs(attrs: dict[str, Any]) -> str:
    if not attrs:
        return ""
    inner = " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
    return f"  [{inner}]"


def _all_spans(dump: dict[str, Any]) -> list[dict[str, Any]]:
    return list(dump.get("spans", ())) + list(dump.get("active_spans", ()))


def trace_ids(dump: dict[str, Any], prefix: str = "") -> list[str]:
    """Distinct trace ids, oldest first (first-span order).
    ``prefix`` filters by trace-id family (``request-`` lists only
    the sampled data-plane traces; ``scaleup-`` only the control
    plane's)."""
    seen: dict[str, None] = {}
    for span in _all_spans(dump):
        if span["trace_id"].startswith(prefix):
            seen.setdefault(span["trace_id"])
    return list(seen)


def list_traces(dump: dict[str, Any], prefix: str = "") -> str:
    """One line per trace: id, root span, start, duration."""
    lines = []
    for tid in trace_ids(dump, prefix):
        spans = [s for s in _all_spans(dump) if s["trace_id"] == tid]
        roots = [s for s in spans if s.get("parent_id") is None]
        root = min(roots or spans, key=lambda s: (s["start"], s["seq"]))
        lines.append(
            f"{tid}  {root['name']}"
            f"  start={root['start']:g}"
            f"  {_fmt_duration(root.get('duration_s'))}"
            f"  spans={len(spans)}{_fmt_attrs(root.get('attrs', {}))}")
    return "\n".join(lines) if lines else "(no traces recorded)"


def render_trace(dump: dict[str, Any], trace_id: str) -> str:
    """The single-tree view: one scale-up from first-Unschedulable to
    last-pod-Running, children in causal order.  Causal = recording
    ``seq``, not start timestamp: a retroactive span (a pass's shared
    observe window) and a submitted-at-pass-start provision share
    timestamps under simulated time, but recording order is the order
    things actually happened."""
    spans = [s for s in _all_spans(dump) if s["trace_id"] == trace_id]
    if not spans:
        known = ", ".join(trace_ids(dump)) or "(none)"
        return f"trace {trace_id!r} not found; known traces: {known}"
    by_parent: dict[str | None, list[dict[str, Any]]] = {}
    span_ids = {s["span_id"] for s in spans}
    for s in spans:
        parent = s.get("parent_id")
        # A parent evicted from the ring leaves an orphan: promote it to
        # the top level rather than dropping it silently.
        if parent is not None and parent not in span_ids:
            parent = None
        by_parent.setdefault(parent, []).append(s)
    for children in by_parent.values():
        children.sort(key=lambda s: s["seq"])

    lines = [f"trace {trace_id}"]

    def self_time(s: dict[str, Any]) -> float | None:
        """Self time = duration minus the children's durations (ISSUE
        20 satellite): the tree answers "where did the time go"
        without the profiler attached.  None while the span (or any
        child) is still open — a partial subtraction would lie."""
        dur = s.get("duration_s")
        if dur is None:
            return None
        child_total = 0.0
        for child in by_parent.get(s["span_id"], ()):
            child_dur = child.get("duration_s")
            if child_dur is None:
                return None
            child_total += child_dur
        return max(0.0, dur - child_total)

    def walk(parent: str | None, depth: int) -> None:
        for s in by_parent.get(parent, ()):
            events = (f"  ({len(s['events'])} events)"
                      if s.get("events") else "")
            # The self column only renders where it differs from the
            # duration (the span has closed children) — leaf rows
            # would just repeat the duration.
            self_s = self_time(s)
            own = ""
            if self_s is not None and by_parent.get(s["span_id"]):
                own = f"  self={_fmt_duration(self_s)}"
            lines.append(
                f"{'  ' * depth}{'└─ ' if depth else ''}{s['name']}"
                f"  {_fmt_duration(s.get('duration_s'))}{own}"
                f"  @{s['start']:g}"
                f"{_fmt_attrs(s.get('attrs', {}))}{events}")
            walk(s["span_id"], depth + 1)

    walk(None, 0)
    return "\n".join(lines)


def span_names_in_order(dump: dict[str, Any], trace_id: str) -> list[str]:
    """Span names of one trace in causal (recording seq) order — the
    e2e acceptance assertion's view."""
    spans = [s for s in _all_spans(dump) if s["trace_id"] == trace_id]
    return [s["name"] for s in sorted(spans, key=lambda s: s["seq"])]


def render_passes(dump: dict[str, Any], last: int = 5,
                  subject: str | None = None) -> str:
    """The explainability view: recent reconcile decision records, each
    with its inputs digest and per-unit reasons.  ``subject`` filters
    events by substring (gang name, unit id, shape…)."""
    passes: Iterable[dict[str, Any]] = dump.get("passes", ())
    picked = list(passes)[-last:] if last else list(passes)
    if not picked:
        return "(no reconcile passes recorded)"
    lines = []
    for rec in picked:
        inputs = rec.get("inputs", {})
        lines.append(
            f"pass #{rec.get('pass')}  t={rec.get('t'):g}  "
            f"nodes={inputs.get('nodes')} pods={inputs.get('pods')} "
            f"pending_gangs={inputs.get('pending_gangs')} "
            f"digest={inputs.get('digest')} "
            f"took={_fmt_duration(rec.get('duration_s'))}")
        events = rec.get("events", ())
        shown = [e for e in events
                 if subject is None or subject in str(e.get("subject", ""))]
        if not shown:
            lines.append("  (no decisions"
                         + (f" matching {subject!r}" if subject else "")
                         + ")")
        for e in shown:
            extra = {k: v for k, v in e.items()
                     if k not in ("subject", "decision", "reason")}
            lines.append(f"  {e.get('subject')}: {e.get('decision')}"
                         + (f" — {e['reason']}" if e.get("reason") else "")
                         + _fmt_attrs(extra))
    return "\n".join(lines)
