"""Flight recorder: bounded in-memory history of completed spans and
per-pass decision records, dumpable from a LIVE process.

Why (ISSUE 5): when a production controller is stuck or slow, the
Prometheus endpoint says *that* something is wrong, not *why*.  The
recorder keeps the last N completed spans (the per-phase latency
anatomy of recent scale-ups) and the last M reconcile decision records
("why did/didn't we provision") in two lock-guarded ring buffers, and
exposes them two ways that both work without a restart:

- ``/debugz`` on the metrics port (``Metrics.serve(port, debugz=...)``)
  returns the dump as JSON;
- SIGUSR1 (``install_sigusr1``) writes the dump to a timestamped file
  under ``/tmp`` — for controllers whose metrics port is firewalled or
  was never enabled.

Retention is bounded by construction (``collections.deque`` maxlen):
the recorder can never grow past ``max_spans + max_passes`` entries no
matter how long the process runs — crash-only discipline applied to
introspection state.  Everything in a dump is JSON-serializable with
``allow_nan=False`` (guarded empty-summary exports; no ``inf`` leaks).
"""

from __future__ import annotations

import collections
import logging
import signal
import time
from typing import Any, Callable

from tpu_autoscaler import concurrency
from tpu_autoscaler.obs.trace import Span

log = logging.getLogger(__name__)

#: Ring bounds (docs/OBSERVABILITY.md).  4096 spans ≈ 500 scale-ups of
#: 8 spans each; 512 passes ≈ 40 min of 5 s-interval history.
DEFAULT_MAX_SPANS = 4096
DEFAULT_MAX_PASSES = 512


class FlightRecorder:
    """Lock-guarded ring buffers of spans + decision records.

    Writers: the reconcile thread (most spans, every pass record) and
    the informer watch threads (relist spans) — hence the lock.  The
    ``/debugz`` HTTP handler and the SIGUSR1 handler read via
    ``dump()``, which copies under the lock.
    """

    def __init__(self, max_spans: int = DEFAULT_MAX_SPANS,
                 max_passes: int = DEFAULT_MAX_PASSES) -> None:
        self._lock = concurrency.Lock()
        self._spans: collections.deque[Span] = collections.deque(
            maxlen=max_spans)
        self._passes: collections.deque[dict[str, Any]] = collections.deque(
            maxlen=max_passes)
        self._spans_recorded = 0
        self._passes_recorded = 0

    # -- writers ----------------------------------------------------------

    def record_span(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)
            self._spans_recorded += 1

    def record_pass(self, record: dict[str, Any]) -> None:
        with self._lock:
            self._passes.append(record)
            self._passes_recorded += 1

    # -- readers ----------------------------------------------------------

    def dump(self, tracer: Any = None) -> dict[str, Any]:
        """JSON-able snapshot: completed spans (recording order — causal
        within a thread), decision records, and — when the owning tracer
        is passed — still-open spans (the "what is it stuck on" view)."""
        with self._lock:
            spans = [s.as_dict() for s in self._spans]
            passes = list(self._passes)
            counts = {"spans_recorded": self._spans_recorded,
                      "passes_recorded": self._passes_recorded,
                      "spans_retained": len(spans),
                      "passes_retained": len(passes)}
        out: dict[str, Any] = {"generated_at": time.time(),
                               "counts": counts,
                               "spans": spans, "passes": passes}
        if tracer is not None:
            out["active_spans"] = [s.as_dict()
                                   for s in tracer.active_spans()]
        return out


def trace_gaps(dump: dict[str, Any], trace_id: str) -> list[str]:
    """Completeness check for one recorded trace (ISSUE 7): the chaos
    engine's "every scale-up trace is complete" invariant, also usable
    against any ``/debugz`` / SIGUSR1 dump.

    Returns human-readable gaps (empty == complete):

    - the root span (``scale_up`` or ``slice_repair``) exists and is
      closed;
    - every span of the trace is closed (``end`` set);
    - a scale-up that dispatched work carries the full phase anatomy
      (observe/plan/dispatch/provision/node_registration) plus
      ``pods_running``; one that bound existing supply needs only
      ``pods_running``;
    - a slice repair carries its drain phase;
    - a repack migration (ISSUE 12) carries its drain phase and, when
      completed, the chip-seconds-saved attribution on the root;
    - a sampled request trace (ISSUE 14, serving/reqtrace.py) carries
      its ``queue_wait`` phase and — unless the request was lost to a
      drain handoff — a ``decode`` phase; a lost request carries the
      ``drain_handoff`` span instead.  Roots whose event journal
      overflowed (``truncated`` attr) are exempt from the phase
      checks (the truncation is declared, not silent).
    """
    spans = [s for s in dump.get("spans", []) if s["trace_id"] == trace_id]
    if not spans:
        return [f"trace {trace_id}: no spans recorded"]
    gaps: list[str] = []
    names = {s["name"] for s in spans}
    roots = [s for s in spans if s["parent_id"] is None]
    if not roots:
        gaps.append(f"trace {trace_id}: no root span")
    for s in spans:
        if s["end"] is None:
            gaps.append(f"trace {trace_id}: span {s['name']} "
                        f"({s['span_id']}) never closed")
    if "scale_up" in names:
        required: tuple[str, ...] = ("pods_running",)
        if "dispatch" in names:
            required += ("observe", "plan")
            # A trace whose every dispatched provision FAILED can still
            # complete off existing supply; one that provisioned must
            # show the registration phase too.
            if "provision" in names:
                required += ("node_registration",)
            elif "provision_failed" not in names:
                required += ("provision",)
        aborted = any(s["name"] == "scale_up" and "aborted" in s["attrs"]
                      for s in spans)
        if not aborted:
            for phase in required:
                if phase not in names:
                    gaps.append(f"trace {trace_id}: missing {phase} span")
    elif "slice_repair" in names:
        abandoned = any(s["name"] == "slice_repair"
                        and ("error" in s["attrs"]
                             or "aborted" in s["attrs"]) for s in spans)
        if not abandoned and "repair_drain" not in names:
            gaps.append(f"trace {trace_id}: missing repair_drain span")
    elif "request" in names:
        # ISSUE 14: a promoted data-plane request trace.  The phase
        # contract is shared by the real engines and the queueing-
        # model replay replicas, so it names only what BOTH record.
        for s in spans:
            if s["name"] != "request" or s["end"] is None:
                continue
            attrs = s["attrs"]
            if attrs.get("truncated"):
                continue
            if attrs.get("lost"):
                # A drain-lost request may never have been admitted
                # at all; its story is the handoff span alone.
                if "drain_handoff" not in names:
                    gaps.append(f"trace {trace_id}: lost request "
                                f"missing drain_handoff span")
                continue
            if "queue_wait" not in names:
                gaps.append(f"trace {trace_id}: missing queue_wait "
                            f"span")
            if "decode" not in names:
                gaps.append(f"trace {trace_id}: missing decode span")
            if attrs.get("preemptions", 0) \
                    and "preempt_requeue" not in names:
                gaps.append(f"trace {trace_id}: preempted request "
                            f"missing preempt_requeue span")
    elif "repack" in names:
        closed = [s for s in spans if s["name"] == "repack"
                  and s["end"] is not None]
        aborted = any("error" in s["attrs"] or "aborted" in s["attrs"]
                      for s in closed)
        if not aborted and closed and "repack_drain" not in names:
            gaps.append(f"trace {trace_id}: missing repack_drain span")
        for s in closed:
            if "aborted" in s["attrs"] or "error" in s["attrs"]:
                continue
            # A completed migration's root must carry its bill — the
            # chip-seconds-saved attribution IS the acceptance surface.
            if "chip_seconds_saved" not in s["attrs"]:
                gaps.append(f"trace {trace_id}: completed repack root "
                            f"missing chip_seconds_saved attribution")
    return gaps


def install_sigusr1(dump_fn: Callable[[], dict[str, Any]],
                    path_prefix: str = "/tmp/tpu-autoscaler-debugz") -> bool:
    """SIGUSR1 → write ``dump_fn()`` as JSON to a timestamped file.

    Returns False on platforms without SIGUSR1 (Windows).  Install from
    the main thread only (a Python signal.signal constraint).  The
    handler is crash-only: a failing dump logs and never takes the
    process down.

    File names are UNIQUE per capture (UTC stamp + pid + a monotonic
    counter, obs/blackbox.py): two signals in the same second used to
    clobber each other's dump — exactly the double-capture an incident
    produces — and the write is atomic (tmp + rename), so a reader
    polling the directory never sees a half-written dump.

    The dump runs on a THROWAWAY THREAD, never inline in the handler:
    Python signal handlers interrupt the main thread between bytecodes,
    and ``dump_fn`` acquires the recorder/tracer/metrics locks — all
    non-reentrant.  An inline dump that lands while the interrupted
    reconcile frame holds one of those locks would deadlock the very
    controller it exists to diagnose; a thread just blocks until the
    main thread releases the lock and then writes the file.
    """
    if not hasattr(signal, "SIGUSR1"):
        return False

    def _write() -> None:
        from tpu_autoscaler.obs.blackbox import (
            unique_dump_path,
            write_atomic,
        )

        path = unique_dump_path(path_prefix)
        try:
            write_atomic(path, dump_fn())
            log.warning("SIGUSR1: flight-recorder dump written to %s", path)
        except Exception:  # noqa: BLE001 — diagnostics must not kill
            log.exception("SIGUSR1 flight-recorder dump failed")

    def _handler(signum: int, frame: Any) -> None:
        # Raw threading on purpose: this fires only in production
        # processes (main.run), outside any scheduler's lifetime.
        import threading

        threading.Thread(target=_write, daemon=True,
                         name="debugz-dump").start()

    signal.signal(signal.SIGUSR1, _handler)
    return True
