"""Continuous control-plane profiler (ISSUE 20).

The reconcile loop can explain every slow request (tailcause) and
every chip-second (the cost ledger), but not its own milliseconds.
This module closes that gap with two pieces:

- :class:`PassProfiler` — a phase-tree profiler for the reconcile
  thread.  ``begin_pass`` / ``phase(name)`` / ``end_pass`` bracket the
  pass; every span is recorded and each phase is charged its SELF
  time (duration minus its direct children), the unattributed
  remainder of the window lands in the ``other`` phase, and the
  ledger-style conservation identity

      sum(self_seconds) + other == pass window   (within tolerance)

  is checked on every ``end_pass``; violations are counted, never
  raised (crash-only observability).  The incremental self-times must
  equal :func:`rebuild_from_events` over the recorded spans — that
  static rebuild is the property-test oracle, exactly like the cost
  ledger's rebuild-from-windows oracle.  Phases timed while NO pass
  is open (the router refresh between passes) accumulate in a
  separate out-of-pass ledger that is reported but deliberately
  outside the conservation identity.

- :class:`StackSampler` — an optional, low-rate sampling collector on
  a crash-only ``concurrency.Thread``: it snapshots the reconcile
  thread's stack via ``sys._current_frames`` at a few hertz and
  counts collapsed stacks (``a;b;c 42`` — flamegraph.pl's collapsed
  format) into a bounded table.  Sampling errors increment a counter
  and the loop keeps going; the table never grows past ``max_stacks``
  (overflow is counted, not stored).

Purity contract (TAP, analysis/purity.py): this module never reads a
wall clock — the caller injects a monotonic ``clock`` callable — and
performs no I/O, so a pass profile is replayable from its recorded
spans alone.  Thread discipline (TAT): every post-``__init__`` write
in :class:`StackSampler` sits under its lock.
"""

from __future__ import annotations

import sys
from collections import deque
from collections.abc import Callable, Iterator
from contextlib import contextmanager
from typing import Any, Protocol


class MetricsLike(Protocol):
    """The slice of MetricsRegistry the profiler publishes through."""

    def inc(self, name: str, by: float = 1.0) -> None: ...

    def observe(self, name: str, value: float) -> None: ...


#: Phases a reconcile pass decomposes into, in pass order.  Every
#: phase gets a ``pass_phase_seconds_<phase>`` observation on every
#: ``end_pass`` (zero when the phase did not run) so the TSDB series
#: exist in every mode and the share-drift sentinel's denominators
#: never go undefined mid-window.  ``other`` is the residual: window
#: time outside every explicit phase (gang grouping, pruning, record
#: assembly).  ``router_refresh`` is out-of-pass by construction (the
#: chaos/serving harness refreshes between passes) but keeps a series
#: here for the same reason.
PHASES: tuple[str, ...] = (
    "actuate_poll",
    "observe",
    "policy",
    "serving",
    "adapter_fold",
    "plan",
    "actuate_dispatch",
    "maintain",
    "cost_close",
    "obs_pass",
    "router_refresh",
    "other",
)

#: Conservation tolerance: float summation order differs between the
#: incremental ledger and the window arithmetic, so the identity holds
#: to rounding, not exactly.  abs + rel * window, ledger-style.
CONSERVATION_ABS = 1e-9
CONSERVATION_REL = 1e-6

#: Default bound on the ring of retained per-pass profiles.
RING_PASSES = 256

#: Metric family for per-phase self time (one summary per phase).
PHASE_METRIC_PREFIX = "pass_phase_seconds_"


def rebuild_from_events(
        events: list[tuple[str, float, float, int]]) -> dict[str, float]:
    """Recompute per-phase SELF seconds from a recorded span list.

    ``events`` rows are ``(name, start, end, parent_index)`` with
    ``parent_index == -1`` for top-level spans.  This is the static
    oracle for the incremental ledger: charge each span its duration,
    then refund that duration to its parent.  Property tests assert
    the incremental per-pass ``self_seconds`` (minus ``other``) equal
    this rebuild for arbitrary seeded phase trees.
    """
    self_times: dict[str, float] = {}
    for name, start, end, parent in events:
        dur = end - start
        self_times[name] = self_times.get(name, 0.0) + dur
        if 0 <= parent < len(events):
            pname = events[parent][0]
            self_times[pname] = self_times.get(pname, 0.0) - dur
    return self_times


class PassProfiler:
    """Phase-tree self-time ledger for the reconcile thread.

    Single-writer: ``begin_pass`` / ``phase`` / ``end_pass`` are only
    ever called from the reconcile thread (``phase`` additionally from
    whichever thread drives the router refresh between passes — by
    contract the same one).  Readers (``debug_state`` from the bundle
    thread) take bounded-retry copies, FlightRecorder-style.
    """

    def __init__(self, clock: Callable[[], float],
                 metrics: MetricsLike | None = None,
                 enabled: bool = True,
                 tolerance_abs: float = CONSERVATION_ABS,
                 tolerance_rel: float = CONSERVATION_REL,
                 ring_passes: int = RING_PASSES,
                 sampler: "StackSampler | None" = None) -> None:
        self._clock = clock
        self._metrics = metrics
        self.enabled = enabled
        self._tol_abs = tolerance_abs
        self._tol_rel = tolerance_rel
        # Open pass state.  _events rows are [name, start, end, parent]
        # (end filled on pop); _stack holds (event_index, child_total).
        self._pass_open = False
        self._pass_start = 0.0
        self._pass_seq = 0
        self._events: list[list[Any]] = []
        self._stack: list[list[Any]] = []
        self._self_seconds: dict[str, float] = {}
        # Cross-pass ledgers.
        self._cumulative: dict[str, float] = {}
        self._out_of_pass: dict[str, float] = {}
        self._pending_out_of_pass: dict[str, float] = {}
        self._ring: deque[dict[str, Any]] = deque(maxlen=ring_passes)
        self.passes_total = 0
        self.conservation_violations = 0
        self.forced_closes = 0
        self.last_conservation: tuple[float, float] | None = None
        self.sampler = sampler

    # -- pass bracketing ------------------------------------------------

    def begin_pass(self, t0: float) -> None:
        """Open a pass window at ``t0`` (the caller's perf-clock read).

        A still-open previous pass (an exception unwound past
        ``end_pass``) is force-closed first and counted as a FORCED
        close, not a conservation violation — an abandoned pass never
        ran the arithmetic, so it cannot have failed it (and chaos
        brownouts crash passes by design; its invariant asserts the
        violation counter stays zero across the run).
        """
        if not self.enabled:
            return
        if self._pass_open:
            self.forced_closes += 1
            if self._metrics is not None:
                self._metrics.inc("profiler_forced_closes")
            self._close_pass(t0, record=False)
        self._pass_open = True
        self._pass_start = t0
        self._pass_seq += 1
        self._events = []
        self._stack = []
        self._self_seconds = {}

    @contextmanager
    def phase(self, name: str) -> Iterator[None]:
        """Time a phase; nests freely; cheap no-op when disabled.

        Outside a pass window the span lands in the out-of-pass
        ledger instead of the pass tree.
        """
        if not self.enabled:
            yield
            return
        start = self._clock()
        parent = self._stack[-1][0] if self._stack else -1
        idx = len(self._events)
        events = self._events
        events.append([name, start, start, parent])
        self._stack.append([idx, 0.0])
        try:
            yield
        finally:
            # A force-close underneath this span swapped the events
            # list out (crash-only recovery already charged its
            # window); dropping the orphan beats popping a stack entry
            # that now belongs to a DIFFERENT pass's tree.
            if self._events is events:
                self._pop(self._clock())

    def _pop(self, end: float) -> None:
        idx, child_total = self._stack.pop()
        ev = self._events[idx]
        ev[2] = end
        dur = end - ev[1]
        self_time = dur - child_total
        if self._stack:
            self._stack[-1][1] += dur
        if self._pass_open:
            name = str(ev[0])
            self._self_seconds[name] = (
                self._self_seconds.get(name, 0.0) + self_time)
        else:
            # Out-of-pass span (router refresh between passes): charge
            # the ledger that end_pass flushes into the NEXT pass's
            # metric observations; the current tree is discarded once
            # the outermost out-of-pass span closes.
            name = str(ev[0])
            self._pending_out_of_pass[name] = (
                self._pending_out_of_pass.get(name, 0.0) + self_time)
            if not self._stack:
                self._events = []

    def end_pass(self) -> dict[str, Any]:
        """Close the pass: conservation check, metrics, ring append.

        Returns the per-pass profile dict for the pass record
        (``phases`` self-seconds including ``other``, the conservation
        verdict, and the dominant phase for the exemplar link).
        """
        if not self.enabled or not self._pass_open:
            return {}
        return self._close_pass(self._clock(), record=True)

    def _close_pass(self, t_end: float, record: bool) -> dict[str, Any]:
        # Force-close any spans an exception left open so the tree is
        # well-formed; their tails count toward the enclosing phase.
        while self._stack:
            self._pop(t_end)
        window = t_end - self._pass_start
        top_total = sum(ev[2] - ev[1] for ev in self._events
                        if ev[3] == -1)
        other = window - top_total
        phases = dict(self._self_seconds)
        phases["other"] = other
        attributed = sum(phases.values())
        tol = self._tol_abs + self._tol_rel * abs(window)
        violated = abs(attributed - window) > tol
        self.last_conservation = (attributed, window)
        self.passes_total += 1
        if violated:
            self.conservation_violations += 1
            if self._metrics is not None:
                self._metrics.inc("profiler_conservation_violations")
        for name, secs in phases.items():
            self._cumulative[name] = (
                self._cumulative.get(name, 0.0) + secs)
        out_of_pass = self._pending_out_of_pass
        self._pending_out_of_pass = {}
        for name, secs in out_of_pass.items():
            self._out_of_pass[name] = (
                self._out_of_pass.get(name, 0.0) + secs)
        if self._metrics is not None:
            # Observe EVERY declared phase every pass (zeros included)
            # so the series stay defined in every mode; out-of-pass
            # self time rides the same families, one pass late.
            for name in PHASES:
                value = phases.get(name, 0.0) + out_of_pass.get(name, 0.0)
                self._metrics.observe(f"pass_phase_seconds_{name}", value)
            for name in phases:
                if name not in PHASES:
                    self._metrics.observe(
                        f"pass_phase_seconds_{name}", phases[name])
        in_pass = {k: v for k, v in phases.items() if k != "other"}
        dominant = max(in_pass, key=lambda k: in_pass[k],
                       default="other") if in_pass else "other"
        info: dict[str, Any] = {
            "pass": self._pass_seq,
            "start": self._pass_start,
            "window_s": window,
            "phases": {k: round(v, 9) for k, v in phases.items()},
            "attributed_s": attributed,
            "conserved": not violated,
            "dominant": dominant,
            "events": [(str(e[0]), float(e[1]), float(e[2]), int(e[3]))
                       for e in self._events],
        }
        if out_of_pass:
            info["out_of_pass"] = {k: round(v, 9)
                                   for k, v in out_of_pass.items()}
        self._pass_open = False
        self._events = []
        self._self_seconds = {}
        if record:
            self._ring.append(info)
        return info

    # -- read side ------------------------------------------------------

    def ring(self) -> list[dict[str, Any]]:
        """The retained per-pass profiles, oldest first (bounded)."""
        return list(self._ring)

    @property
    def ring_limit(self) -> int:
        """The ring's declared bound (chaos re-asserts it per step)."""
        return self._ring.maxlen or 0

    def debug_state(self) -> dict[str, Any]:
        """Snapshot for ``/debugz/profile`` and incident bundles.

        May be called from the bundle-capture thread while the
        reconcile thread mutates the ledgers: bounded-retry copies,
        FlightRecorder-style — a contended snapshot degrades to
        ``{"unavailable": "mutating"}``, never blocks the pass.
        """
        for _ in range(5):
            try:
                state: dict[str, Any] = {
                    "enabled": self.enabled,
                    "passes_total": self.passes_total,
                    "phases": dict(self._cumulative),
                    "out_of_pass": dict(self._out_of_pass),
                    "conservation": {
                        "violations": self.conservation_violations,
                        "forced_closes": self.forced_closes,
                        "last": self.last_conservation,
                        "tolerance_abs": self._tol_abs,
                        "tolerance_rel": self._tol_rel,
                    },
                    "ring": [dict(p) for p in self._ring],
                }
                break
            except RuntimeError:  # dict/deque mutated under us
                continue
        else:
            return {"unavailable": "mutating"}
        if self.sampler is not None:
            state["sampler"] = self.sampler.debug_state()
        return state


class StackSampler:
    """Low-rate collapsed-stack sampler on a crash-only thread.

    ``start(thread_id)`` spawns a daemon ``concurrency.Thread`` that
    snapshots the target thread's stack ``hz`` times a second and
    counts collapsed stacks into a bounded table; ``collapsed()``
    renders flamegraph.pl's collapsed format.  A sampling error is
    counted and the loop keeps going; once ``max_stacks`` distinct
    stacks are held, new ones are dropped (counted), never stored.
    """

    def __init__(self, hz: float = 2.0, max_stacks: int = 512,
                 metrics: MetricsLike | None = None,
                 max_depth: int = 64) -> None:
        from tpu_autoscaler import concurrency
        self._hz = max(hz, 0.1)
        self._max_stacks = max_stacks
        self._max_depth = max_depth
        self._metrics = metrics
        self._lock = concurrency.Lock()
        self._stop = concurrency.Event()
        self._thread: Any = None
        self._target: int | None = None
        self._counts: dict[str, int] = {}
        self.samples_total = 0
        self.dropped_total = 0
        self.errors_total = 0

    def start(self, thread_id: int) -> None:
        """Begin sampling ``thread_id``; idempotent."""
        from tpu_autoscaler import concurrency
        with self._lock:
            if self._thread is not None:
                return
            self._target = thread_id
            thread = concurrency.Thread(
                target=self._run, name="profiler-sampler", daemon=True)
            self._thread = thread
        thread.start()

    def stop(self) -> None:
        """Stop and join the sampler thread (bounded wait)."""
        self._stop.set()
        with self._lock:
            thread = self._thread
            self._thread = None
        if thread is not None:
            thread.join(timeout=2.0)

    @property
    def running(self) -> bool:
        return self._thread is not None

    def _run(self) -> None:
        interval = 1.0 / self._hz
        while not self._stop.wait(interval):
            try:
                self._sample()
            except Exception:
                with self._lock:
                    self.errors_total += 1
                if self._metrics is not None:
                    self._metrics.inc("profiler_sampler_errors")

    def _sample(self) -> None:
        with self._lock:
            target = self._target
        if target is None:
            return
        frame = sys._current_frames().get(target)
        parts: list[str] = []
        depth = 0
        while frame is not None and depth < self._max_depth:
            code = frame.f_code
            parts.append(f"{code.co_filename.rsplit('/', 1)[-1]}:"
                         f"{code.co_name}")
            frame = frame.f_back
            depth += 1
        if not parts:
            return
        key = ";".join(reversed(parts))  # root first, leaf last
        dropped = False
        with self._lock:
            self.samples_total += 1
            if key in self._counts or len(self._counts) < self._max_stacks:
                self._counts[key] = self._counts.get(key, 0) + 1
            else:
                self.dropped_total += 1
                dropped = True
        if self._metrics is not None:
            self._metrics.inc("profiler_stack_samples")
            if dropped:
                self._metrics.inc("profiler_stacks_dropped")

    # -- read side ------------------------------------------------------

    def collapsed(self) -> list[str]:
        """Flamegraph-format lines (``stack;frames count``), sorted by
        count descending then stack, bounded by ``max_stacks``."""
        with self._lock:
            items = list(self._counts.items())
        items.sort(key=lambda kv: (-kv[1], kv[0]))
        return [f"{stack} {count}" for stack, count in items]

    def debug_state(self) -> dict[str, Any]:
        with self._lock:
            return {
                "hz": self._hz,
                "running": self._thread is not None,
                "samples_total": self.samples_total,
                "dropped_total": self.dropped_total,
                "errors_total": self.errors_total,
                "distinct_stacks": len(self._counts),
                "max_stacks": self._max_stacks,
            }
