"""Windowed control-plane phase decomposition (ISSUE 20).

The ``tpu-autoscaler perf-report`` CLI's computation layer — the
tail-report for the controller's OWN latency.  One code path serves
every source: a live ``/debugz/tsdb`` fetch, an incident bundle's
``tsdb`` section, or a SIGUSR1 dump file all carry a
``TimeSeriesDB.dump()`` body; :func:`decompose` rebuilds a queryable
store from it and answers "where did the control plane's seconds go"
over a window, and :func:`diff` names the regressing phase between
two windows/bundles — the offline twin of the ``phase-share-drift``
sentinel (obs/alerts.py), sharing its share math so the two can never
disagree about what "share" means.

Shares are per-phase SELF seconds over the sum of ALL phase self
seconds in the window (the profiler's conservation identity makes
that sum the reconcile wall-time, ``other`` included), so a fleet
that merely got busier does not register: only a shifted *mix* does.
"""

from __future__ import annotations

import math
from typing import Any

from tpu_autoscaler.obs.profiler import PHASE_METRIC_PREFIX, PHASES
from tpu_autoscaler.obs.tsdb import TimeSeriesDB


def _phase_seconds(db: TimeSeriesDB, start: float,
                   end: float) -> dict[str, float]:
    """Per-phase self seconds accumulated in ``[start, end]``.

    Reads the ``pass_phase_seconds_<phase>:sum`` cumulative series
    the profiler feeds each pass; a series the window never saw
    contributes zero.  Phases outside the declared tuple are picked
    up too (a custom phase must not silently vanish from reports).
    """
    names = set(PHASES)
    for series in db.series_names():
        if (series.startswith(PHASE_METRIC_PREFIX)
                and series.endswith(":sum")):
            names.add(series[len(PHASE_METRIC_PREFIX):-len(":sum")])
    out: dict[str, float] = {}
    for phase in sorted(names):
        d = db.delta(f"{PHASE_METRIC_PREFIX}{phase}:sum", start, end)
        if d is not None and d > 0.0:
            out[phase] = d
    return out


def decompose(tsdb_dump: dict[str, Any],
              window: float | None = None) -> dict[str, Any]:
    """Phase decomposition of a TSDB dump over its trailing window.

    Returns ``{"start", "end", "seconds", "phases": {phase:
    {"seconds", "share"}}, "dominant", "passes"}`` — shares of total
    attributed self time, dominant = largest non-``other`` share.
    ``window`` trims to the trailing seconds (None: whole dump).
    """
    db = TimeSeriesDB.from_dump(tsdb_dump)
    end = -math.inf
    for name in db.series_names():
        if name.startswith(PHASE_METRIC_PREFIX):
            ts, _ = db.points(name)
            if len(ts):
                end = max(end, float(ts[-1]))
    if math.isinf(end):
        return {"start": None, "end": None, "seconds": 0.0,
                "phases": {}, "dominant": None, "passes": 0}
    start = end - window if window is not None else -math.inf
    seconds = _phase_seconds(db, start, end)
    total = sum(seconds.values())
    phases = {p: {"seconds": round(s, 9),
                  "share": (s / total) if total > 0 else 0.0}
              for p, s in sorted(seconds.items())}
    in_pass = {p: s for p, s in seconds.items() if p != "other"}
    dominant = max(in_pass, key=lambda p: in_pass[p]) if in_pass else None
    passes = db.delta(f"{PHASE_METRIC_PREFIX}other:count", start, end)
    return {
        "start": None if math.isinf(start) else start,
        "end": end,
        "seconds": round(total, 9),
        "phases": phases,
        "dominant": dominant,
        "passes": int(passes) if passes else 0,
    }


def from_bundle(bundle: dict[str, Any],
                window: float | None = None) -> dict[str, Any]:
    """Decompose an incident bundle's ``tsdb`` section (empty report
    when the bundle predates the profiler — render-only degrade)."""
    dump = bundle.get("tsdb")
    if not isinstance(dump, dict):
        return decompose({}, window)
    return decompose(dump, window)


def diff(before: dict[str, Any], after: dict[str, Any],
         min_share_delta: float = 0.0) -> dict[str, Any]:
    """Name the regressing phase between two decompositions.

    Compares per-phase SHARES (not absolute seconds — a busier fleet
    is not a regression, a shifted mix is).  ``regressing`` is the
    phase with the largest share increase above ``min_share_delta``
    (None when nothing moved that much).
    """
    names = sorted(set(before.get("phases", {}))
                   | set(after.get("phases", {})))
    deltas: dict[str, dict[str, float]] = {}
    for name in names:
        b = before.get("phases", {}).get(name, {})
        a = after.get("phases", {}).get(name, {})
        deltas[name] = {
            "share_before": b.get("share", 0.0),
            "share_after": a.get("share", 0.0),
            "share_delta": a.get("share", 0.0) - b.get("share", 0.0),
            "seconds_before": b.get("seconds", 0.0),
            "seconds_after": a.get("seconds", 0.0),
        }
    regressing = None
    worst = min_share_delta
    for name, row in deltas.items():
        if name != "other" and row["share_delta"] > worst:
            worst = row["share_delta"]
            regressing = name
    return {"phases": deltas, "regressing": regressing,
            "worst_share_delta": (deltas[regressing]["share_delta"]
                                  if regressing else 0.0)}


# -- renderers (the CLI's text layer) ---------------------------------


def render_report(report: dict[str, Any]) -> str:
    """Human phase-decomposition table, largest share first."""
    lines = ["control-plane phase decomposition"]
    if not report.get("phases"):
        lines.append("  (no pass_phase_seconds_* series in this "
                     "window — profiler off or pre-profiler dump)")
        return "\n".join(lines)
    span = ("whole dump" if report.get("start") is None
            else f"{report['end'] - report['start']:.0f}s window")
    lines.append(f"  window: {span}  attributed: "
                 f"{report['seconds'] * 1e3:.1f}ms over "
                 f"{report.get('passes', 0)} passes")
    rows = sorted(report["phases"].items(),
                  key=lambda kv: -kv[1]["share"])
    for name, row in rows:
        mark = "  <- dominant" if name == report.get("dominant") else ""
        lines.append(f"  {name:<18} {row['share'] * 100:6.2f}%  "
                     f"{row['seconds'] * 1e3:10.2f}ms{mark}")
    return "\n".join(lines)


def render_diff(delta: dict[str, Any]) -> str:
    """Human diff table naming the regressing phase."""
    lines = ["control-plane phase diff (share points, after - before)"]
    if not delta.get("phases"):
        lines.append("  (no phases on either side)")
        return "\n".join(lines)
    rows = sorted(delta["phases"].items(),
                  key=lambda kv: -kv[1]["share_delta"])
    for name, row in rows:
        mark = ("  <- regressing"
                if name == delta.get("regressing") else "")
        lines.append(
            f"  {name:<18} {row['share_before'] * 100:6.2f}% -> "
            f"{row['share_after'] * 100:6.2f}%  "
            f"({row['share_delta'] * 100:+6.2f}pt){mark}")
    if delta.get("regressing") is None:
        lines.append("  no phase regressed")
    return "\n".join(lines)
