"""`tpu-autoscaler cost-report`: render the fleet bill (docs/COST.md).

Input is the ledger's ``debug_state()`` body — fetched live from
``/debugz/cost`` or read from an incident bundle's ``cost`` section —
plus, for ``--window``, the same dump's TSDB section: the windowed
bill is computed from ``cost_chip_seconds_<state>`` /
``cost_dollar_proxy_total`` series deltas, so "what did the last hour
cost" works offline from any bundle that retains the history.

Pure formatting over dict inputs (CLI wiring lives in main.py).
"""

from __future__ import annotations

from typing import Any, Mapping

from tpu_autoscaler.cost.ledger import STATES
from tpu_autoscaler.units import ChipSeconds, Seconds


def _fmt_cs(cs: ChipSeconds) -> str:
    if cs >= 3600.0:
        return f"{cs / 3600.0:.1f} chip-h"
    return f"{cs:.0f} chip-s"


def render_bill(cost: Mapping[str, Any], *, top_gangs: int = 10) -> str:
    """The full bill breakdown: by state, by pool, by class/tier, the
    top gangs, fragmentation scores, and the conservation verdict."""
    lines: list[str] = []
    states = cost.get("states", {})
    total_cs = sum(float(s.get("chip_seconds", 0.0))
                   for s in states.values())
    total_chips = sum(int(s.get("chips", 0)) for s in states.values())
    lines.append(f"FLEET BILL  (as of t={cost.get('as_of', 0):g}; "
                 f"{total_chips} chips live, "
                 f"{_fmt_cs(total_cs)} attributed, "
                 f"~${cost.get('dollar_proxy_total', 0.0):.2f} proxy)")
    lines.append("")
    lines.append("by state:")
    usd_by_state: dict[str, float] = {}
    for combo in cost.get("combos", ()):
        usd_by_state[combo["state"]] = (
            usd_by_state.get(combo["state"], 0.0) + combo["usd"])
    for state in STATES:
        body = states.get(state, {})
        cs = float(body.get("chip_seconds", 0.0))
        if not cs and not body.get("chips"):
            continue
        share = (100.0 * cs / total_cs) if total_cs else 0.0
        lines.append(
            f"  {state:<13} {body.get('chips', 0):>6} chips  "
            f"{_fmt_cs(cs):>14}  {share:5.1f}%  "
            f"~${usd_by_state.get(state, 0.0):.2f}")
    pools = cost.get("pools", {})
    if pools:
        lines.append("")
        lines.append("by pool (chip-seconds per state):")
        for pool in sorted(pools):
            parts = ", ".join(
                f"{state}={_fmt_cs(cs)}"
                for state, cs in sorted(pools[pool].items(),
                                        key=lambda kv: -kv[1]) if cs)
            lines.append(f"  {pool:<20} {parts or '(none)'}")
    combos = cost.get("combos", ())
    if combos:
        lines.append("")
        lines.append("by class / tier:")
        for combo in sorted(combos,
                            key=lambda c: -c["chip_seconds"])[:12]:
            lines.append(
                f"  {combo['accel']:<24} {combo['tier']:<12} "
                f"{combo['state']:<13} "
                f"{_fmt_cs(combo['chip_seconds']):>14}  "
                f"~${combo['usd']:.2f}")
    gangs = cost.get("gangs", {})
    if gangs:
        lines.append("")
        lines.append(f"top gangs (cost-to-serve, chip-seconds; "
                     f"#N = incarnation epoch):")
        ranked = sorted(gangs.items(), key=lambda kv: -kv[1])
        for gid, cs in ranked[:top_gangs]:
            lines.append(f"  {gid:<44} {_fmt_cs(cs)}")
    frag = cost.get("fragmentation", {})
    if frag:
        lines.append("")
        lines.append("fragmentation:")
        for pool in sorted(frag, key=lambda p: -frag[p]["score"]):
            s = frag[pool]
            lines.append(
                f"  {pool:<20} score={s['score']:.3f}  "
                f"stranded={s['stranded_chips']} "
                f"displaced={s['displaced_chips']} "
                f"overprov={s['overprovisioned_chips']} "
                f"of {s['chips']} chips")
    cons = cost.get("conservation", {})
    if cons:
        last = cons.get("last")
        verdict = "OK" if not cons.get("violations") else \
            f"{cons['violations']} VIOLATION(S)"
        lines.append("")
        lines.append(
            f"conservation: {verdict}"
            + (f" (last pass: {last[0]}/{last[1]} chips attributed)"
               if last else ""))
    unpriced = cost.get("unpriced_chip_seconds", 0.0)
    if unpriced:
        lines.append(f"unpriced: {_fmt_cs(unpriced)} fell back to the "
                     f"default rate (price-book gap)")
    return "\n".join(lines)


def render_frag(cost: Mapping[str, Any]) -> str:
    """The ``--frag`` section (ISSUE 12 satellite): per-pool
    fragmentation scores with the full stranded / displaced /
    overprovisioned component breakdown and what each component means
    for the repacker — the operator-explainable view of why a pool
    will (or will not) be defragmented (docs/REPACK.md candidate
    scoring; the weights are cost/frag.py's)."""
    from tpu_autoscaler.cost.frag import (
        W_DISPLACED,
        W_OVERPROVISIONED,
        W_STRANDED,
    )

    frag = cost.get("fragmentation", {})
    lines = ["FRAGMENTATION  (score = "
             f"({W_STRANDED:g}*stranded + {W_DISPLACED:g}*displaced "
             f"+ {W_OVERPROVISIONED:g}*overprov) / pool chips, "
             "capped at 1)"]
    if not frag:
        lines.append("  (no pools scored — fleet empty or ledger "
                     "not yet closed)")
        return "\n".join(lines)
    for pool in sorted(frag, key=lambda p: -frag[p]["score"]):
        s = frag[pool]
        lines.append(f"  {pool}  score={s['score']:.3f}  "
                     f"({s['chips']} chips)")
        if s["stranded_chips"]:
            lines.append(
                f"    stranded       {s['stranded_chips']:>6} chips — "
                f"no catalog shape can ever use them (pure loss; "
                f"reclaim, not repack)")
        if s["displaced_chips"]:
            lines.append(
                f"    displaced      {s['displaced_chips']:>6} chips — "
                f"busy on reservation tier while same-shape spot sits "
                f"idle (a displace migration's target)")
        if s["overprovisioned_chips"]:
            lines.append(
                f"    overprovisioned{s['overprovisioned_chips']:>6} "
                f"chips — inside busy units beyond what their gangs "
                f"request (a rightsize migration's target)")
        if not (s["stranded_chips"] or s["displaced_chips"]
                or s["overprovisioned_chips"]):
            lines.append("    (clean: nothing stranded, displaced or "
                         "overprovisioned)")
    return "\n".join(lines)


def windowed_bill(tsdb_dump: Mapping[str, Any],
                  window_seconds: Seconds) -> dict[str, Any]:
    """A by-state bill over the trailing ``window_seconds`` of TSDB
    history: deltas of the cumulative ``cost_chip_seconds_<state>``
    and ``cost_dollar_proxy_total`` series — works on any bundle that
    retains the window."""
    from tpu_autoscaler.obs.tsdb import TimeSeriesDB

    db = TimeSeriesDB.from_dump(dict(tsdb_dump))
    newest = 0.0
    for name in db.series_names("cost_"):
        v = db.points(name)[0]
        if len(v):
            newest = max(newest, float(v[-1]))
    start = newest - window_seconds
    by_state = {}
    for state in STATES:
        d = db.delta(f"cost_chip_seconds_{state}", start, newest)
        if d is not None and d > 0:
            by_state[state] = round(d, 3)
    usd = db.delta("cost_dollar_proxy_total", start, newest)
    return {"window_seconds": window_seconds,
            "window": [start, newest],
            "chip_seconds_by_state": by_state,
            "dollar_proxy": round(usd, 4) if usd is not None else None}


def render_windowed(body: Mapping[str, Any]) -> str:
    lines = [f"WINDOWED BILL  (trailing {body['window_seconds']:g}s, "
             f"t=[{body['window'][0]:g}, {body['window'][1]:g}])"]
    by_state = body.get("chip_seconds_by_state", {})
    total = sum(by_state.values())
    for state in STATES:
        cs = by_state.get(state)
        if cs is None:
            continue
        share = (100.0 * cs / total) if total else 0.0
        lines.append(f"  {state:<13} {_fmt_cs(cs):>14}  {share:5.1f}%")
    if not by_state:
        lines.append("  (no cost_* history retained in the window)")
    usd = body.get("dollar_proxy")
    if usd is not None:
        lines.append(f"  dollar proxy   ~${usd:.2f}")
    return "\n".join(lines)
