"""Fleet cost & capacity attribution ledger (ISSUE 11, docs/COST.md).

Once per reconcile pass, every TPU chip-second on the fleet is
attributed to exactly ONE state:

- ``serving``      — chips under serving-replica workload;
- ``training``     — chips under any other workload gang;
- ``prewarm``      — warm capacity held on purpose (un-consumed policy
                     prewarms, operator spare slices);
- ``repair``       — broken units being cordoned/drained/replaced
                     (slice repairs, requested/unhealthy/preemption
                     drains);
- ``provisioning`` — registered hosts still behind the readiness
                     barrier;
- ``idle``         — ready, workload-free capacity on the reclaim
                     clocks (including cancellable idle-reclaim
                     drains);
- ``stranded``     — capacity nothing can ever use: sub-slice
                     fragments past the stranded window, unknown
                     shapes, broken workload-free ICI domains.

**Conservation identity**: the per-state chip counts sum EXACTLY (int
equality, zero tolerance) to the fleet's observed TPU chips every
pass — checked at ``close_pass`` against the reconciler's own
independent fleet sum, counted on ``cost_conservation_violations``
when broken, and asserted per step by the chaos corpus
(chaos/invariants.py).

**Cost model**: O(churn) per pass like the PR 9 fleet fold.  Every
rollup is a lazy accumulator ``(chips, since, banked)`` — observing a
unit whose classification did not change is one tuple compare;
changes bank ``chips x elapsed`` and restart the clock; ``close_pass``
reads only the handful of state/class/tier accumulators, never the
unit table.  ``rebuild()`` recomputes every chip count from the unit
table from scratch — the property-suite oracle
(tests/test_cost.py, the informer-indices pattern).

Threading: reconcile-thread-only writes, like every other piece of
controller bookkeeping — no locks.  ``debug_state()`` is read from
the /debugz thread and copies with the established bounded-retry
pattern.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Iterable, Mapping, Sequence, TypeVar

from tpu_autoscaler.cost.pricebook import PriceBook, tier_of_labels
from tpu_autoscaler.topology.catalog import (
    TPU_RESOURCE,
    shape_from_selectors,
)
from tpu_autoscaler.units import Chips, ChipSeconds, Seconds, Usd, usd

log = logging.getLogger(__name__)

#: The attribution states, in bill-rendering order (docs/COST.md).
STATES = ("serving", "training", "prewarm", "repair", "provisioning",
          "idle", "stranded")

#: Namespaces whose workload counts as serving (the PR 8/9 advisory
#: namespaces; real serving fleets deploy their replicas here).
SERVING_NAMESPACES = frozenset({"tpu-serving"})

#: Terminal per-gang rollups are retained this long for reports, then
#: folded into the state totals only (bounded state).
GANG_RETENTION_SECONDS: Seconds = 3600.0

#: Accumulator-table key: str pools, (pool, shape) pairs, state combos.
_K = TypeVar("_K")


class _Acc:
    """Lazy chip-second accumulator: ``chips`` holds NOW, ``banked``
    holds everything before ``since``.  total(t) never mutates."""

    __slots__ = ("chips", "since", "banked")

    def __init__(self, t: Seconds) -> None:
        self.chips: Chips = 0
        self.since: Seconds = t
        self.banked: ChipSeconds = 0.0

    def adjust(self, delta_chips: Chips, t: Seconds) -> None:
        self.banked += self.chips * max(0.0, t - self.since)
        self.chips += delta_chips
        self.since = t

    def total(self, t: Seconds) -> ChipSeconds:
        return self.banked + self.chips * max(0.0, t - self.since)


@dataclasses.dataclass
class _Unit:
    """Cached classification of one supply unit."""

    state: str
    chips: Chips
    pool: str
    accel: str
    tier: str
    shape: str | None
    gang_id: str | None        # dominant gang's epoch-rollup id
    used_chips: Chips          # workload-requested chips (frag input)
    entered_at: Seconds        # current state entered (waste reads)
    state_banked: ChipSeconds = 0.0  # prior same-state spans


def classify_cost_state(slice_state: str, *, has_workload: bool,
                        serving: bool, under_repair: bool,
                        cancellable_drain: bool, policy_hold: bool,
                        spare: bool, broken: bool,
                        stranded_overdue: bool) -> str:
    """Map one observed unit to its attribution state — a pure
    function of what the reconcile pass already knows (docs/COST.md
    "Attribution states" documents every branch)."""
    if slice_state == "draining":
        if cancellable_drain and not under_repair:
            return "idle"          # an idle-reclaim drain is still waste
        return "repair"
    if has_workload:
        return "serving" if serving else "training"
    if slice_state == "unhealthy":
        return "stranded"          # broken ICI domain, nothing aboard
    if slice_state == "provisioning":
        if broken and stranded_overdue:
            return "stranded"      # partial/unknown past the window
        return "provisioning"
    if policy_hold or spare or slice_state == "spare":
        return "prewarm"
    return "idle"


class CostLedger:
    """Per-pass chip-second attribution over the observed fleet."""

    def __init__(self, price_book: PriceBook | None = None,
                 metrics: Any = None,
                 serving_namespaces: Iterable[str] = SERVING_NAMESPACES,
                 stranded_after_seconds: Seconds = 900.0) -> None:
        self.price_book = price_book or PriceBook()
        self._metrics = metrics
        self.serving_namespaces = frozenset(serving_namespaces)
        self.stranded_after_seconds = stranded_after_seconds
        self._units: dict[str, _Unit] = {}
        # Static per-unit metadata (pool, accel, tier, shape, hosts):
        # a unit's labels never change over its lifetime, so the label
        # walks + catalog lookup run ONCE per unit, not per pass.
        self._meta: dict[str, tuple[str, str, str, str | None, int]] = {}
        # Rollup accumulators (all lazy; ints conserve exactly).
        self._state: dict[str, _Acc] = {}
        self._combo: dict[tuple[str, str, str], _Acc] = {}  # (state,accel,tier)
        self._pool: dict[tuple[str, str], _Acc] = {}        # (pool,state)
        self._gang: dict[str, _Acc] = {}
        self._gang_last_seen: dict[str, float] = {}
        # Gang incarnation epochs (ISSUE 11 satellite): rollups key on
        # (gang key, epoch) so a Job completing and restarting under
        # the same (ns,name) never double-counts its final partial
        # pass — a disjoint member-uid set is a new incarnation.
        self._gang_epoch: dict[tuple[str, str, str],
                               tuple[int, frozenset[str], float]] = {}
        # Fragmentation inputs (cost/frag.py), maintained incrementally.
        self._idle_spot_chips: dict[str, int] = {}          # shape -> chips
        self._res_busy_chips: dict[tuple[str, str], int] = {}  # (pool,shape)
        self._over_chips: dict[str, int] = {}               # pool -> chips
        self._pool_chips: dict[str, int] = {}               # pool -> chips
        self._stranded_pool: dict[str, int] = {}            # pool -> chips
        # Export cursors (counters emit deltas per close).
        self._exported_cs: dict[str, ChipSeconds] = {}
        self._exported_usd: Usd = 0.0
        self._exported_unpriced: ChipSeconds = 0.0
        self._last_close: Seconds | None = None
        self.pass_seq = 0
        self.conservation_violations = 0
        #: Last close's (attributed chips, fleet chips) — the chaos
        #: conservation invariant reads this pair.
        self.last_conservation: tuple[int, int] | None = None

    # -- metrics helper ---------------------------------------------------

    def _inc(self, name: str, by: float = 1.0) -> None:
        if self._metrics is not None and by:
            self._metrics.inc(name, by)

    def set_gauge(self, name: str, value: float) -> None:
        if self._metrics is not None:
            self._metrics.set_gauge(name, value)

    # -- classification inputs -------------------------------------------

    def _gang_rollup_id(self, key: tuple[str, str, str],
                        uids: frozenset[str], now: Seconds) -> str:
        """Epoch-keyed rollup id for one gang incarnation.  A member
        set DISJOINT from the last seen one is a new incarnation (the
        restart-under-the-same-name case); overlapping sets merge —
        members materialize gradually and repairs recreate them in
        waves.  Entries carry a last-touched stamp so the amortized
        sweep can drop gangs gone past retention (bounded state)."""
        epoch, seen, _touched = self._gang_epoch.get(
            key, (0, frozenset(), now))
        if seen and uids and not (seen & uids):
            epoch += 1
            seen = uids
        else:
            seen = seen | uids
        self._gang_epoch[key] = (epoch, seen, now)
        return "/".join(str(p) for p in key) + f"#{epoch}"

    # -- the write path (reconcile thread only) ---------------------------

    def note_unit(self, unit_id: str, unit_nodes: Sequence[Any],
                  unit_pods: Sequence[Any], slice_state: str,
                  now: Seconds, *, under_repair: bool = False,
                  cancellable_drain: bool = False,
                  policy_hold: bool = False, spare: bool = False,
                  first_seen: Seconds | None = None) -> None:
        """Fold one unit's observation in.  O(1); a no-change
        observation is one tuple compare (the churn contract)."""
        if not unit_nodes or not unit_nodes[0].is_tpu:
            return  # CPU units are outside the chip ledger
        meta = self._meta.get(unit_id)
        if meta is None:
            node = unit_nodes[0]
            try:
                shape = shape_from_selectors(node.labels)
            except KeyError:
                shape = None
            pool = node.pool or node.labels.get(
                "cloud.google.com/gke-nodepool") or (
                node.tpu_accelerator or "unknown")
            meta = (pool, node.tpu_accelerator or "unknown",
                    tier_of_labels(node.labels),
                    shape.name if shape is not None else None,
                    shape.hosts if shape is not None else 0)
            self._meta[unit_id] = meta
        pool, accel, tier, shape_name, hosts = meta
        chips = sum(int(n.allocatable.get(TPU_RESOURCE))
                    for n in unit_nodes)
        workload = [p for p in unit_pods if p.is_workload]
        serving = any(p.namespace in self.serving_namespaces
                      or (p.gang_key is not None
                          and p.gang_key[0] == "serving")
                      for p in workload)
        broken = shape_name is None or len(unit_nodes) < hosts
        overdue = (first_seen is not None
                   and now - first_seen > self.stranded_after_seconds)
        state = classify_cost_state(
            slice_state, has_workload=bool(workload), serving=serving,
            under_repair=under_repair,
            cancellable_drain=cancellable_drain,
            policy_hold=policy_hold, spare=spare, broken=broken,
            stranded_overdue=overdue)

        gang_id = None
        used = 0
        if workload:
            by_gang: dict[tuple[str, str, str], list[Any]] = {}
            for p in workload:
                used += p.tpu_chips
                if p.gang_key is not None:
                    by_gang.setdefault(p.gang_key, []).append(p)
            if by_gang:
                key = max(by_gang,
                          key=lambda k: (sum(p.tpu_chips
                                             for p in by_gang[k]),
                                         str(k)))
                gang_id = self._gang_rollup_id(
                    key, frozenset(p.uid for p in by_gang[key]), now)

        cached = self._units.get(unit_id)
        if cached is not None and cached.state == state \
                and cached.chips == chips and cached.pool == pool \
                and cached.tier == tier and cached.gang_id == gang_id \
                and cached.used_chips == used:
            return  # unchanged: the O(churn) early-out
        if cached is not None:
            self._retire(unit_id, cached, now)
        unit = _Unit(state=state, chips=chips, pool=pool, accel=accel,
                     tier=tier, shape=shape_name, gang_id=gang_id,
                     used_chips=used, entered_at=now)
        if cached is not None and cached.state == state:
            # Same state, different chips/gang: the state clock
            # continues — _retire just banked everything through
            # ``now``, so the fresh span starts here (starting it at
            # the OLD entered_at would double-count the banked span).
            unit.state_banked = cached.state_banked
        self._units[unit_id] = unit
        self._apply(unit, +1, now)

    def known_units(self) -> list[str]:
        """Unit ids currently attributed (the reconciler sweeps this
        against its observed unit set every pass)."""
        return list(self._units)

    def remove_unit(self, unit_id: str, now: Seconds) -> None:
        """A unit's nodes are gone: its chips leave the fleet."""
        cached = self._units.pop(unit_id, None)
        self._meta.pop(unit_id, None)
        if cached is not None:
            self._retire(unit_id, cached, now)

    def _retire(self, unit_id: str, unit: _Unit,
                now: Seconds) -> None:
        unit.state_banked += unit.chips * max(0.0, now - unit.entered_at)
        self._apply(unit, -1, now)

    def _apply(self, unit: _Unit, sign: int, now: Seconds) -> None:
        delta = sign * unit.chips
        self._acc(self._state, unit.state, now).adjust(delta, now)
        self._acc(self._combo, (unit.state, unit.accel, unit.tier),
                  now).adjust(delta, now)
        self._acc(self._pool, (unit.pool, unit.state),
                  now).adjust(delta, now)
        if unit.gang_id is not None and unit.state in ("serving",
                                                       "training",
                                                       "repair"):
            self._acc(self._gang, unit.gang_id, now).adjust(delta, now)
            self._gang_last_seen[unit.gang_id] = now
        # Fragmentation inputs (ints; cost/frag.py reads them).
        self._pool_chips[unit.pool] = (
            self._pool_chips.get(unit.pool, 0) + delta)
        if unit.state == "stranded":
            self._stranded_pool[unit.pool] = (
                self._stranded_pool.get(unit.pool, 0) + delta)
        if unit.shape is not None:
            if unit.state in ("idle", "prewarm") and unit.tier == "spot":
                self._idle_spot_chips[unit.shape] = (
                    self._idle_spot_chips.get(unit.shape, 0) + delta)
            if unit.state in ("serving", "training") \
                    and unit.tier == "reservation":
                key = (unit.pool, unit.shape)
                self._res_busy_chips[key] = (
                    self._res_busy_chips.get(key, 0) + delta)
        if unit.state in ("serving", "training") \
                and unit.used_chips < unit.chips:
            self._over_chips[unit.pool] = (
                self._over_chips.get(unit.pool, 0)
                + sign * (unit.chips - unit.used_chips))

    @staticmethod
    def _acc(table: dict[_K, _Acc], key: _K, now: Seconds) -> _Acc:
        acc = table.get(key)
        if acc is None:
            acc = table[key] = _Acc(now)
        return acc

    # -- per-pass close ---------------------------------------------------

    def close_pass(self, now: Seconds,
                   fleet_chips: Chips) -> dict[str, Any]:
        """Seal the pass: conservation check against the reconciler's
        INDEPENDENT fleet chip sum, metric export (deltas for the
        cumulative families, levels for the gauges), fragmentation
        scores, bounded-state pruning.  Returns the pass record's
        ``cost`` section.  O(states + combos + pools), never O(units).
        """
        from tpu_autoscaler.cost.frag import score_pools

        self.pass_seq += 1
        attributed = sum(acc.chips for acc in self._state.values())
        self.last_conservation = (attributed, fleet_chips)
        if attributed != fleet_chips:
            self.conservation_violations += 1
            self._inc("cost_conservation_violations")
            log.warning(
                "cost ledger conservation broken: attributed %d chips "
                "vs fleet %d", attributed, fleet_chips)

        usd_total: Usd = 0.0
        unpriced: ChipSeconds = 0.0
        usd_per_hour = 0.0     # $/hour: a rate, not an alias currency
        for (state, accel, tier), acc in self._combo.items():
            cs = acc.total(now)
            rate, priced = self.price_book.rate(accel, tier)
            usd_total += usd(rate, cs)
            usd_per_hour += acc.chips * rate
            if not priced:
                unpriced += cs
        for state in STATES:
            acc = self._state.get(state)
            cs = acc.total(now) if acc is not None else 0.0
            self.set_gauge(f"cost_chips_{state}",
                           acc.chips if acc is not None else 0)
            last = self._exported_cs.get(state, 0.0)
            if cs > last:
                self._inc(f"cost_chip_seconds_{state}", cs - last)
                self._exported_cs[state] = cs
        if usd_total > self._exported_usd:
            self._inc("cost_dollar_proxy_total",
                      usd_total - self._exported_usd)
            self._exported_usd = usd_total
        if unpriced > self._exported_unpriced:
            self._inc("cost_unpriced_chip_seconds",
                      unpriced - self._exported_unpriced)
            self._exported_unpriced = unpriced
        self.set_gauge("cost_dollar_proxy_per_hour", round(usd_per_hour, 6))

        scores = score_pools(
            pool_chips=self._pool_chips,
            stranded=self._stranded_pool,
            over_chips=self._over_chips,
            res_busy=self._res_busy_chips,
            idle_spot=self._idle_spot_chips)
        frag_stranded = sum(self._stranded_pool.values())
        frag_displaced = sum(s.displaced_chips for s in scores.values())
        frag_over = sum(self._over_chips.values())
        self.set_gauge("frag_stranded_chips", frag_stranded)
        self.set_gauge("frag_displaced_chips", frag_displaced)
        self.set_gauge("frag_overprovisioned_chips", frag_over)
        for pool, s in scores.items():
            self.set_gauge(f"frag_score_{pool}", round(s.score, 4))

        # Bounded state, amortized: the gang-retention and zero-bucket
        # sweeps walk their whole tables, so they run every 64th close
        # (O(gangs/64) amortized — a close must stay O(states+combos),
        # never O(gangs), on the pass budget).
        if self.pass_seq % 64 == 0:
            horizon = now - GANG_RETENTION_SECONDS
            for gid in [g for g, seen in self._gang_last_seen.items()
                        if seen < horizon and self._gang[g].chips == 0]:
                del self._gang[gid]
                del self._gang_last_seen[gid]
            # Epoch entries of gangs gone past retention go too
            # (review-found unbounded growth) — but never while the
            # current incarnation still holds chips: a live steady
            # gang's epoch may sit untouched for hours (the unchanged
            # early-out skips _gang_rollup_id) and pruning it would
            # lose the uid set the next restart is detected against.
            for key in [
                    k for k, (ep, _seen, touched)
                    in self._gang_epoch.items()
                    if touched < horizon
                    and getattr(self._gang.get(
                        "/".join(str(p) for p in k) + f"#{ep}"),
                        "chips", 0) == 0]:
                del self._gang_epoch[key]
            for table in (self._idle_spot_chips, self._res_busy_chips,
                          self._over_chips, self._stranded_pool):
                for key in [k for k, v in table.items() if v == 0]:
                    del table[key]

        self._last_close = now
        return {
            "attributed_chips": attributed,
            "fleet_chips": fleet_chips,
            "conserved": attributed == fleet_chips,
            "chips": {s: (self._state[s].chips if s in self._state
                          else 0) for s in STATES},
            "dollar_per_hour": round(usd_per_hour, 4),
        }

    # -- reads ------------------------------------------------------------

    def accrued_chip_seconds(self, unit_ids: Iterable[str],
                             now: Seconds, state: str | None = None
                             ) -> ChipSeconds | None:
        """Chip-seconds the named units accrued in their CURRENT state
        span (banked prior same-state spans included) — the policy
        waste budget's one source of truth.  None when no named unit
        is tracked (callers fall back to their own estimate)."""
        total: ChipSeconds = 0.0
        hit = False
        for unit_id in unit_ids:
            unit = self._units.get(unit_id)
            if unit is None or (state is not None
                                and unit.state != state):
                continue
            hit = True
            total += unit.state_banked + unit.chips * max(
                0.0, now - unit.entered_at)
        return total if hit else None

    def gang_attrs(self, gang_key: tuple[str, str, str], now: Seconds
                   ) -> dict[str, float] | None:
        """Cost-to-serve attrs for a closing trace: the gang's CURRENT
        incarnation's attributed chip-seconds (None: never attributed
        — e.g. the gang ran on capacity the ledger never saw busy)."""
        epoch, _uids, _t = self._gang_epoch.get(
            gang_key, (0, frozenset(), 0.0))
        gid = "/".join(str(p) for p in gang_key) + f"#{epoch}"
        acc = self._gang.get(gid)
        if acc is None:
            return None
        return {"cost_chip_seconds": round(acc.total(now), 3)}

    def placement_quality(self) -> dict[str, Any]:
        """Per-unit placement rows for the repacker (ISSUE 12,
        docs/REPACK.md): every BUSY unit's pool/tier/shape/chip
        numbers, plus the current idle-spot-by-shape availability the
        displacement candidates are matched against.  O(busy units)
        — consumed once per pass by the (opt-in) repack pass, never
        by the always-on close."""
        rows = []
        for unit_id, u in self._units.items():
            if u.state not in ("serving", "training"):
                continue
            rows.append({
                "unit_id": unit_id, "pool": u.pool, "accel": u.accel,
                "tier": u.tier, "shape": u.shape, "chips": u.chips,
                "used_chips": u.used_chips, "state": u.state,
                "since": u.entered_at, "gang_id": u.gang_id,
            })
        return {"rows": rows,
                "idle_spot_chips": {k: v for k, v
                                    in self._idle_spot_chips.items()
                                    if v > 0}}

    def rebuild(self) -> dict[str, Any]:
        """From-scratch chip counts off the unit table — the property
        oracle the incremental accumulators are checked against."""
        state: dict[str, int] = {}
        pool: dict[tuple[str, str], int] = {}
        combo: dict[tuple[str, str, str], int] = {}
        gang: dict[str, int] = {}
        idle_spot: dict[str, int] = {}
        res_busy: dict[tuple[str, str], int] = {}
        over: dict[str, int] = {}
        stranded: dict[str, int] = {}
        pool_chips: dict[str, int] = {}
        for u in self._units.values():
            state[u.state] = state.get(u.state, 0) + u.chips
            pool[(u.pool, u.state)] = pool.get((u.pool, u.state),
                                               0) + u.chips
            combo_key = (u.state, u.accel, u.tier)
            combo[combo_key] = combo.get(combo_key, 0) + u.chips
            pool_chips[u.pool] = pool_chips.get(u.pool, 0) + u.chips
            if u.gang_id is not None and u.state in ("serving",
                                                     "training",
                                                     "repair"):
                gang[u.gang_id] = gang.get(u.gang_id, 0) + u.chips
            if u.state == "stranded":
                stranded[u.pool] = stranded.get(u.pool, 0) + u.chips
            if u.shape is not None:
                if u.state in ("idle", "prewarm") and u.tier == "spot":
                    idle_spot[u.shape] = (idle_spot.get(u.shape, 0)
                                          + u.chips)
                if u.state in ("serving", "training") \
                        and u.tier == "reservation":
                    res_busy[(u.pool, u.shape)] = (
                        res_busy.get((u.pool, u.shape), 0) + u.chips)
            if u.state in ("serving", "training") \
                    and u.used_chips < u.chips:
                over[u.pool] = over.get(u.pool, 0) + (u.chips
                                                      - u.used_chips)
        return {"state": state, "pool": pool, "combo": combo,
                "gang": gang, "idle_spot": idle_spot,
                "res_busy": res_busy, "over": over,
                "stranded": stranded, "pool_chips": pool_chips}

    def live_counts(self) -> dict[str, Any]:
        """The incremental counters in ``rebuild()``'s shape (the
        property suite compares the two for equality)."""
        return {
            "state": {k: a.chips for k, a in self._state.items()
                      if a.chips},
            "pool": {k: a.chips for k, a in self._pool.items()
                     if a.chips},
            "combo": {k: a.chips for k, a in self._combo.items()
                      if a.chips},
            "gang": {k: a.chips for k, a in self._gang.items()
                     if a.chips},
            "idle_spot": {k: v for k, v in self._idle_spot_chips.items()
                          if v},
            "res_busy": {k: v for k, v in self._res_busy_chips.items()
                         if v},
            "over": {k: v for k, v in self._over_chips.items() if v},
            "stranded": {k: v for k, v in self._stranded_pool.items()
                         if v},
            "pool_chips": {k: v for k, v in self._pool_chips.items()
                           if v},
        }

    def debug_state(self,
                    now: Seconds | None = None) -> dict[str, Any]:
        """The ``/debugz/cost`` body and the incident bundle's ``cost``
        section: the full bill breakdown (docs/COST.md "The bill").
        Read from the /debugz thread while the reconcile thread
        mutates — bounded-retry copy, degrade-not-500."""
        from tpu_autoscaler.cost.frag import score_pools

        now = self._last_close if now is None else now
        if now is None:
            now = 0.0
        for _ in range(5):
            try:
                by_state = {
                    s: {"chips": (self._state[s].chips
                                  if s in self._state else 0),
                        "chip_seconds": round(
                            self._state[s].total(now), 3)
                        if s in self._state else 0.0}
                    for s in STATES}
                pools: dict[str, dict[str, float]] = {}
                for (pool, state), acc in list(self._pool.items()):
                    cs = acc.total(now)
                    if cs or acc.chips:
                        pools.setdefault(pool, {})[state] = round(cs, 3)
                combos = [
                    {"state": s, "accel": a, "tier": t,
                     "chips": acc.chips,
                     "chip_seconds": round(acc.total(now), 3),
                     "usd": round(acc.total(now)
                                  * self.price_book.rate(a, t)[0]
                                  / 3600.0, 6)}
                    for (s, a, t), acc in list(self._combo.items())
                    if acc.chips or acc.total(now)]
                gangs = {
                    gid: round(acc.total(now), 3)
                    for gid, acc in list(self._gang.items())}
                scores = {
                    pool: dataclasses.asdict(s)
                    for pool, s in score_pools(
                        pool_chips=dict(self._pool_chips),
                        stranded=dict(self._stranded_pool),
                        over_chips=dict(self._over_chips),
                        res_busy=dict(self._res_busy_chips),
                        idle_spot=dict(
                            self._idle_spot_chips)).items()}
                break
            # A reconcile-thread mutation mid-copy surfaces as
            # RuntimeError (dict resize) or KeyError/IndexError
            # (entry vanishing between the keys walk and the read).
            except (RuntimeError, KeyError, IndexError):
                continue
        else:
            return {"unavailable": "mutating"}
        usd_total = sum(c["usd"] for c in combos)
        return {
            "as_of": now,
            "states": by_state,
            "pools": pools,
            "combos": combos,
            "gangs": gangs,
            "fragmentation": scores,
            "dollar_proxy_total": round(usd_total, 4),
            "unpriced_chip_seconds": round(self._exported_unpriced, 3),
            "conservation": {
                "violations": self.conservation_violations,
                "last": list(self.last_conservation)
                if self.last_conservation else None,
            },
        }
