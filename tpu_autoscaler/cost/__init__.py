"""Fleet cost & capacity attribution (ISSUE 11, docs/COST.md).

- ``pricebook`` — declarative $-proxy per accelerator class × price
  tier (reservation / on-demand / spot), with tier detection off the
  labels GKE already stamps;
- ``ledger``    — the per-pass attribution ledger: every TPU
  chip-second on the fleet lands in exactly one state, conserved
  against the fleet total every pass (chaos-checked);
- ``frag``      — topology-aware fragmentation scoring per pool (the
  future repacker's input);
- ``report``    — the ``tpu-autoscaler cost-report`` bill renderer.
"""

from tpu_autoscaler.cost.frag import FragScore, score_pools
from tpu_autoscaler.cost.ledger import (
    STATES,
    CostLedger,
    classify_cost_state,
)
from tpu_autoscaler.cost.pricebook import PriceBook, tier_of_labels
from tpu_autoscaler.cost.report import (
    render_bill,
    render_frag,
    render_windowed,
    windowed_bill,
)

__all__ = [
    "STATES",
    "CostLedger",
    "FragScore",
    "PriceBook",
    "classify_cost_state",
    "render_bill",
    "render_frag",
    "render_windowed",
    "score_pools",
    "tier_of_labels",
    "windowed_bill",
]
