"""Declarative price book: accelerator class × price tier → $-proxy.

The ledger (cost/ledger.py) attributes chip-seconds; this module turns
them into money — a *dollar proxy*, deliberately not a billing export:
the absolute numbers only need to be stable and ordered (spot <
reservation < on-demand) for the fragmentation scorer, the budget
alert, and the repacker's future never-costs-more-than-it-saves guard
to mean anything.  Rates are $/chip-hour.

Tier detection reads the labels GKE already stamps on nodes:

- ``cloud.google.com/gke-spot`` (or the legacy ``gke-preemptible``)
  → ``spot``;
- ``cloud.google.com/reservation-name`` → ``reservation``;
- otherwise ``on_demand``.

An accelerator class absent from the book falls back to
``default_rate`` and is COUNTED (``cost_unpriced_chip_seconds``) —
an unpriced class is a config gap, never a silent $0 (docs/COST.md
"Price book").  Pure data + lookups: no clocks, no I/O (the CLI's
YAML loading happens in main.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping

from tpu_autoscaler.units import UsdPerChipHour

#: Known price tiers, cheapest-last (docs/COST.md).
TIERS = ("on_demand", "reservation", "spot")

#: Node labels that betray the tier (GKE conventions; the fake cloud's
#: preemptible nodes carry the spot label too — k8s/payloads.py).
SPOT_LABELS = ("cloud.google.com/gke-spot",
               "cloud.google.com/gke-preemptible")
RESERVATION_LABEL = "cloud.google.com/reservation-name"

#: Default on-demand $-proxy per chip-hour by TPU generation —
#: order-of-magnitude public-list-price shaped, NOT billing data.
DEFAULT_GENERATION_RATES: dict[str, float] = {
    "v4": 3.22,
    "v5e": 1.20,
    "v5p": 4.20,
    "v6e": 2.70,
}

#: Tier multipliers over the on-demand rate (reservation: committed
#: discount; spot: preemptible discount).
DEFAULT_TIER_FACTORS: dict[str, float] = {
    "on_demand": 1.0,
    "reservation": 0.6,
    "spot": 0.35,
}

#: Fallback $/chip-hour for classes the book does not price.
DEFAULT_CLASS_RATE = 2.0

#: Plausibility band for configured $/chip-hour rates (ISSUE 16
#: pricebook hardening).  Config is the one place the static TAU10xx
#: pass cannot see, and the classic config slip is a rate written in
#: the WRONG TIMEBASE: a $/chip-second entry is off by 3600x and lands
#: far outside this band in either direction (1.20 $/chip-hour written
#: as-per-second ~ 0.00033; 4.20 $/chip-hour pre-multiplied by 3600 ~
#: 15120).  Zero stays legal (an explicitly free class is not a
#: timebase bug).
MIN_SANE_RATE = 0.01
MAX_SANE_RATE = 100.0


def tier_of_labels(labels: Mapping[str, str]) -> str:
    """Price tier of a node, from its labels."""
    for label in SPOT_LABELS:
        if labels.get(label) == "true":
            return "spot"
    if labels.get(RESERVATION_LABEL):
        return "reservation"
    return "on_demand"


def _catalog_class_rates() -> dict[str, float]:
    """Per-accelerator-class on-demand rates derived from the catalog:
    every accelerator type of a generation inherits the generation's
    rate (the catalog is the one place shape↔generation lives)."""
    from tpu_autoscaler.topology.catalog import SLICE_SHAPES

    out: dict[str, float] = {}
    for shape in SLICE_SHAPES.values():
        rate = DEFAULT_GENERATION_RATES.get(shape.generation)
        if rate is not None:
            out.setdefault(shape.accelerator_type, rate)
    return out


@dataclasses.dataclass(frozen=True)
class PriceBook:
    """accel class → on-demand $/chip-hour, with tier factors.

    ``class_rates`` keys are accelerator-type label values (what nodes
    actually carry); ``rate()`` returns ``(usd_per_chip_hour, priced)``
    — ``priced=False`` means the class fell back to ``default_rate``
    and the caller must count the chip-seconds as unpriced."""

    class_rates: Mapping[str, float] = dataclasses.field(
        default_factory=_catalog_class_rates)
    tier_factors: Mapping[str, float] = dataclasses.field(
        default_factory=lambda: dict(DEFAULT_TIER_FACTORS))
    default_rate: float = DEFAULT_CLASS_RATE

    def rate(self, accel_class: str,
             tier: str) -> tuple[UsdPerChipHour, bool]:
        base = self.class_rates.get(accel_class)
        priced = base is not None
        if base is None:
            base = self.default_rate
        factor = self.tier_factors.get(tier,
                                       DEFAULT_TIER_FACTORS["on_demand"])
        return base * factor, priced

    @classmethod
    def from_dict(cls, body: Mapping[str, Any]) -> "PriceBook":
        """Build from a YAML-shaped mapping (docs/COST.md format)::

            default_rate: 2.0
            tiers: {reservation: 0.6, spot: 0.35}
            classes:
              v5e: 1.2                    # generation: expands to every
              tpu-v5p-slice: 4.2          # accelerator type, or exact

        Generation keys expand through the catalog; unknown keys are an
        error (a typo'd class would silently price as the default)."""
        from tpu_autoscaler.topology.catalog import SLICE_SHAPES

        by_generation: dict[str, list[str]] = {}
        known_classes: set[str] = set()
        for shape in SLICE_SHAPES.values():
            by_generation.setdefault(shape.generation, []).append(
                shape.accelerator_type)
            known_classes.add(shape.accelerator_type)

        class_rates = dict(_catalog_class_rates())
        out_of_band: list[str] = []
        for key, value in dict(body.get("classes") or {}).items():
            rate = float(value)
            if rate < 0.0:
                raise ValueError(f"negative rate for {key!r}")
            if rate != 0.0 and not (MIN_SANE_RATE <= rate
                                    <= MAX_SANE_RATE):
                out_of_band.append(f"{key}={rate:g}")
                continue
            if key in by_generation:
                for accel in by_generation[key]:
                    class_rates[accel] = rate
            elif key in known_classes or key.startswith("tpu-"):
                class_rates[key] = rate
            else:
                raise ValueError(
                    f"unknown price-book class {key!r} (generations: "
                    f"{', '.join(sorted(by_generation))})")
        factors = dict(DEFAULT_TIER_FACTORS)
        for key, value in dict(body.get("tiers") or {}).items():
            if key not in TIERS:
                raise ValueError(
                    f"unknown price tier {key!r} (known: "
                    f"{', '.join(TIERS)})")
            factors[key] = float(value)
        default_rate = float(body.get("default_rate",
                                      DEFAULT_CLASS_RATE))
        if default_rate != 0.0 and not (MIN_SANE_RATE <= default_rate
                                        <= MAX_SANE_RATE):
            out_of_band.append(f"default_rate={default_rate:g}")
        if out_of_band:
            raise ValueError(
                f"{len(out_of_band)} price-book rate(s) outside the "
                f"[{MIN_SANE_RATE:g}, {MAX_SANE_RATE:g}] $/chip-hour "
                f"plausibility band ({', '.join(sorted(out_of_band))})"
                " — a rate this far out is almost always a timebase "
                "slip (a $/chip-second value is off by 3600x)")
        return cls(class_rates=class_rates, tier_factors=factors,
                   default_rate=default_rate)
