"""Topology-aware fragmentation scoring (ISSUE 11, docs/COST.md).

Turns the ledger's incremental per-pool aggregates into one score per
pool in ``[0, 1]`` — the number the ROADMAP's cost-aware continuous
repacker will consume: "which pool should I defragment first, and is
the migration worth its drain cost?".  Three components, each a chip
count the ledger maintains O(churn):

- **stranded** — capacity no catalog shape can ever use (partial
  slices past the stranded window, unknown shapes, broken
  workload-free ICI domains);
- **displaced** — workload pinned on reservation-tier chips while a
  same-shape spot unit sits idle: the gang could run identically for a
  fraction of the $-proxy (``min(reservation-busy, idle-spot)`` per
  shape — an upper bound: the scorer ranks, the repacker verifies);
- **overprovisioned** — busy units whose gang requests fewer chips
  than the slice carries (topology-poor placement: a v5e-16 gang
  parked on a v5e-32 strands half the slice *inside* a busy unit,
  where the idle clocks never see it).

Pure functions over injected counts: no clocks, no controller state —
unit-testable exactly like the SLO algebra (policy/slo.py).
"""

from __future__ import annotations

import dataclasses
from typing import Mapping

from tpu_autoscaler.units import Chips, Fraction

#: Component weights: stranded capacity is pure loss; displacement
#: costs the tier delta; overprovisioning is recoverable only by a
#: migration, so it weighs least (docs/COST.md "Fragmentation score").
W_STRANDED = 1.0
W_DISPLACED = 0.8
W_OVERPROVISIONED = 0.4


@dataclasses.dataclass(frozen=True)
class FragScore:
    """One pool's fragmentation verdict."""

    pool: str
    chips: Chips
    stranded_chips: Chips
    displaced_chips: Chips
    overprovisioned_chips: Chips
    score: Fraction               # weighted fraction of the pool, [0,1]


def score_pools(*, pool_chips: Mapping[str, int],
                stranded: Mapping[str, int],
                over_chips: Mapping[str, int],
                res_busy: Mapping[tuple[str, str], int],
                idle_spot: Mapping[str, int]
                ) -> dict[str, FragScore]:
    """Score every pool with chips.  ``res_busy`` is keyed
    ``(pool, shape)``; ``idle_spot`` by shape — displacement matches
    reservation-busy chips against idle spot chips of the SAME shape
    (a like-for-like migration target), attributed to the busy pool.
    """
    displaced: dict[str, int] = {}
    for (pool, shape), busy in res_busy.items():
        if busy <= 0:
            continue
        spot_free = idle_spot.get(shape, 0)
        if spot_free > 0:
            displaced[pool] = displaced.get(pool, 0) + min(busy,
                                                           spot_free)
    out: dict[str, FragScore] = {}
    for pool, chips in pool_chips.items():
        if chips <= 0:
            continue
        s = stranded.get(pool, 0)
        d = displaced.get(pool, 0)
        o = over_chips.get(pool, 0)
        weighted = (W_STRANDED * s + W_DISPLACED * d
                    + W_OVERPROVISIONED * o)
        out[pool] = FragScore(
            pool=pool, chips=chips, stranded_chips=s,
            displaced_chips=d, overprovisioned_chips=o,
            score=min(1.0, weighted / chips))
    return out
