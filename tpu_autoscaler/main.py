"""CLI / process entry (L5).

Reference parity: main.py's click-decorated ``main()`` with flags → Cluster
ctor → loop-with-sleep (SURVEY.md §3.1).  Differences, deliberate:

- subcommands: ``run`` (real cluster), ``demo`` (fake cloud, simulated
  time — the dry-run-plus story the reference lacked);
- the loop interval defaults to 5 s, not 60 s: detection latency is part of
  the north-star budget;
- a metrics endpoint (``--metrics-port``) exports the BASELINE metrics.
"""

from __future__ import annotations

import os
import sys

import click

from tpu_autoscaler.controller import Controller, ControllerConfig
from tpu_autoscaler.engine.planner import PoolPolicy
from tpu_autoscaler.metrics import Metrics
from tpu_autoscaler.notify import LogNotifier, SlackNotifier
from tpu_autoscaler.topology.catalog import cpu_shape_by_name


def _policy(default_generation, generation_fallbacks, cpu_machine_type,
            over_provision, spare_agents, spare_slices, namespace_quotas,
            max_cpu_nodes, max_total_chips, preemptible,
            fair_share=False) -> PoolPolicy:
    from tpu_autoscaler.topology.catalog import (
        SLICE_SHAPES,
        shapes_for_generation,
    )

    for gen in generation_fallbacks:
        try:
            shapes_for_generation(gen)
        except KeyError:
            raise click.BadParameter(
                f"unknown TPU generation {gen!r}",
                param_hint="--generation-fallback") from None

    spares: dict[str, int] = {}
    for item in spare_slices:
        shape, _, count = item.partition("=")
        if shape not in SLICE_SHAPES:
            raise click.BadParameter(
                f"unknown slice shape {shape!r} (known: "
                f"{', '.join(sorted(SLICE_SHAPES))})",
                param_hint="--spare-slice")
        if shape in spares:
            raise click.BadParameter(
                f"duplicate shape {shape!r}", param_hint="--spare-slice")
        try:
            spares[shape] = int(count or "1")
        except ValueError:
            raise click.BadParameter(
                f"bad count in {item!r}; expected SHAPE=N",
                param_hint="--spare-slice") from None
        if spares[shape] < 0:
            raise click.BadParameter(
                f"negative count in {item!r}", param_hint="--spare-slice")
    quotas: dict[str, int] = {}
    for item in namespace_quotas:
        ns, sep, chips = item.partition("=")
        if not sep or not ns:
            raise click.BadParameter(
                f"bad quota {item!r}; expected NAMESPACE=CHIPS",
                param_hint="--namespace-quota")
        if ns in quotas:
            raise click.BadParameter(
                f"duplicate namespace {ns!r} (one ceiling per namespace)",
                param_hint="--namespace-quota")
        try:
            quotas[ns] = int(chips)
        except ValueError:
            raise click.BadParameter(
                f"bad chip count in {item!r}; expected NAMESPACE=CHIPS",
                param_hint="--namespace-quota") from None
        if quotas[ns] < 0:
            raise click.BadParameter(
                f"negative quota in {item!r}",
                param_hint="--namespace-quota")
    return PoolPolicy(
        default_generation=default_generation,
        generation_fallbacks=tuple(generation_fallbacks),
        cpu_shape=cpu_shape_by_name(cpu_machine_type),
        over_provision_nodes=over_provision,
        spare_nodes=spare_agents,
        spare_slices=spares,
        namespace_chip_quota=quotas,
        max_cpu_nodes=max_cpu_nodes,
        max_total_chips=max_total_chips,
        preemptible=preemptible,
        fair_share=fair_share,
    )


def _load_config(ctx, param, value):
    """--config FILE: YAML keys become flag defaults (CLI still wins).

    The reference was flags-only (SURVEY.md §6.6); a config file makes the
    policy data.  Keys are flag names (dashes or underscores), e.g.::

        idle_threshold: 900
        spare_slices: ["v5e-8=1"]
        default-generation: v5p

    Unknown keys are an error, not a silent no-op — a typo'd policy knob
    must never quietly mis-scale a cluster.
    """
    if not value:
        return value
    import yaml

    try:
        with open(value) as f:
            loaded = yaml.safe_load(f) or {}
    except yaml.YAMLError as e:
        raise click.BadParameter(f"invalid YAML: {e}",
                                 param_hint="--config") from None
    if not isinstance(loaded, dict):
        raise click.BadParameter("config must be a YAML mapping",
                                 param_hint="--config")
    known = {p.name for p in ctx.command.params if p.name}
    normalized = {str(k).replace("-", "_"): v for k, v in loaded.items()}
    unknown = sorted(set(normalized) - known)
    if unknown:
        raise click.BadParameter(
            f"unknown config key(s): {', '.join(unknown)} "
            f"(known: {', '.join(sorted(known))})",
            param_hint="--config")
    ctx.default_map = {**(ctx.default_map or {}), **normalized}
    return value


_common = [
    click.option("--config", type=click.Path(exists=True, dir_okay=False),
                 is_eager=True, callback=_load_config, expose_value=False,
                 help="YAML file of flag defaults (CLI flags override)."),
    click.option("--sleep", default=5.0, show_default=True,
                 type=click.FloatRange(min=0.1),
                 help="Reconcile interval seconds (reference: --sleep, 60)."),
    click.option("--idle-threshold", default=1800.0, show_default=True,
                 help="Seconds idle before a unit is reclaimed."),
    click.option("--grace-period", default=300.0, show_default=True,
                 help="Post-launch grace seconds."),
    click.option("--drain-grace", default=120.0, show_default=True,
                 help="Checkpoint window before force-evicting."),
    click.option("--utilization-threshold", default=0.0, show_default=True,
                 help="Consolidate CPU nodes below this requested fraction "
                      "(0 disables)."),
    click.option("--gang-settle", default=0.0, show_default=True,
                 help="Wait seconds before sizing an un-pinned TPU gang "
                      "(guards against partial pod observation)."),
    click.option("--provision-timeout", default=900.0, show_default=True,
                 help="Cancel and retry provisions stuck in flight this "
                      "long (stockout guard)."),
    click.option("--preemption", is_flag=True,
                 help="Let clamp-blocked higher-priority gangs reclaim "
                      "chips from lower-priority jobs (checkpoint-aware)."),
    click.option("--repack", "enable_repack", is_flag=True,
                 help="Enable cost-aware continuous repacking: migrate "
                      "wrongly-placed gangs (expensive tier while "
                      "same-shape spot sits idle; oversized slices) "
                      "under a hard never-costs-more-than-it-saves "
                      "budget guard (docs/REPACK.md). Off by default: "
                      "repacking moves live work."),
    click.option("--reconcile-shards", default=0, show_default=True,
                 type=click.IntRange(min=0),
                 help="Shard reconcile planning by accelerator "
                      "class/pool across this many workers, merged "
                      "back byte-identical on the reconcile thread "
                      "(docs/SHARDING.md). 0 = serial, the oracle."),
    click.option("--spare-agents", default=1, show_default=True,
                 help="Free CPU nodes kept warm (reference: --spare-agents)."),
    click.option("--spare-slice", "spare_slices", multiple=True,
                 help="Warm TPU slices, e.g. --spare-slice v5e-8=1."),
    click.option("--namespace-quota", "namespace_quotas", multiple=True,
                 help="Per-namespace chip ceiling, e.g. "
                      "--namespace-quota teamx=256."),
    click.option("--over-provision", default=0, show_default=True,
                 help="Extra CPU nodes beyond demand."),
    click.option("--default-generation", default="v5e", show_default=True),
    click.option("--generation-fallback", "generation_fallbacks",
                 multiple=True,
                 help="Fallback TPU generation(s), in order, for unpinned "
                      "gangs whose provisions keep failing (capacity "
                      "stockout), e.g. --generation-fallback v6e "
                      "--generation-fallback v5p."),
    click.option("--cpu-machine-type", default="e2-standard-8",
                 show_default=True),
    click.option("--max-cpu-nodes", default=100, show_default=True),
    click.option("--max-total-chips", default=4096, show_default=True),
    click.option("--preemptible", is_flag=True,
                 help="Provision spot/preemptible TPU capacity."),
    click.option("--fair-share", is_flag=True,
                 help="Serve equal-priority gangs from the namespace "
                      "using the fewest chips first (multi-tenant "
                      "fairness under a contended chip budget)."),
    click.option("--no-scale", is_flag=True),
    click.option("--no-maintenance", is_flag=True),
    click.option("--policy", "enable_policy", is_flag=True,
                 help="Enable the predictive SLO-driven policy engine: "
                      "forecast demand and prewarm slices ahead of the "
                      "Unschedulable event (docs/POLICY.md)."),
    click.option("--policy-min-confidence", default=0.6,
                 show_default=True, type=click.FloatRange(0.0, 1.0),
                 help="Forecast confidence below which no prewarm "
                      "fires."),
    click.option("--policy-waste-budget", default=120000.0,
                 show_default=True, type=click.FloatRange(min=0.0),
                 help="Rolling wasted-chip-seconds budget per hour for "
                      "mispredicted prewarms."),
    click.option("--policy-early-reclaim", is_flag=True,
                 help="Also let the policy SHRINK idle thresholds for "
                      "classes with no forecast demand (cost wins; "
                      "idle units may be reclaimed well before "
                      "--idle-threshold). Off by default: --policy "
                      "alone only prewarms and holds."),
    click.option("--price-book", "price_book", default=None,
                 type=click.Path(exists=True, dir_okay=False),
                 help="YAML price book for the cost ledger's $-proxy "
                      "(per-class rates + tier factors; docs/COST.md). "
                      "Unset: the built-in catalog-derived book."),
    click.option("--slack-hook", default=None,
                 help="Slack incoming-webhook URL for scale events."),
    click.option("--slack-channel", default=None),
    click.option("--metrics-port", default=0, show_default=True,
                 help="Serve /metrics, /healthz, /debugz and "
                      "/debugz/tsdb on this port (0=off)."),
    click.option("--recorder-spans", default=4096, show_default=True,
                 type=click.IntRange(min=16),
                 help="Flight-recorder completed-span ring capacity "
                      "(docs/OBSERVABILITY.md retention bounds)."),
    click.option("--recorder-passes", default=512, show_default=True,
                 type=click.IntRange(min=16),
                 help="Flight-recorder decision-record ring capacity."),
    click.option("--no-alerts", is_flag=True,
                 help="Disable the SLO burn-rate alert engine "
                      "(docs/OPERATIONS.md alert catalog; on by "
                      "default — the autoscaler watches itself)."),
    click.option("--incident-dir", default=None,
                 help="Directory for black-box incident bundles, "
                      "captured automatically when an alert fires "
                      "(unset = no automatic captures; SIGUSR1 and "
                      "/debugz still work)."),
    click.option("--no-profile", is_flag=True,
                 help="Disable the control-plane phase profiler "
                      "(docs/OBSERVABILITY.md \"Control-plane "
                      "profiling\"; on by default — off degrades "
                      "phase timing to a no-op)."),
    click.option("--profile-sampling-hz", default=0.0,
                 show_default=True,
                 help="Collapsed-stack sampling rate over the "
                      "reconcile thread (0=off).  Stacks ride "
                      "/debugz/profile and incident bundles."),
    click.option("--log-json", is_flag=True,
                 help="Emit structured JSON log lines."),
    click.option("-v", "--verbose", is_flag=True),
]


def common_options(f):
    for opt in reversed(_common):
        f = opt(f)
    return f


def _build(kube, actuator, *, sleep, idle_threshold, grace_period,
           drain_grace, utilization_threshold, gang_settle,
           provision_timeout, preemption, spare_agents, spare_slices,
           namespace_quotas, over_provision,
           default_generation, generation_fallbacks, cpu_machine_type,
           max_cpu_nodes, max_total_chips, preemptible, fair_share,
           no_scale, no_maintenance, enable_policy,
           policy_min_confidence, policy_waste_budget,
           policy_early_reclaim, slack_hook,
           slack_channel, metrics_port, recorder_spans, recorder_passes,
           no_alerts, incident_dir, no_profile, profile_sampling_hz,
           log_json, verbose,
           price_book=None, enable_repack=False,
           reconcile_shards=0) -> Controller:
    import time as _time

    from tpu_autoscaler.logging_setup import setup_logging
    from tpu_autoscaler.obs import (
        AlertEngine,
        BlackBox,
        FlightRecorder,
        PassProfiler,
        StackSampler,
    )

    setup_logging(verbose=verbose, json_format=log_json)
    book = None
    if price_book:
        import yaml

        from tpu_autoscaler.cost import PriceBook

        try:
            with open(price_book, encoding="utf-8") as f:
                book = PriceBook.from_dict(yaml.safe_load(f) or {})
        except (OSError, ValueError, yaml.YAMLError) as e:
            raise click.BadParameter(
                f"invalid price book {price_book!r}: {e}",
                param_hint="--price-book") from None
    notifier = (SlackNotifier(slack_hook, slack_channel) if slack_hook
                else LogNotifier())
    metrics = Metrics()
    config = ControllerConfig(
        policy=_policy(default_generation, generation_fallbacks,
                       cpu_machine_type, over_provision,
                       spare_agents, spare_slices, namespace_quotas,
                       max_cpu_nodes, max_total_chips, preemptible,
                       fair_share),
        grace_seconds=grace_period,
        idle_threshold_seconds=idle_threshold,
        drain_grace_seconds=drain_grace,
        utilization_threshold=utilization_threshold,
        gang_settle_seconds=gang_settle,
        provision_timeout_seconds=provision_timeout,
        enable_preemption=preemption,
        reconcile_shards=reconcile_shards,
        enable_repack=enable_repack,
        price_book=book,
        no_scale=no_scale, no_maintenance=no_maintenance)
    policy_engine = None
    if enable_policy:
        from tpu_autoscaler.policy import (
            PolicyConfig,
            PolicyEngine,
            SloPolicy,
        )

        policy_engine = PolicyEngine(PolicyConfig(slo=SloPolicy(
            min_confidence=policy_min_confidence,
            waste_budget_chip_seconds=policy_waste_budget,
            # Early reclaim is an explicit operator opt-in from the
            # CLI: during the cold-start learning window no class has
            # a confident forecast, and silently shrinking every idle
            # threshold to the floor would override --idle-threshold
            # the operator configured.
            early_reclaim=policy_early_reclaim,
            idle_ceiling_seconds=max(7200.0, idle_threshold * 4))))
    controller = Controller(
        kube, actuator, config, notifier, metrics,
        policy_engine=policy_engine,
        # Ring capacities are operator knobs now (ISSUE 10 satellite):
        # deep rings for incident-heavy fleets, shallow for tiny ones.
        recorder=FlightRecorder(max_spans=recorder_spans,
                                max_passes=recorder_passes),
        alert_engine=AlertEngine(rules=()) if no_alerts else None,
        # Control-plane profiler (docs/OBSERVABILITY.md "Control-plane
        # profiling"): on by default like the alert engine; the
        # collapsed-stack sampler is a strict opt-in (it spawns a
        # thread).
        profiler=PassProfiler(
            clock=_time.perf_counter, metrics=metrics,
            enabled=not no_profile,
            sampler=(StackSampler(hz=profile_sampling_hz,
                                  metrics=metrics)
                     if profile_sampling_hz > 0 else None)))
    if incident_dir:
        # Black-box capture on alert fire (obs/blackbox.py).  Wired
        # post-ctor: the bundle producer IS a controller method.
        controller.blackbox = BlackBox(incident_dir,
                                       controller.incident_bundle,
                                       metrics=metrics)
    if metrics_port:
        # Serve /metrics + /healthz + /debugz + /debugz/tsdb +
        # /debugz/cost together (discoverable via /debugz/index): the
        # flight-recorder dump, the metric history and the cost bill
        # all ride the port operators already expose.
        metrics.serve(metrics_port, debugz=controller.debug_dump,
                      routes={"/debugz/tsdb": controller.tsdb_route,
                              "/debugz/cost": controller.cost_route,
                              "/debugz/repack":
                                  controller.repack_route,
                              "/debugz/profile":
                                  controller.profile_route})
    return controller


_kube_options = [
    click.option("--kube-url", default=None,
                 help="Apiserver URL (default: in-cluster)."),
    click.option("--kube-token", default=None),
    click.option("--kubeconfig", default=None,
                 help="Path to a kubeconfig file (reference: --kubeconfig)."),
    click.option("--kube-context", default=None,
                 help="kubeconfig context name (default: current-context)."),
]


def kube_options(f):
    for opt in reversed(_kube_options):
        f = opt(f)
    return f


def make_kube_client(kube_url, kube_token, kubeconfig, kube_context,
                     dry_run=False):
    """One connection path for every subcommand: kubeconfig > explicit
    URL/token > in-cluster."""
    from tpu_autoscaler.k8s.client import RestKubeClient

    import yaml

    try:
        if kubeconfig:
            return RestKubeClient.from_kubeconfig(kubeconfig, kube_context,
                                                  dry_run=dry_run)
    except (OSError, KeyError, AttributeError, TypeError, ValueError,
            yaml.YAMLError) as e:
        # Malformed/missing kubeconfig: a clean CLI error naming the
        # file, not a traceback — and not misdiagnosed as connectivity.
        raise click.UsageError(
            f"could not load kubeconfig {kubeconfig!r}: "
            f"{e.__class__.__name__}: {e}") from e
    try:
        return RestKubeClient(base_url=kube_url, token=kube_token,
                              dry_run=dry_run)
    except (RuntimeError, OSError) as e:
        # No cluster reachable: `run` outside a cluster is a common
        # first touch — fail politely.
        raise click.UsageError(
            f"cannot connect to a cluster: {e} — pass --kube-url/"
            "--kubeconfig or run in-cluster") from e


@click.group()
def cli():
    """TPU-native Kubernetes cluster autoscaler."""


@cli.command()
@common_options
@kube_options
@click.option("--actuator", "actuator_kind", default="gke",
              type=click.Choice(["gke", "queued-resources"]),
              show_default=True)
@click.option("--project", default=None, help="GCP project id.")
@click.option("--location", default=None, help="GCE zone / region.")
@click.option("--cluster", default=None, help="GKE cluster name.")
@click.option("--dry-run", is_flag=True,
              help="Log mutations instead of performing them.")
@click.option("--leader-elect", is_flag=True,
              help="Coordinate replicas via a kube-system Lease; only the "
                   "leader acts.")
@click.option("--actuation-workers", default=16, show_default=True,
              type=click.IntRange(min=0),
              help="Concurrent actuation dispatches (pooled sessions, "
                   "batched polling; 0 = serial blocking actuation).")
def run(kube_url, kube_token, kubeconfig, kube_context, actuator_kind,
        project, location, cluster, dry_run, leader_elect,
        actuation_workers, sleep, **kw):
    """Run against a real cluster (in-cluster, --kubeconfig, or
    --kube-url)."""
    kube = make_kube_client(kube_url, kube_token, kubeconfig, kube_context,
                            dry_run=dry_run)
    executor = None
    if actuation_workers > 0:
        from tpu_autoscaler.actuators.executor import ActuationExecutor

        executor = ActuationExecutor(max_workers=actuation_workers)
    if actuator_kind == "gke":
        from tpu_autoscaler.actuators.gke import GkeNodePoolActuator

        actuator = GkeNodePoolActuator(project=project, location=location,
                                       cluster=cluster, dry_run=dry_run,
                                       executor=executor)
    else:
        from tpu_autoscaler.actuators.queued_resources import (
            QueuedResourceActuator,
        )

        actuator = QueuedResourceActuator(project=project, zone=location,
                                          dry_run=dry_run,
                                          executor=executor)
    # NOTE: no --once / cron mode on purpose: in-flight provision tracking
    # and all scale-down timers are in-memory by design (crash-only), so a
    # process-per-pass invocation would double-provision materializing
    # slices and never reach any idle threshold. Run as a long-lived
    # Deployment (deploy/autoscaler.yaml).
    controller = _build(kube, actuator, sleep=sleep, **kw)
    # SIGUSR1 → full incident bundle to /tmp (a strict superset of
    # the old flight-recorder dump: the `trace`/`explain` CLI reads it
    # unchanged, and `python -m tpu_autoscaler.obs replay` gets the
    # TSDB + alert sections too), for controllers whose metrics port
    # is off or firewalled (docs/OBSERVABILITY.md).
    from tpu_autoscaler.obs import install_sigusr1

    install_sigusr1(lambda: controller.incident_bundle("sigusr1"))
    lock = None
    if leader_elect:
        from tpu_autoscaler.k8s.leader import LeaseLock

        lock = LeaseLock(kube)
    controller.run_forever(interval_seconds=sleep, leader_lock=lock)


@cli.command()
@kube_options
@click.option("--node-name", default=None,
              help="Node object to label (default: NODE_NAME env via the "
                   "downward API, else this host's hostname).")
@click.option("--slice-id", default=None,
              help="Unit id to stamp (default: TPU_AUTOSCALER_SLICE_ID "
                   "env, else derived from the '<id>-w-<n>' hostname "
                   "convention).")
@click.option("--pool", default=None,
              help="Pool label value (default: TPU_AUTOSCALER_POOL env, "
                   "else 'tpuas').")
@click.option("--shape", default=None,
              help="Catalog shape name (e.g. v5e-8). Default: "
                   "TPU_AUTOSCALER_SHAPE env, else resolved from the "
                   "tpu-env metadata's ACCELERATOR_TYPE.")
@click.option("--interval", default=60.0, show_default=True,
              type=click.FloatRange(min=1.0),
              help="Seconds between label assertions.")
@click.option("--once", is_flag=True, help="Assert once and exit.")
@click.option("--dry-run", is_flag=True,
              help="Log the patch instead of sending it.")
@click.option("--log-json", is_flag=True, help="Structured JSON logs.")
@click.option("-v", "--verbose", is_flag=True)
def agent(kube_url, kube_token, kubeconfig, kube_context, node_name,
          slice_id, pool, shape, interval, once, dry_run, log_json,
          verbose):
    """Slice-registration agent for QueuedResource TPU VM fleets.

    Runs on each TPU VM host and stamps its Node object with the
    slice-id / pool / accelerator / topology labels the controller keys
    on (GKE node pools get these natively; QR fleets need the agent).
    """
    from tpu_autoscaler import agent as agent_mod
    from tpu_autoscaler.logging_setup import setup_logging

    setup_logging(verbose=verbose, json_format=log_json)
    env = dict(os.environ)
    if slice_id:
        env["TPU_AUTOSCALER_SLICE_ID"] = slice_id
    if pool:
        env["TPU_AUTOSCALER_POOL"] = pool
    if shape:
        env["TPU_AUTOSCALER_SHAPE"] = shape
    if node_name:
        env["NODE_NAME"] = node_name
    try:
        identity = agent_mod.discover_identity(
            env, tpu_env_text=agent_mod.fetch_tpu_env())
    except ValueError as e:
        raise click.UsageError(str(e)) from e
    kube = make_kube_client(kube_url, kube_token, kubeconfig, kube_context,
                            dry_run=dry_run)
    agent_mod.run_agent(kube, identity, interval=interval, once=once)


@cli.command()
@kube_options
@click.option("--default-generation", default="v5e", show_default=True)
@click.option("--json", "as_json", is_flag=True,
              help="Machine-readable output.")
@click.option("--plan", "show_plan", is_flag=True,
              help="Also show a what-if plan from current cluster state "
                   "(default policy; ignores the live controller's "
                   "in-flight work and configured policy).")
def status(kube_url, kube_token, kubeconfig, kube_context,
           default_generation, as_json, show_plan):
    """Read-only snapshot: supply units + pending gangs with fit verdicts."""
    import json as _json

    from tpu_autoscaler.controller.status import (
        build_plan,
        build_status,
        render_status,
    )

    kube = make_kube_client(kube_url, kube_token, kubeconfig, kube_context)
    nodes, pods = kube.list_nodes(), kube.list_pods()
    if as_json:
        snap = build_status(nodes, pods, default_generation)
        if show_plan:
            snap["plan"] = build_plan(nodes, pods, default_generation)
        click.echo(_json.dumps(snap, indent=2))
        return
    click.echo(render_status(nodes, pods, default_generation))
    if show_plan:
        plan = build_plan(nodes, pods, default_generation)
        click.echo("WOULD PROVISION")
        if not plan["requests"]:
            click.echo("  (nothing)")
        for r in plan["requests"]:
            click.echo(f"  {r['count']}x {r['shape']}"
                       + (f" for {r['gang']}" if r["gang"] else "")
                       + f" ({r['reason']})")
        for item in plan["unsatisfiable"]:
            click.echo(f"  UNSATISFIABLE {item['gang']}: {item['reason']}")


@cli.command()
@common_options
@click.option("--scenario", default="v5e-8", show_default=True,
              type=click.Choice(["cpu", "v5e-8", "v5e-64", "2xv5p-128",
                                 "v5p-256", "churn"]),
              help="Pending workload to simulate (BASELINE eval configs, "
                   "or 'churn' for randomized fleet traffic).")
@click.option("--provision-delay", default=90.0, show_default=True,
              help="Simulated cloud provisioning delay seconds.")
@click.option("--until", default=3600.0, show_default=True,
              help="Simulated seconds to run.")
@click.option("--scale-down", is_flag=True,
              help="After the job runs, complete it and demo the "
                   "slice-atomic reclaim to zero.")
def demo(scenario, provision_delay, until, scale_down, sleep, **kw):
    """Run the full loop against the in-memory fake cloud (simulated time).

    Prints scale events and the measured Unschedulable→Running latency —
    an executable version of BASELINE.md's eval configs.
    """
    from tpu_autoscaler.actuators.fake import FakeActuator
    from tpu_autoscaler.k8s.fake import FakeKube
    from tpu_autoscaler.sim import seed_scenario, simulate, simulate_churn

    kube = FakeKube()
    actuator = FakeActuator(kube, provision_delay=provision_delay)
    controller = _build(kube, actuator, sleep=sleep, **kw)
    if scenario == "churn":
        click.echo(simulate_churn(kube, controller, until=until,
                                  step=sleep))
        sys.exit(0)
    chips = seed_scenario(kube, scenario)
    result = simulate(kube, controller, until=until, step=sleep,
                      scenario=scenario, chips_requested=chips,
                      scale_down=scale_down)
    click.echo(result.describe())
    sys.exit(0 if result.all_running else 1)


def _read_dump_file(source):
    """Load one JSON dump file, wrapping failures as clean CLI
    errors."""
    import json as _json

    try:
        with open(source, encoding="utf-8") as f:
            return _json.load(f)
    except (OSError, ValueError) as e:
        raise click.UsageError(
            f"could not read dump {source!r}: {e}") from e


def _debugz_url(url, endpoint, params=None):
    """Normalize an operator-supplied controller URL to one debug
    endpoint: bare ``host:port`` gets a scheme, a trailing ``/debugz``
    is treated as the PORT'S debug root (so the URL form ``trace``/
    ``explain`` accept also works for ``/debugz/tsdb`` instead of
    yielding ``/debugz/debugz/tsdb`` — review-found), and ``endpoint``
    is appended unless already present."""
    import urllib.parse

    if "://" not in url:
        url = f"http://{url}"
    url = url.rstrip("/")
    if not url.endswith(endpoint):
        if url.endswith("/debugz") and endpoint.startswith("/debugz"):
            url = url[:-len("/debugz")]
        url += endpoint
    if params:
        url += "?" + urllib.parse.urlencode(params)
    return url


def _fetch_debugz(url, endpoint, params=None):
    """GET one debug endpoint off a live controller, wrapping failures
    as clean CLI errors — shared by every dump-reading subcommand."""
    import json as _json
    import urllib.request

    url = _debugz_url(url, endpoint, params)
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return _json.loads(r.read().decode())
    except (OSError, ValueError) as e:
        raise click.UsageError(
            f"could not fetch {url!r}: {e} — is the controller running "
            "with --metrics-port?") from e


def _require_one_source(source, url, what):
    if bool(source) == bool(url):
        raise click.UsageError(
            f"pass exactly one of --from FILE ({what}) or "
            "--url http://HOST:METRICS_PORT (a live controller)")


def _load_dump(source, url):
    """Read a flight-recorder dump: a SIGUSR1 file / incident bundle
    (``--from``) or a live controller's ``/debugz`` endpoint
    (``--url``, which may be just ``host:port``)."""
    _require_one_source(source, url, "a SIGUSR1 dump")
    if source:
        return _read_dump_file(source)
    return _fetch_debugz(url, "/debugz")


_dump_options = [
    click.option("--from", "source", default=None,
                 type=click.Path(exists=True, dir_okay=False),
                 help="Flight-recorder dump file (written on SIGUSR1)."),
    click.option("--url", default=None,
                 help="Live controller /debugz URL (or just host:port)."),
]


def dump_options(f):
    for opt in reversed(_dump_options):
        f = opt(f)
    return f


@cli.command()
@dump_options
@click.argument("trace_id", required=False)
def trace(source, url, trace_id):
    """Render one gang scale-up as a span tree (no TRACE_ID: list
    recorded traces).

    The tree runs first-Unschedulable → observe/plan/dispatch →
    provision ACTIVE → node registration → all pods Running; span
    durations decompose `scale_up_latency_seconds` per phase
    (docs/OBSERVABILITY.md).
    """
    from tpu_autoscaler.obs.render import list_traces, render_trace

    dump = _load_dump(source, url)
    if trace_id:
        click.echo(render_trace(dump, trace_id))
    else:
        click.echo(list_traces(dump))


def _series_match(name, pattern):
    """Series-filter predicate: a plain pattern is a name PREFIX (the
    original contract); one carrying glob metacharacters (``*?[``)
    must glob-match the WHOLE name (ISSUE 12 satellite — ``--prefix
    'repack_*'`` / ``'frag_score_*'``).  One predicate for both the
    ``--url`` and ``--from`` paths, pinned equal by the parity test
    (tests/test_repack.py)."""
    if not pattern:
        return True
    if any(ch in pattern for ch in "*?["):
        import fnmatch

        return fnmatch.fnmatchcase(name, pattern)
    return name.startswith(pattern)


def _load_tsdb_dump(source, url, prefix, window):
    """Read a TSDB dump: a live controller's ``/debugz/tsdb`` (with
    server-side prefix/window filtering) or any incident bundle /
    SIGUSR1 file (its ``tsdb`` section; filtered client-side).
    Glob patterns filter client-side in BOTH modes (the server speaks
    plain prefixes; it is sent the glob's literal head to narrow the
    transfer, and the glob finishes here — url/file parity)."""
    import re as _re

    _require_one_source(source, url, "an incident bundle")
    globbing = bool(prefix) and any(ch in prefix for ch in "*?[")
    if not source:
        params = {}
        if prefix:
            head = _re.split(r"[*?\[]", prefix, 1)[0] if globbing \
                else prefix
            if head:
                params["prefix"] = head
        if window:
            params["window"] = str(window)
        body = _fetch_debugz(url, "/debugz/tsdb", params)
        if globbing and isinstance(body.get("series"), dict):
            body["series"] = {n: s for n, s in body["series"].items()
                             if _series_match(n, prefix)}
        return body
    raw = _read_dump_file(source)
    body = dict(raw.get("tsdb", raw))  # bundle section, or a bare dump
    series = {n: s for n, s in body.get("series", {}).items()
              if _series_match(n, prefix)}
    if window:
        # Client-side window trim (the --url branch filters
        # server-side): "now" is the newest timestamp the bundle
        # retains, matching the capture instant closely enough.
        newest = max((row[0] for s in series.values()
                      for tier in ("raw", "mid", "coarse")
                      for row in s.get(tier, ())), default=0.0)
        floor = newest - window
        series = {
            n: {k: ([row for row in v if row[0] >= floor]
                    if k in ("raw", "mid", "coarse") else v)
                for k, v in s.items()}
            for n, s in series.items()}
    body["series"] = series
    return body


@cli.command("metrics-history")
@dump_options
@click.argument("series", required=False)
@click.option("--prefix", default="",
              help="Series-name prefix filter (listing mode).")
@click.option("--window", default=None, type=float,
              help="Only this many trailing seconds of history.")
@click.option("--points", "max_points", default=24, show_default=True,
              help="Recent points to print per series.")
@click.option("--format", "fmt", default="table", show_default=True,
              type=click.Choice(["table", "csv"]),
              help="table = human rendering; csv = machine-readable "
                   "rows for offline analysis (works for both --url "
                   "and --from).")
def metrics_history(source, url, series, prefix, window, max_points,
                    fmt):
    """Metric history from the in-process TSDB (docs/OBSERVABILITY.md
    "Time-series history"): list retained series, or render one
    series' recent points with its downsampled min/max envelope —
    "when did p99 scale-up start degrading?" without external
    infrastructure.  ``--format csv`` streams the same data as CSV
    (ISSUE 11 satellite): listing mode emits one summary row per
    series; single-series mode emits every retained point across all
    tiers, so ledger/TSDB history pulls straight into pandas."""
    dump = _load_tsdb_dump(source, url, prefix if not series else series,
                           window)
    all_series = dump.get("series", {})
    if dump.get("unavailable"):
        click.echo("(tsdb snapshot unavailable: writer was mutating; "
                   "retry)")
        return
    if not series:
        if fmt == "csv":
            click.echo("series,points,last_t,last_value")
            for name in sorted(all_series):
                raw = all_series[name].get("raw", [])
                last_t = f"{raw[-1][0]:g}" if raw else ""
                last_v = f"{raw[-1][1]:g}" if raw else ""
                click.echo(f"{name},{len(raw)},{last_t},{last_v}")
            return
        tiers = dump.get("tiers", {})
        click.echo(f"{len(all_series)} series retained "
                   f"(raw={tiers.get('raw_points')}p, "
                   f"mid={tiers.get('mid_seconds')}s, "
                   f"coarse={tiers.get('coarse_seconds')}s)")
        for name in sorted(all_series):
            raw = all_series[name].get("raw", [])
            last = f"{raw[-1][1]:g} @ {raw[-1][0]:g}" if raw else "(empty)"
            click.echo(f"  {name}  points={len(raw)}  last={last}")
        return
    body = all_series.get(series)
    if body is None:
        known = ", ".join(sorted(all_series)[:20]) or "(none)"
        raise click.UsageError(
            f"series {series!r} not retained; known (first 20): {known}")
    if fmt == "csv":
        # Every retained point, all tiers: raw rows carry value only;
        # downsampled buckets carry their full aggregate columns.
        click.echo("series,tier,t,value,min,max,sum,count")
        for tier in ("coarse", "mid"):
            for r in body.get(tier, []):
                click.echo(f"{series},{tier},{r[0]:g},{r[1]:g},"
                           f"{r[2]:g},{r[3]:g},{r[4]:g},{int(r[5])}")
        for t, v in body.get("raw", []):
            click.echo(f"{series},raw,{t:g},{v:g},,,,")
        return
    for tier in ("coarse", "mid"):
        rows = body.get(tier, [])
        if rows:
            click.echo(f"{tier} ({len(rows)} buckets): "
                       f"min={min(r[2] for r in rows):g} "
                       f"max={max(r[3] for r in rows):g}")
    raw = body.get("raw", [])
    click.echo(f"raw ({len(raw)} points, showing {max_points}):")
    for t, v in raw[-max_points:]:
        click.echo(f"  t={t:g}  {v:g}")
    # Exemplars (ISSUE 14): a histogram family's series resolve to a
    # concrete sampled trace — the bucket series are named
    # ``family:le:<bound>``, so match on the family prefix.
    for fam, rows in sorted(dump.get("exemplars", {}).items()):
        if rows and (series == fam or series.startswith(f"{fam}:")):
            t, v, tid = rows[-1]
            click.echo(f"exemplar: trace {tid}  value={v:g}  "
                       f"@{t:g}  (tpu-autoscaler trace {tid})")


@cli.command("cost-report")
@dump_options
@click.option("--window", default=None, type=float,
              help="Also render a trailing-window bill from the TSDB's "
                   "cost_* history (seconds).")
@click.option("--top", default=10, show_default=True,
              help="Gangs to list in the cost-to-serve ranking.")
@click.option("--frag", "frag", is_flag=True,
              help="Also render the per-pool fragmentation breakdown "
                   "(stranded / displaced / overprovisioned component "
                   "chips and what the repacker would do about each — "
                   "docs/REPACK.md).")
def cost_report(source, url, window, top, frag):
    """Render the fleet bill (docs/COST.md): every chip-second
    attributed by state / pool / accelerator class / price tier, the
    per-gang cost-to-serve ranking, fragmentation scores, and the
    conservation verdict — from a live controller's ``/debugz/cost``
    or any incident bundle / SIGUSR1 dump."""
    from tpu_autoscaler.cost import (
        render_bill,
        render_frag,
        render_windowed,
        windowed_bill,
    )

    _require_one_source(source, url, "an incident bundle")
    if source:
        raw = _read_dump_file(source)
        cost = raw.get("cost")
        tsdb = raw.get("tsdb")
        if cost is None:
            raise click.UsageError(
                f"{source!r} carries no cost section — capture a fresh "
                "bundle (SIGUSR1 / alert firing) from a build with the "
                "cost ledger")
    else:
        cost = _fetch_debugz(url, "/debugz/cost")
        tsdb = _fetch_debugz(url, "/debugz/tsdb",
                             {"prefix": "cost_"}) if window else None
    if cost.get("unavailable"):
        click.echo("(cost snapshot unavailable: writer was mutating; "
                   "retry)")
        return
    click.echo(render_bill(cost, top_gangs=top))
    if frag:
        click.echo("")
        click.echo(render_frag(cost))
    if window:
        if not tsdb or not tsdb.get("series"):
            raise click.UsageError(
                "--window needs cost_* TSDB history (none retained in "
                "this source)")
        click.echo("")
        click.echo(render_windowed(windowed_bill(tsdb, window)))


@cli.command("repack-report")
@dump_options
def repack_report(source, url):
    """Render the repacker's books (docs/REPACK.md): migration totals
    and net savings, the rolling cost budget, in-flight migrations,
    recent closes with their chip-seconds-saved attribution, and why
    the last pass's candidates were turned down — from a live
    controller's ``/debugz/repack`` or any incident bundle."""
    from tpu_autoscaler.repack import render_repack

    _require_one_source(source, url, "an incident bundle")
    if source:
        raw = _read_dump_file(source)
        body = raw.get("repack")
        if body is None:
            raise click.UsageError(
                f"{source!r} carries no repack section — capture a "
                "fresh bundle from a build with the repacker")
    else:
        body = _fetch_debugz(url, "/debugz/repack")
    if body.get("unavailable"):
        click.echo("(repack snapshot unavailable: writer was "
                   "mutating; retry)")
        return
    click.echo(render_repack(body))


@cli.command("tail-report")
@dump_options
@click.option("--window", nargs=2, type=float, default=None,
              help="Analysis window [START END] in controller time "
                   "(default: the serving-SLO alert's breach window "
                   "when the source carries one, else all retained "
                   "tail captures).")
@click.option("--json", "as_json", is_flag=True,
              help="Machine-readable report.")
def tail_report(source, url, window, as_json):
    """Tail-latency root-cause attribution (docs/OBSERVABILITY.md
    "Request spans & exemplars"): decompose the sampled SLO-missing
    requests into attributed phases (queue wait / prefill / decode /
    preemption requeue / drain), correlate with the TSDB (KV
    occupancy, queue depth, preemption rate), and — when the tail is
    dominated by requests waiting for capacity — cross-link the
    ``scaleup-*`` control-plane trace whose provision would have
    absorbed it: one causal chain from user-visible p99 burn down to
    stockout/quota/actuation latency."""
    import json as _json

    from tpu_autoscaler.obs import tailcause

    _require_one_source(source, url, "an incident bundle")
    if source:
        bundle = _read_dump_file(source)
    else:
        # Assemble the analyzer's bundle shape from the live debug
        # endpoints: spans + alerts from /debugz, history + exemplars
        # from /debugz/tsdb.
        bundle = _fetch_debugz(url, "/debugz")
        bundle["tsdb"] = _fetch_debugz(url, "/debugz/tsdb")
    report = tailcause.analyze(
        bundle, window=tuple(window) if window else None)
    if as_json:
        click.echo(_json.dumps(report, indent=2, default=str))
        return
    click.echo(tailcause.render_report(report))


@cli.command("perf-report")
@dump_options
@click.option("--window", default=None, type=float,
              help="Trailing window in seconds (default: the whole "
                   "retained history).")
@click.option("--against", default=None,
              type=click.Path(exists=True, dir_okay=False),
              help="Second bundle / SIGUSR1 dump as the BEFORE side; "
                   "the main source is the AFTER — the diff names "
                   "the regressing phase.")
@click.option("--json", "as_json", is_flag=True,
              help="Machine-readable report.")
def perf_report(source, url, window, against, as_json):
    """Where the control plane's milliseconds went
    (docs/OBSERVABILITY.md "Control-plane profiling"): per-phase
    self-time decomposition of the reconcile pass over the profiler's
    ``pass_phase_seconds_*`` TSDB series — from a live controller's
    ``/debugz/tsdb`` or any incident bundle / SIGUSR1 dump.  With
    ``--against``, diffs the two windows on phase SHARES and names
    the regressing phase — the offline twin of the
    ``phase-share-drift`` alert rule."""
    import json as _json

    from tpu_autoscaler.obs import perfreport

    _require_one_source(source, url, "an incident bundle")
    if source:
        raw = _read_dump_file(source)
        dump = raw.get("tsdb", raw)
    else:
        dump = _fetch_debugz(url, "/debugz/tsdb",
                             {"prefix": "pass_phase_seconds_"})
    report = perfreport.decompose(dump, window)
    if against:
        raw_before = _read_dump_file(against)
        before = perfreport.decompose(
            raw_before.get("tsdb", raw_before), window)
        delta = perfreport.diff(before, report)
        if as_json:
            click.echo(_json.dumps({"before": before, "after": report,
                                    "diff": delta}, indent=2))
            return
        click.echo(perfreport.render_report(report))
        click.echo("")
        click.echo(perfreport.render_diff(delta))
        return
    if as_json:
        click.echo(_json.dumps(report, indent=2))
        return
    click.echo(perfreport.render_report(report))


@cli.command()
@dump_options
@click.option("--last", default=5, show_default=True,
              help="How many recent reconcile passes to show (0=all).")
@click.option("--subject", default=None,
              help="Filter decisions by substring (gang, unit, shape).")
def explain(source, url, last, subject):
    """Explain recent reconcile passes: inputs digest + per-unit
    decisions ("why did/didn't we provision") from the flight
    recorder."""
    from tpu_autoscaler.obs.render import render_passes

    dump = _load_dump(source, url)
    click.echo(render_passes(dump, last=last, subject=subject))


if __name__ == "__main__":
    cli()
