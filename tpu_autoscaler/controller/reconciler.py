"""The control loop (L4): observe → decide → act.

Analog of the reference's cluster.py §Cluster.loop_logic / §Cluster.scale /
§Cluster.maintain, with the reconcile re-derived for slice-atomic supply:

- crash-only: every pass recomputes desired state from scratch; the only
  cross-pass memory is timers (SliceTracker) whose loss merely delays
  scale-down (SURVEY.md §6.3);
- non-blocking actuation: provisions are submitted and polled, never waited
  on (reference: deployments.py "don't block beyond submission"), and
  disjoint gangs provision in parallel (the reference's one-in-flight
  serialization is too blunt for <6 min at 256 chips, SURVEY.md §8);
- maintain operates on supply *units* — TPU slices and single CPU nodes —
  cordoning, draining (checkpoint-aware), and deleting whole units only.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time

from tpu_autoscaler.actuators.base import (
    ACTIVE,
    FAILED,
    Actuator,
    in_flight_of,
)
from tpu_autoscaler.cost import CostLedger
from tpu_autoscaler.engine.planner import InFlight, Planner, PoolPolicy
from tpu_autoscaler.k8s.client import KubeClient
from tpu_autoscaler.k8s.gangs import Gang, group_into_gangs
from tpu_autoscaler.k8s.objects import (
    UNSATISFIABLE_ANNOTATION,
    Node,
    Pod,
)
from tpu_autoscaler.metrics import Metrics
from tpu_autoscaler.notify import LogNotifier, Notifier
from tpu_autoscaler.obs import (
    AlertEngine,
    FlightRecorder,
    Span,
    TimeSeriesDB,
    Tracer,
)
from tpu_autoscaler.obs.profiler import PassProfiler
from tpu_autoscaler.state import SliceState, SliceTracker, classify_slice
from tpu_autoscaler.state.tracker import DRAIN_ANNOTATION

log = logging.getLogger(__name__)

# Annotation stamped on workload pods when their slice is being reclaimed:
# the checkpoint contract. A TPU job that sees this on itself should write a
# checkpoint and exit cleanly before the drain deadline (BASELINE config #5;
# see tpu_autoscaler.workloads.checkpoint for the job-side helper).
CHECKPOINT_ANNOTATION = "autoscaler.tpu.dev/checkpoint-requested"

# UNSATISFIABLE_ANNOTATION (stamped on pods of gangs the planner cannot
# satisfy, or whose provisions fail — with the reason) is defined in
# k8s/objects.py and re-exported via the import block above, keeping
# read-only consumers decoupled from this module.

# Node taints GKE applies ahead of involuntary termination (TPU
# maintenance events, spot/preemptible reclamation).  Any host of a unit
# carrying one of these puts the WHOLE unit into the checkpoint-aware
# drain path — the hardware is going away regardless; the job gets the
# drain window instead of a hard kill.
TERMINATION_TAINT_KEYS = frozenset({
    "cloud.google.com/impending-node-termination",
    "DeletionCandidateOfClusterAutoscaler",
})


@dataclasses.dataclass
class ControllerConfig:
    policy: PoolPolicy = dataclasses.field(default_factory=PoolPolicy)
    # Post-launch grace before a unit may be reclaimed (reference: launch
    # grace period in cluster.py's state machine).
    grace_seconds: float = 300.0
    # Idle time before reclaim (reference: --idle-threshold, default 1800).
    idle_threshold_seconds: float = 1800.0
    # Bounded wait for the checkpoint contract before force-evicting.
    drain_grace_seconds: float = 120.0
    # A Ready slice with a NotReady host is replaced after this long.
    unhealthy_timeout_seconds: float = 600.0
    # ICI-atomic slice repair (ISSUE 7): a broken slice that still hosts
    # Running workload pods is repaired — cordon + checkpoint-drain the
    # gang, replace the WHOLE slice as one unit (never a lone host into
    # an ICI domain), with advisory replacement demand fed to the
    # planner so provisioning overlaps the drain.
    enable_slice_repair: bool = True
    # Flap window before a NotReady host inside a workload-bearing slice
    # triggers repair.  A host whose Node object was DELETED repairs
    # immediately: the apiserver affirmatively removed it, there is
    # nothing to flap.  (unhealthy_timeout_seconds still governs
    # workload-free unhealthy slices — nothing to repair toward.)
    slice_repair_after_seconds: float = 120.0
    # Give up TRACKING a repair after this long (span closes abandoned,
    # supply-guard holds release); the normal drain/backoff machinery
    # keeps converging regardless — this only bounds bookkeeping.
    slice_repair_timeout_seconds: float = 3600.0
    # Backoff before re-provisioning after a FAILED provision (the
    # reference's blunt one-deployment-at-a-time serialization throttled
    # retries implicitly; we need it explicit).
    provision_retry_seconds: float = 60.0
    # A provision stuck in ACCEPTED/PROVISIONING this long (stockout that
    # never reports FAILED) is cancelled and retried — without this the
    # gang it serves waits forever behind a dead in-flight entry
    # (SURVEY §8 hard parts: "slice stuck in PROVISIONING").
    provision_timeout_seconds: float = 900.0
    # Consolidation: CPU units busier than idle but below this requested/
    # allocatable fraction, with all pods movable, are drained so their
    # pods repack onto other nodes (reference: UNDER_UTILIZED_DRAINABLE).
    # 0.0 disables (default: consolidation moves pods, opt in explicitly).
    utilization_threshold: float = 0.0
    # Damping for gangs WITHOUT an exact topology pin: wait this long after
    # the gang's first pod appears before sizing a slice for it, so a Job
    # whose pods materialize gradually isn't fitted against a partial
    # observation (pinned gangs are exact regardless and never wait).
    gang_settle_seconds: float = 0.0
    # Checkpoint-aware priority preemption: a gang unsatisfiable ONLY
    # because of max_total_chips may reclaim chips from busy units whose
    # workload has strictly lower priority — those jobs get the drain
    # window (checkpoint + clean exit) and re-queue behind the clamp.
    # Off by default: preemption moves victims' work.
    enable_preemption: bool = False
    # Delta-driven planning (docs/INFORMER.md): with an informer
    # attached, re-plan only gangs whose inputs digest (member pods,
    # candidate supply class, serving in-flight/guard entries, backoff
    # state) changed since the last pass; every plan_resync_passes-th
    # pass re-plans everything as the safety net.  The planner stays a
    # pure function (TAP1xx) — this layer only decides WHICH gangs it
    # is fed.  Auto-disabled when fair_share or preemption is on (their
    # decisions depend on the full demand set) or no informer indices
    # are available.
    delta_planning: bool = True
    plan_resync_passes: int = 16
    # Testing/bench hook: compute the full plan alongside every delta
    # plan and count divergences (delta_plan_mismatches metric).  The
    # parity gate in tests keeps the incremental path byte-identical
    # to full planning on the seeded scenarios.
    verify_delta_plans: bool = False
    # Sharded reconcile planning (ISSUE 13, docs/SHARDING.md):
    # partition plan + the maintenance claim scan by accelerator
    # class/pool across a capped worker pool, merged back on the
    # reconcile thread with byte-identical output.  0 = serial, the
    # oracle every sharded pass is provably identical to.  Auto-
    # serial per pass under fair_share/namespace quotas (cross-shard
    # admission order is load-bearing there) and below
    # shard_min_gangs (partition overhead must not tax small passes).
    reconcile_shards: int = 0
    shard_min_gangs: int = 16
    # Columnar planner core (docs/PLANNER.md): run the planner's hot
    # loops over the informer-maintained struct-of-arrays state
    # (k8s/columnar.py) when its digest stamps prove it describes
    # exactly this pass's observation — otherwise (or on any error)
    # the Python planner runs alone, crash-only.  Composes with
    # reconcile_shards (per-shard column slices).
    columnar_planning: bool = True
    # Testing/bench hook, the delta/shard landing pattern: plan every
    # pass BOTH ways and count divergences (columnar_plan_mismatches);
    # on mismatch the Python oracle's plan is adopted.
    verify_columnar_plans: bool = False
    # Cost attribution ledger (ISSUE 11, docs/COST.md): the price book
    # pricing the $-proxy rollups; None = the built-in catalog-derived
    # book.  The ledger itself is always on — it rides the _maintain
    # pass the loop already runs and costs O(churn).
    price_book: object | None = None
    # Cost-aware continuous repacking (ISSUE 12, docs/REPACK.md): a
    # background repacker reads the ledger's placement rows each pass,
    # drains wrongly-placed gangs (expensive tier while same-shape
    # spot sits idle; oversized slices) through the repair pipeline's
    # drain + advisory-replacement machinery, under a hard
    # never-costs-more-than-it-saves budget guard.  Off by default:
    # repacking moves live work (the preemption precedent).
    enable_repack: bool = False
    # RepackConfig overriding the defaults (repack/policy.py); None =
    # defaults.  Typed object (not dataclass field) to keep the
    # import lazy like price_book.
    repack: object | None = None
    # Reference parity flags (main.py --no-scale / --no-maintenance).
    no_scale: bool = False
    no_maintenance: bool = False


# Prometheus histogram bucket bounds (seconds) for the north-star phase
# latencies.  Spans watch-triggered detection (sub-second) through the
# 6-minute BASELINE budget and the cloud's worst provisioning tail, so a
# real cluster run exports the end-to-end latency distribution directly.
LATENCY_BUCKETS: tuple[float, ...] = (
    0.5, 1.0, 2.0, 5.0, 10.0, 30.0, 60.0, 120.0, 240.0, 360.0, 600.0,
    1200.0)

# Request-latency histogram bounds in ENGINE TICKS (ISSUE 14): the
# data plane's clock is its own tick counter, not wall seconds.
REQUEST_LATENCY_TICK_BUCKETS: tuple[float, ...] = (
    1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0, 500.0, 1000.0,
    2000.0)

# The per-gang phase anatomy of scale_up_latency_seconds (SURVEY §4.2):
#   detect    — gang first seen Unschedulable → provision submitted
#   provision — provision submitted → slice ACTIVE (VM boot + registration)
#   register  — first host registered → all hosts Ready (the barrier;
#               overlaps the provision tail by definition)
#   bind      — supply Ready (and gang pending) → all pods Running
PHASE_LATENCY_METRICS: tuple[str, ...] = (
    "detect_latency_seconds",
    "provision_latency_seconds",
    "ready_barrier_seconds",
    "bind_latency_seconds",
    "scale_up_latency_seconds",
)


class Controller:
    def __init__(self, client: KubeClient, actuator: Actuator,
                 config: ControllerConfig | None = None,
                 notifier: Notifier | None = None,
                 metrics: Metrics | None = None,
                 informer=None, executor=None,
                 tracer: Tracer | None = None,
                 recorder: FlightRecorder | None = None,
                 policy_engine=None, serving_scaler=None,
                 tsdb: TimeSeriesDB | None = None,
                 alert_engine: AlertEngine | None = None,
                 blackbox=None,
                 profiler: PassProfiler | None = None):
        self.client = client
        self.actuator = actuator
        self.config = config or ControllerConfig()
        self.notifier = notifier or LogNotifier()
        self.metrics = metrics or Metrics()
        # Decision tracing (docs/OBSERVABILITY.md): one trace per gang
        # scale-up, per-pass decision records, all retained in the
        # bounded flight recorder and served on /debugz + SIGUSR1.  The
        # tracer's clock matters only for spans recorded without an
        # explicit time (actuation dispatches, informer relists); every
        # controller-side span uses the injected reconcile clock so
        # simulated-time runs produce coherent traces.
        if tracer is not None:
            self.tracer = tracer
            # An injected zero-retention tracer (recorder=None) must not
            # leave the pass-record sink None — reconcile_once records
            # unconditionally.
            self.recorder = (recorder if recorder is not None
                             else tracer.recorder) or FlightRecorder()
        else:
            self.recorder = recorder if recorder is not None \
                else FlightRecorder()
            self.tracer = Tracer(recorder=self.recorder,
                                 metrics=self.metrics)
        self.tracer.bind_metrics(self.metrics)
        # Cached observe path (k8s/informer.py): when set, reconcile
        # passes read watch-fed snapshots instead of re-LISTing and
        # re-parsing the world.  None = the relist-every-pass baseline;
        # run_forever auto-creates one when the client can watch.
        self.informer = informer
        # Pipelined actuation (actuators/executor.py): completed
        # dispatches are drained at the top of every pass — the ONLY
        # place actuator state mutates off the poll/provision calls —
        # keeping all mutation on the reconcile thread.  Defaults to
        # the executor the actuator was built with (main.py wires one
        # into both); None = the serial blocking baseline.
        self.executor = (executor if executor is not None
                         else getattr(actuator, "executor", None))
        if self.executor is not None \
                and hasattr(self.executor, "set_metrics"):
            self.executor.set_metrics(self.metrics)
        if self.executor is not None \
                and hasattr(self.executor, "set_tracer"):
            self.executor.set_tracer(self.tracer)
        if hasattr(actuator, "set_tracer"):
            actuator.set_tracer(self.tracer)
        # Sticky staleness guard (_observe): node names a direct LIST
        # saw that the informer's node cache has not delivered yet.
        self._nodes_awaiting_cache: set[str] = set()
        # The store digests captured BESIDE the last _observe's cache
        # snapshots (the O(1) pass-digest path; None = the pass
        # observed via bypass/LIST and the legacy frozenset hash over
        # the observed lists applies).
        self._observed_digest: int | None = None
        self._observed_cache_digests: tuple[int, int] | None = None
        # Sticky supply guard (_update_supply_guard): provisions that
        # went ACTIVE but whose supply units have not REGISTERED as
        # nodes yet.  The informer guard above closes the cache-lag
        # half of the ACTIVE→registration window; this closes the
        # apiserver-lag half — on the serial path it is the ONLY guard
        # (the double-provision window the race harness reproduces,
        # tests/test_races.py).  id -> (planner view, unit ids, since).
        self._supply_awaiting_nodes: dict[
            str, tuple[InFlight, tuple[str, ...], float]] = {}
        # Actuators that do REST I/O surface their retry counters
        # through the controller's metrics registry (gcp.py GcpRest);
        # the real kube client does the same (kube_retries).
        if hasattr(actuator, "set_metrics"):
            actuator.set_metrics(self.metrics)
        if hasattr(client, "set_metrics"):
            client.set_metrics(self.metrics)
        self.planner = Planner(self.config.policy)
        # Sharded planning (ISSUE 13): the fan-out/merge driver, used
        # only from the reconcile thread; workers see frozen inputs
        # and the serial planner above stays the byte-identity oracle.
        # shard_balance/shard_count are exported from startup (1.0 =
        # "a serial loop is balanced") so the shard-imbalance alert
        # rule reads a defined series in every mode.
        self.sharder = None
        if self.config.reconcile_shards > 0:
            from tpu_autoscaler.controller.shard import ShardedPlanner

            self.sharder = ShardedPlanner(
                self.config.reconcile_shards, self.planner,
                metrics=self.metrics,
                min_gangs=self.config.shard_min_gangs)
        self.metrics.set_gauge("shard_balance", 1.0)
        self.metrics.set_gauge("shard_count", 0)
        self.tracker = SliceTracker()
        for name in PHASE_LATENCY_METRICS:
            self.metrics.declare_histogram(name, LATENCY_BUCKETS)
        # Data-plane request latency (ISSUE 14): fed by the exemplar
        # path — each pass the adapter's taken exemplar value (a
        # sampled request's latency, in engine ticks) is observed into
        # this family, so its TSDB series and the exemplar that links
        # them to a concrete trace are born from the same pass.
        self.metrics.declare_histogram("serving_request_latency_ticks",
                                       REQUEST_LATENCY_TICK_BUCKETS)
        # Pending (family -> (trace_id, value)) exemplars minted by
        # control-plane span closes this pass (e.g. the scale_up
        # root); drained into the TSDB by _obs_pass.
        self._span_exemplars: dict[str, tuple[str, float]] = {}
        # Gang lifecycle: first time each gang was seen Unschedulable, for
        # the north-star latency metric; cleared when the gang runs.
        self._gang_first_pending: dict[tuple, float] = {}
        # Root "scale_up" span per pending gang (same lifecycle as
        # _gang_first_pending — minted on first-Unschedulable, ended
        # when the gang runs or its pods disappear).
        self._gang_traces: dict[tuple, Span] = {}
        # Open "node_registration" spans per supply-guarded provision
        # (see _update_supply_guard), keyed by provision id — one span
        # per served trace (multislice siblings each get the anatomy).
        self._registration_spans: dict[str, list[Span]] = {}
        # Per-pass decision record state (reset at the top of every
        # reconcile_once; reconcile-thread-only).
        self._pass_seq = 0
        self._pass_events: list[dict] = []
        # The current pass's shared phase windows, replayed into each
        # served gang's trace at dispatch time: (pass now, observe
        # seconds) and plan seconds.
        self._pass_obs: tuple[float, float] = (0.0, 0.0)
        self._pass_plan_s = 0.0
        # Gangs whose detect phase (first pending → first provision
        # submitted) has been observed; swept with _gang_first_pending.
        self._gang_detect_observed: set[tuple] = set()
        self._drain_started: dict[str, float] = {}
        # First time each supply unit was observed, for the orphaned
        # partial-slice reclaim (fuzzer-found: a provision that FAILs
        # after materializing SOME hosts leaks a forever-PROVISIONING
        # partial slice nothing else cleans up).
        self._unit_first_seen: dict[str, float] = {}
        # Drains begun for idleness (not requested/unhealthy) may be
        # cancelled if matching demand appears before deletion.
        self._drain_cancellable: set[str] = set()
        self._unhealthy_since: dict[str, float] = {}
        self._reported_unsatisfiable: set[tuple] = set()
        self._seen_failures: set[str] = set()
        # Retry-at times after failed provisions, per gang key and (for
        # gang-less spare provisions) per shape name.
        self._retry_at: dict[object, float] = {}
        # Consecutive provision failures per demand unit, driving the
        # capacity-stockout generation fallback (policy
        # generation_fallbacks); reset when a provision for the unit
        # goes ACTIVE or its demand disappears.
        self._failure_streak: dict[object, int] = {}
        self._fallback_noted: dict[object, str] = {}
        # Provision submit times, for the provision_latency_seconds metric.
        self._submitted_at: dict[str, float] = {}
        # Trace roots captured at dispatch time, per provision id: a
        # provision can resolve AFTER its gang's trace closed (the gang
        # ran off other supply while this one raced), and its
        # provision/provision_failed span must still land in the trace
        # that dispatched it (fuzzer-found: "missing provision span").
        self._provision_roots: dict[str, list[Span]] = {}
        # Gang size observations for the settle window: key -> (size,
        # last-grown timestamp); swept alongside _gang_first_pending.
        self._gang_sizes: dict[tuple, tuple[int, float]] = {}
        # Delta-driven planning (ISSUE 6): per-gang inputs digest as of
        # the last pass that fed the gang to the planner; a matching
        # digest means nothing that could change the gang's plan moved,
        # so the gang is skipped this pass.  Reconcile-thread-only.
        self._gang_plan_digests: dict[tuple, int] = {}
        # This pass's planning-scope record (mode + counts), surfaced
        # in the flight recorder's decision record.
        self._pass_plan_info: dict = {}
        # Units the operator (or spot reclamation) asked us to evacuate.
        self._requested_drains: set[str] = set()
        self._seen_namespaces: set[str] = set()
        self._last_pass_at: float | None = None
        # ICI-atomic slice repair (ISSUE 7): unit id -> repair
        # bookkeeping (root span, drain span, served gang keys, the
        # like-for-like replacement shape, linked provision id).
        # Reconcile-thread-only; bounded by slice_repair_timeout.
        self._slice_repairs: dict[str, dict] = {}
        # slice_repair root spans by gang key, so replacement
        # provisions trace under the repair root (_trace_roots).
        self._repair_roots: dict[tuple, Span] = {}
        self.metrics.declare_histogram("slice_repair_seconds",
                                       LATENCY_BUCKETS)
        # Fleet cost & capacity attribution (ISSUE 11, docs/COST.md):
        # every TPU chip-second lands in exactly one state per pass,
        # conserved against the fleet total (the chaos invariant).
        # Fed from the _maintain unit loop (O(churn): an unchanged
        # unit is one tuple compare), closed once per pass BEFORE the
        # TSDB ingest so cost_*/frag_* series land the same pass.
        # Reconcile-thread-only; /debugz/cost copies bounded-retry.
        self.cost = CostLedger(
            price_book=self.config.price_book,
            metrics=self.metrics,
            stranded_after_seconds=(
                self.config.provision_timeout_seconds))
        # Cost-aware continuous repacking (ISSUE 12, docs/REPACK.md):
        # migrations ride the _slice_repairs table (kind="repack") so
        # the drain contract, advisory replacement, supply-guard holds
        # and solo-planning deferral generalize without a second
        # pipeline.  Strictly opt-in and crash-only.
        self.repacker = None
        if self.config.enable_repack:
            from tpu_autoscaler.repack import Repacker, RepackConfig

            self.repacker = Repacker(
                self.config.repack or RepackConfig(),
                price_book=self.cost.price_book)
            self.repacker.bind(metrics=self.metrics)
        self.metrics.declare_histogram("repack_seconds",
                                       LATENCY_BUCKETS)
        # Predictive SLO-driven policy (ISSUE 8, docs/POLICY.md):
        # strictly ADVISORY — the engine forecasts demand and this
        # loop feeds its prewarm demand through the planner's existing
        # advisory_gangs hook; a policy failure degrades to the
        # reactive baseline, never aborts a pass.  Reconcile-thread-
        # only, like every other piece of controller bookkeeping.
        self.policy_engine = policy_engine
        if policy_engine is not None:
            policy_engine.bind(
                metrics=self.metrics, tracer=self.tracer,
                default_generation=self.config.policy.default_generation,
                cost_ledger=self.cost)
        # This pass's policy outputs: units held for an un-consumed
        # prewarm, per-unit idle-threshold overrides (SLO/cost
        # scale-down tradeoff), and the advice digest folded into the
        # pass record.
        self._policy_holds: set[str] = set()
        self._policy_idle_overrides: dict[str, float] = {}
        self._policy_digest = 0
        # Serving-aware scaling (ISSUE 9, docs/SERVING.md): live
        # engine signals folded into replica-target advice, expressed
        # through the SAME advisory hook as prewarms and repairs.
        # Strictly advisory, crash-only, reconcile-thread-only.
        self.serving_scaler = serving_scaler
        if serving_scaler is not None:
            serving_scaler.bind(metrics=self.metrics,
                                tracer=self.tracer)
        #: The last pass's serving advice (scale-in counts are read by
        #: the serving platform / replay driver, not acted on here —
        #: replica drain rides the serve.py drain contract).
        self.serving_advice = None
        # Time-series health layer (ISSUE 10, docs/OBSERVABILITY.md):
        # every pass folds the metrics snapshot into the in-process
        # TSDB (reconcile-thread append, zero new locks on the hot
        # path) and evaluates the SLO burn-rate alert catalog over it
        # — the autoscaler watches itself.  Both halves degrade on
        # failure (counted, logged), never abort a pass.
        self.tsdb = tsdb if tsdb is not None else TimeSeriesDB()
        self.alerts = (alert_engine if alert_engine is not None
                       else AlertEngine())
        # Black-box incident capture (obs/blackbox.py): when an alert
        # FIRES, dump a self-contained bundle.  None = no automatic
        # captures (operators still get SIGUSR1 / /debugz).
        self.blackbox = blackbox
        for rule in self.alerts.rules:
            # Export the whole gauge family as 0 from the first scrape
            # — an absent series and a resolved alert must not look
            # alike to the paging layer.
            self.metrics.set_gauge(
                f"tpu_autoscaler_alerts_active_"
                f"{rule.name.replace('-', '_')}", 0.0)
        # Control-plane profiler (ISSUE 20, docs/OBSERVABILITY.md
        # "Control-plane profiling"): every pass's wall time attributed
        # to exactly one phase, conservation-checked in the cost-ledger
        # style; per-phase self-time series feed the phase-share-drift
        # sentinel above.  Reconcile-thread-only (the optional stack
        # sampler reads via sys._current_frames, never touches state);
        # disabling degrades phase() to a cheap no-op.
        self.profiler = (profiler if profiler is not None
                         else PassProfiler(clock=time.perf_counter,
                                           metrics=self.metrics))
        if serving_scaler is not None:
            adapter = getattr(serving_scaler, "adapter", None)
            if adapter is not None and hasattr(adapter, "profiler"):
                # The fold's cost nests under the serving phase even
                # when the scaler drives it from inside advise().
                adapter.profiler = self.profiler
        # The sampler targets the reconcile thread, whose identity is
        # only known once a pass runs — started lazily there.
        self._sampler_started = False

    # ------------------------------------------------------------------ #

    def reconcile_once(self, now: float | None = None) -> None:
        """One reconcile pass. All time injected for testability."""
        now = time.time() if now is None else now
        t0 = time.perf_counter()
        self._pass_seq += 1
        self._pass_events = []
        # Open the profiler's pass window on the SAME t0 the
        # reconcile_seconds / duration_s measurements use, so the
        # conservation identity talks about the same wall time.
        self.profiler.begin_pass(t0)
        if not self._sampler_started:
            self._sampler_started = True
            if self.profiler.sampler is not None:
                self.profiler.sampler.start(threading.get_ident())

        # Drain the actuation executor, then poll the actuator, THEN
        # observe.  Drain first: completed dispatches (create POSTs,
        # batched polls) mutate actuator state here, on the reconcile
        # thread — executor workers never touch it (docs/ACTUATION.md).
        # Poll before observe: a provision that just went ACTIVE must
        # have its nodes visible in this pass's observation, or the
        # planner would see neither the in-flight provision nor the new
        # supply and double-provision.
        with self.profiler.phase("actuate_poll"):
            if self.executor is not None:
                self.executor.drain()
            self.actuator.poll(now)
        t_obs = time.perf_counter()
        with self.profiler.phase("observe"):
            nodes, pods, pending = self._observe()
        observe_s = time.perf_counter() - t_obs
        self.metrics.observe("observe_seconds", observe_s)
        # Replayed into each served gang's trace at dispatch time: a
        # pass observes once for every gang it serves.
        self._pass_obs = (now, observe_s)
        self._update_supply_guard(nodes, now)

        gangs = group_into_gangs(pending)
        # Policy pass BEFORE latency tracking: a prediction consumed
        # this pass records its prewarm span into the gang's still-open
        # scale-up trace (the root ends in _track_gang_latency below).
        with self.profiler.phase("policy"):
            policy_advisory = self._policy_pass(gangs, nodes, pods, now)
        # Serving signals fold AFTER policy (both are advisory; order
        # only affects log readability) — live replica-target demand
        # rides the same hook below.
        with self.profiler.phase("serving"):
            serving_advisory = self._serving_pass(now)
        self._track_gang_latency(gangs, pods, nodes, now)
        # Settling only delays SIZING (the _scale path); _maintain still
        # sees every pending gang so reclaim deferral protects supply a
        # settling gang will bind to.
        settled_gangs = self._settled(gangs, now)

        # ICI-atomic slice repair (ISSUE 7): advisory replacement demand
        # for units under repair, plus pending gangs whose siblings are
        # still bound to a broken/draining slice — those are sized only
        # as part of the whole-gang repair, NEVER solo (a recreated
        # member planned alone would backfill a lone host's worth of
        # capacity into a job that needs one ICI domain).
        advisory, repair_deferred = self._repair_advisory(
            nodes, pods, gangs, now)
        # Policy prewarm demand and serving replica-target demand ride
        # the SAME advisory hook as repair replacements — admitted by
        # the pure planner AFTER organic demand and repairs (a
        # misprediction can never displace real work under clamp
        # contention).
        advisory = advisory + policy_advisory + serving_advisory
        self.metrics.set_gauge("gangs_deferred_to_repair",
                               len(repair_deferred))
        if repair_deferred:
            settled_gangs = [g for g in settled_gangs
                             if g.key not in repair_deferred]
            for key in repair_deferred:
                # Force a re-plan when the gang stops being deferred —
                # a stale matching digest must not skip it.
                self._gang_plan_digests.pop(key, None)
                self._explain(key, "planning deferred to slice repair",
                              "gang members still bound to a broken or "
                              "draining slice; sized whole, never solo")

        # Cancel idle-reclaim drains that pending demand claims BEFORE
        # planning, so the planner sees the uncordoned slice as supply
        # instead of provisioning a redundant replacement.
        if self._drain_cancellable and gangs:
            units = self._units(nodes)
            cancellable = {uid: uns for uid, uns in units.items()
                           if uid in self._drain_cancellable}
            claimed = self._claimed_by_pending(cancellable, gangs, pods)
            for unit_id in claimed:
                self._cancel_drain(unit_id, cancellable[unit_id])
            if claimed:
                # Mid-pass refresh after the uncordon patches — must
                # bypass the informer cache (the watch hasn't delivered
                # our own writes yet).
                nodes = self._fresh_nodes()

        # Delta-driven planning: decide WHICH gangs this pass feeds the
        # planner (all of them in full mode; only input-changed ones in
        # delta mode — docs/INFORMER.md resync contract).
        plan_gangs, plan_mode = self._plan_scope(settled_gangs, gangs,
                                                 nodes, now)
        if not self.config.no_scale:
            with self.profiler.phase("plan"):
                self._scale(plan_gangs, nodes, pods, now,
                            all_gangs=settled_gangs, plan_mode=plan_mode,
                            advisory=advisory)
        if not self.config.no_maintenance:
            # Advisory repair gangs count as pending demand for the
            # reclaim-deferral check: an idle slice the repair will
            # hand the gang to must not be reclaimed meanwhile.
            with self.profiler.phase("maintain"):
                self._maintain(
                    nodes, pods, now,
                    pending_gangs=gangs + [g for g, _ in advisory])

        # Bound long-run memory: drop bookkeeping for demands/provisions
        # that no longer exist (actuators prune terminal statuses; gangs
        # whose pods are gone re-report if re-created, which is desired).
        live_status_ids = {s.id for s in self.actuator.statuses()}
        self._seen_failures &= live_status_ids
        self._submitted_at = {k: v for k, v in self._submitted_at.items()
                              if k in live_status_ids}
        self._provision_roots = {
            k: v for k, v in self._provision_roots.items()
            if k in live_status_ids}
        live_gang_keys = {p.gang_key for p in pods}
        self._reported_unsatisfiable &= live_gang_keys
        for key in [k for k, t in self._retry_at.items()
                    if t < now - 3600.0]:
            del self._retry_at[key]
        # Failure streaks (generation fallback) are pruned against LIVE
        # demand — every pod's gang key plus jobset group keys — not the
        # settle-filtered gang list, so a gang that resizes mid-stockout
        # keeps the fallback it earned.  Shape-keyed entries (gang-less
        # spares) persist until their provision lands.
        live_demand_keys = set(live_gang_keys)
        for key in live_gang_keys:
            if key and key[0] == "jobset":
                live_demand_keys.add(("jobset", key[1],
                                      key[2].split("/", 1)[0]))
        for p in pods:
            if p.jobset_name:
                live_demand_keys.add(("jobset", p.namespace,
                                      p.jobset_name))
        for key in [k for k in self._failure_streak
                    if not (isinstance(k, tuple) and k
                            and k[0] == "shape")
                    and k not in live_demand_keys]:
            del self._failure_streak[key]
            self._fallback_noted.pop(key, None)

        self.metrics.observe("reconcile_seconds", time.perf_counter() - t0)
        self.metrics.set_gauge("pending_gangs", len(gangs))
        self.metrics.set_gauge("nodes", len(nodes))
        # Cost proxy: fleet chip count and its time integral.
        from tpu_autoscaler.topology.catalog import TPU_RESOURCE

        fleet_chips = sum(int(n.allocatable.get(TPU_RESOURCE))
                          for n in nodes if n.is_tpu)
        self.metrics.set_gauge("fleet_chips", fleet_chips)
        if self._last_pass_at is not None and now > self._last_pass_at:
            self.metrics.inc("chip_seconds_provisioned",
                             fleet_chips * (now - self._last_pass_at))
        self._last_pass_at = now
        # Per-namespace chip usage (quota observability): zero out
        # namespaces that disappeared so gauges don't go stale.
        ns_usage: dict[str, int] = {}
        for p in pods:
            if p.node_name and p.phase in {"Pending", "Running"} \
                    and p.tpu_chips:
                ns_usage[p.namespace] = (ns_usage.get(p.namespace, 0)
                                         + p.tpu_chips)
        # sorted(): gauge creation order feeds snapshot()/TSDB series
        # order, which bundle digests serialize (TAD904).
        for ns in sorted(self._seen_namespaces - set(ns_usage)):
            self.metrics.set_gauge(f"namespace_chips_used_{ns}", 0)
        for ns, used in ns_usage.items():
            self.metrics.set_gauge(f"namespace_chips_used_{ns}", used)
        self._seen_namespaces |= set(ns_usage)
        # Cost ledger close (ISSUE 11): seal this pass's attribution
        # against the INDEPENDENT fleet sum above, export cost_*/
        # frag_* metrics, score fragmentation — before _obs_pass so
        # the series land in the TSDB the same pass they describe.
        # The _maintain loop fed the unit observations; with
        # maintenance off nothing classified, so the close (and its
        # conservation check) is suspended rather than false-alarmed.
        with self.profiler.phase("cost_close"):
            cost_info = self._cost_pass(now, fleet_chips)
        # Decision record: this pass's inputs digest + per-unit reasons
        # ("why did/didn't we provision"), for `explain` / /debugz.
        # The digest is an O(n) frozenset hash — cheap enough for the
        # controller-overhead budget, strong enough to show whether two
        # passes saw the same world.  It folds in node identity AND
        # readiness/cordon state plus the in-flight and supply-guard
        # ledgers: now that digests are load-bearing for delta-driven
        # planning, "unchanged" must never span a node drain, a
        # provision state change, or a guard release/expiry.
        # World half of the digest: the informer's O(1) incremental
        # store digests when this pass observed straight off synced
        # caches (every pod/node change bumps an rv, so (key, rv)
        # XORs are strictly MORE change-sensitive than the legacy
        # field tuples) — the O(pods) frozenset walk was a measurable
        # slice of the million-pod pass (ISSUE 13).  Captured by
        # _observe BESIDE the snapshots (live watch threads advance
        # the caches mid-pass; a record-time read could describe the
        # NEXT pass's world).  Any bypass/LIST observation keeps the
        # legacy hash: the cache digest would not describe what the
        # pass actually saw.
        if self._observed_digest is not None:
            world_digest = self._observed_digest
        else:
            world_digest = (
                hash(frozenset((p.uid, p.phase, p.node_name or "")
                               for p in pods))
                ^ hash(frozenset(
                    (n.name, n.resource_version or "", n.is_ready,
                     n.unschedulable) for n in nodes)))
        digest = (world_digest
                  ^ hash(frozenset((s.id, s.state)
                                   for s in self.actuator.statuses()))
                  ^ hash(frozenset(
                      (pid, unit_ids) for pid, (_inf, unit_ids, _since)
                      in self._supply_awaiting_nodes.items()))
                  # Policy outputs fold in (ISSUE 8): advisory prewarm
                  # demand, holds and idle overrides are pass inputs
                  # like any other — "unchanged" must never span a
                  # policy decision.
                  ^ hash(("policy", self._policy_digest)))
        # Retention + self-alerting AFTER this pass's metrics landed
        # (reconcile_seconds above is part of the ingested snapshot)
        # and BEFORE the decision record, so alert transitions show up
        # in the very pass record that caused them.
        with self.profiler.phase("obs_pass"):
            alerts_info = self._obs_pass(now)
        # Close the profiler window LAST so every phase above is
        # inside it; its per-phase observations therefore reach the
        # TSDB on the NEXT pass's ingest — one pass late, like the
        # span exemplars.  The dominant phase's exemplar names this
        # pass record, linking a phase spike to the decision record
        # that produced it.
        profile_info = self.profiler.end_pass()
        if profile_info:
            dominant = profile_info["dominant"]
            self._span_exemplars[f"pass_phase_seconds_{dominant}"] = (
                f"pass-{self._pass_seq}",
                profile_info["phases"].get(dominant, 0.0))
        record = {
            "pass": self._pass_seq,
            "t": now,
            "inputs": {"nodes": len(nodes), "pods": len(pods),
                       "pending_gangs": len(gangs),
                       "in_flight": sum(
                           1 for s in self.actuator.statuses()
                           if s.in_flight),
                       "digest": f"{digest & 0xffffffffffffffff:016x}"},
            "planning": dict(self._pass_plan_info),
            "duration_s": time.perf_counter() - t0,
            "events": self._pass_events,
        }
        if alerts_info:
            record["alerts"] = alerts_info
        if cost_info:
            # Per-pass cost attribution in the decision record: "where
            # did this pass's chips sit" rides the same explain/replay
            # surfaces as every other decision (docs/COST.md).
            record["cost"] = cost_info
        if profile_info:
            # Where this pass's milliseconds went (ISSUE 20) — the
            # span list stays in the profiler's own ring; the record
            # carries the ledger the conservation oracle re-derives.
            record["profile"] = {
                "window_s": profile_info["window_s"],
                "phases": profile_info["phases"],
                "conserved": profile_info["conserved"],
                "dominant": profile_info["dominant"],
            }
        self.recorder.record_pass(record)

    def _observe(self) -> tuple[list[Node], list[Pod], list[Pod]]:
        """One pass's world view: ``(nodes, pods, pending)`` — informer
        snapshots when attached (watch-fed cache, LIST fallback while
        unsynced), else the relist-every-pass baseline.  The pending
        (Unschedulable) working set rides the informer's secondary
        index when available — consistent with the pod snapshot (one
        lock hold) and O(pending) instead of an O(cluster) scan.

        Staleness guard: when a provision transitioned to ACTIVE since
        its submission was recorded, the node side bypasses the cache —
        the planner must see the new supply in the SAME pass the
        provision stops being in-flight, and the node watch may not
        have delivered it yet (the one ordering the crash-only loop
        cannot recompute its way out of: it would double-provision).
        The bypass is STICKY, not one-pass: the ACTIVE status (and its
        ``_submitted_at`` entry) is gone by the next pass, but the
        watch's delivery lag is independent of pass boundaries — a
        wake-triggered pass milliseconds later would otherwise see
        neither the in-flight provision nor the new supply.  So the
        bypass persists until the node cache contains every node a
        direct LIST sees (nodes the cache has EXTRA are fine: deletion
        lag only defers reclaim by a pass).
        """
        self._observed_digest = None
        self._observed_cache_digests = None
        self._columnar_memo = None
        if self.informer is None:
            pods = [Pod(p) for p in self.client.list_pods()]
            return ([Node(p) for p in self.client.list_nodes()], pods,
                    [p for p in pods if p.is_unschedulable])
        just_active = any(
            s.state == ACTIVE and s.id in self._submitted_at
            for s in self.actuator.statuses())
        if just_active or self._nodes_awaiting_cache:
            nodes = self._fresh_nodes()
            snap = self.informer.node_cache.snapshot()
            if snap is None:
                # Cache unsynced: node reads fall back to a direct LIST
                # anyway, so there is no staleness to guard against.
                self._nodes_awaiting_cache = set()
            else:
                self._nodes_awaiting_cache = (
                    {n.name for n in nodes} - {n.name for n in snap})
        elif hasattr(self.informer, "observe_with_digests"):
            # The one-lock-hold-per-cache read: snapshots AND the
            # store digests describing exactly them (watch threads
            # keep the caches moving mid-pass, so a digest read any
            # later could stamp this pass's record with the NEXT
            # pass's world; review-found).  None = a cache unsynced —
            # fall through to the LIST-fallback reads below and the
            # legacy per-list digest.  The raw (node, pod) digest pair
            # additionally gates attaching the columnar view's state
            # to this pass (docs/PLANNER.md).
            obs = self.informer.observe_with_digests()
            if obs is not None:
                nodes, pods, pending, digest, node_d, pod_d = obs
                self._observed_digest = digest
                self._observed_cache_digests = (node_d, pod_d)
                return nodes, pods, pending
            nodes = self.informer.nodes()
        elif hasattr(self.informer, "observe_with_digest"):
            obs = self.informer.observe_with_digest()
            if obs is not None:
                nodes, pods, pending, digest = obs
                self._observed_digest = digest
                return nodes, pods, pending
            nodes = self.informer.nodes()
        else:
            nodes = self.informer.nodes()
        pods, pending = self.informer.pods_and_pending()
        return nodes, pods, pending

    def _update_supply_guard(self, nodes: list[Node], now: float) -> None:
        """Close the ACTIVE→node-registration double-provision window.

        A provision stops counting as in-flight the moment it reports
        ACTIVE, but its nodes register with the apiserver asynchronously
        — in that window the planner sees neither the in-flight work nor
        the new supply and would submit a duplicate (the pre-existing
        gap the schedule harness reproduces on the pre-fix serial path).
        Mirror of the informer's sticky ``_nodes_awaiting_cache`` guard,
        one layer down: keep a planner-visible ``InFlight`` for every
        just-ACTIVE provision until each of its supply units appears
        among the observed nodes.  Bounded: an entry whose nodes never
        register expires after ``provision_timeout_seconds`` so a lost
        slice cannot shield its demand from re-provisioning forever.
        """
        seen_units = set(self._units(nodes))
        for status in self.actuator.statuses():
            if (status.state == ACTIVE and status.unit_ids
                    and status.id in self._submitted_at
                    and status.id not in self._supply_awaiting_nodes
                    and any(u not in seen_units for u in status.unit_ids)):
                self._supply_awaiting_nodes[status.id] = (
                    InFlight(kind=status.request.kind,
                             shape_name=status.request.shape_name,
                             gang_key=status.request.gang_key,
                             count=status.request.count),
                    tuple(status.unit_ids), now)
                self.metrics.inc("supply_guard_engaged")
                self._explain(status.id, "supply-guard engaged",
                              "ACTIVE but units not yet registered as "
                              "nodes", units=",".join(status.unit_ids))
                # The node_registration span is NOT started here: the
                # guard engages before this pass records the provision
                # span (_note_failures), and seq order is the render's
                # causal order — the span opens there, after it.
        for pid, (_inf, unit_ids, since) in list(
                self._supply_awaiting_nodes.items()):
            if all(u in seen_units for u in unit_ids):
                del self._supply_awaiting_nodes[pid]
                for span in self._registration_spans.pop(pid, ()):
                    self.tracer.end(span, t=now)
                self._explain(pid, "supply-guard released",
                              "all units registered as nodes")
            elif now - since > self.config.provision_timeout_seconds:
                if self._repair_depends_on(_inf.gang_key):
                    # An in-flight slice repair rides this provision:
                    # expiring the entry would show the planner neither
                    # in-flight work nor supply for the gang mid-repair
                    # — phantom free capacity, then a double provision.
                    # Hold the guard (refresh the clock) until the
                    # repair completes or is abandoned; repairs are
                    # themselves bounded (slice_repair_timeout), so the
                    # hold cannot live forever.
                    self._supply_awaiting_nodes[pid] = (_inf, unit_ids,
                                                        now)
                    self.metrics.inc("supply_guard_repair_holds")
                    self._explain(pid, "supply-guard held",
                                  "registration overdue but a slice "
                                  "repair depends on this provision "
                                  "staying planner-visible")
                    continue
                del self._supply_awaiting_nodes[pid]
                self.metrics.inc("supply_guard_expired")
                for span in self._registration_spans.pop(pid, ()):
                    self.tracer.end(span, t=now,
                                    attrs={"expired": True})
                self._explain(pid, "supply-guard expired",
                              "units never registered within "
                              "provision_timeout")

    def _in_flight(self) -> list[InFlight]:
        """The planner's view of outstanding work: the actuator's
        in-flight provisions plus ACTIVE ones still awaiting node
        registration (the sticky supply guard)."""
        return (in_flight_of(self.actuator)
                + [inf for inf, _, _ in
                   self._supply_awaiting_nodes.values()])

    # ---- predictive policy (ISSUE 8) -----------------------------------

    def _policy_pass(self, gangs: list[Gang], nodes: list[Node],
                     pods: list[Pod], now: float
                     ) -> list[tuple[Gang, str]]:
        """Consult the PolicyEngine for this pass's advice.

        Strictly advisory and crash-only: any policy failure zeroes
        the advice and the loop continues as the reactive baseline —
        a forecasting bug must never take down scaling.  Returns the
        prewarm advisory gangs for the planner; holds and idle
        overrides land on ``self`` for ``_maintain``.
        """
        self._policy_holds = set()
        self._policy_idle_overrides = {}
        self._policy_digest = 0
        if self.policy_engine is None:
            return []
        try:
            self.policy_engine.observe(
                gangs, nodes, pods, self.actuator.statuses(), now,
                gang_traces=self._gang_traces)
            advice = self.policy_engine.advise(
                nodes, pods, now,
                base_idle_threshold=self.config.idle_threshold_seconds)
        except Exception:  # noqa: BLE001 — advisory only
            self.metrics.inc("policy_errors")
            log.exception("policy engine pass failed; continuing with "
                          "the reactive baseline")
            return []
        self._policy_holds = advice.hold_units
        self._policy_idle_overrides = advice.idle_overrides
        self._policy_digest = advice.digest
        for d in advice.decisions:
            self._explain(d.key, "prewarm decided", d.reason,
                          shape=d.shape_name)
            self._notify(
                f"prewarm: provisioning {d.shape_name} ahead of "
                f"forecast demand ({d.key})")
        if len(advice.rejections) <= 8:
            for r in advice.rejections:
                self._explain("policy", "prewarm rejected", r)
        elif advice.rejections:
            self._explain("policy", "prewarm rejected",
                          f"{len(advice.rejections)} forecasts below "
                          f"the firing bar")
        return advice.advisory

    # ---- serving-aware scaling (ISSUE 9) -------------------------------

    def _serving_pass(self, now: float) -> list[tuple[Gang, str]]:
        """Consult the ServingScaler for this pass's replica-target
        advice.  Strictly advisory and crash-only, exactly like
        ``_policy_pass``: a signal-path failure zeroes the advice and
        scaling degrades to the reactive (pod-pending) baseline."""
        self.serving_advice = None
        if self.serving_scaler is None:
            return []
        try:
            advice = self.serving_scaler.advise(
                self.actuator.statuses(), now)
        except Exception:  # noqa: BLE001 — advisory only
            self.metrics.inc("serving_errors")
            log.exception("serving scaler pass failed; continuing "
                          "with reactive scaling")
            return []
        self.serving_advice = advice
        for pool, n in advice.scale_in.items():
            self._explain(("serving", pool), "serving scale-in advised",
                          f"{n} surplus replica(s); platform drains "
                          f"via the serve.py drain contract")
        return advice.advisory

    # ---- ICI-atomic slice repair (ISSUE 7) -----------------------------

    def _repair_depends_on(self, gang_key) -> bool:
        """Whether an active repair rides the given gang key's
        provision (the supply-guard hold predicate)."""
        return (gang_key is not None
                and any(gang_key in st["gang_keys"]
                        for st in self._slice_repairs.values()))

    def _repair_advisory(self, nodes: list[Node], pods: list[Pod],
                         gangs: list[Gang], now: float
                         ) -> tuple[list[tuple[Gang, str]], set[tuple]]:
        """Advisory replacement demand for active repairs, and the
        pending gang keys to withhold from solo planning.

        Broken units: under repair, carrying a cordoned/NotReady host,
        or missing a host outright (fewer nodes than the shape's count
        after the readiness barrier once cleared).  Any pending gang
        with members still bound to one is deferred — sizing the
        pending fraction alone is exactly the lone-host backfill the
        ICI contract forbids; crash-only on purpose: derived from
        observed node state, not repair memory, so a restarted
        controller still never backfills mid-drain.

        Advisory demand is built only for units in ``_slice_repairs``:
        the full gang (members of every phase) paired with the broken
        unit's OWN shape — a like-for-like replacement the planner
        admits with its normal algebra (plan.deferred when clamped).
        """
        from tpu_autoscaler.topology.catalog import shape_from_selectors

        units = self._units(nodes)
        unready: set[str] | None = None
        if self.informer is not None \
                and hasattr(self.informer, "unready_nodes"):
            sel = self.informer.unready_nodes()
            if sel is not None:
                # O(failures) read off the readiness index — the
                # node-failure delta surface (docs/INFORMER.md).
                unready = {n.name for n in sel}
        broken: dict[str, list[Node]] = {}
        for unit_id, unit_nodes in units.items():
            if not unit_nodes[0].is_tpu:
                continue
            if unit_id in self._slice_repairs:
                broken[unit_id] = unit_nodes
                continue
            if unready is not None:
                damaged = any(n.name in unready for n in unit_nodes)
            else:
                damaged = any(n.unschedulable or not n.is_ready
                              for n in unit_nodes)
            if not damaged:
                # Fewer hosts than the shape says: a host deleted from
                # a live slice, OR a partial slice still materializing
                # (or never completing — a failed staggered provision).
                # Both defer solo planning of any gang with members
                # aboard: the remainder must never be sized against an
                # incomplete ICI domain.
                try:
                    shape = shape_from_selectors(unit_nodes[0].labels)
                except KeyError:
                    shape = None
                damaged = (shape is not None
                           and len(unit_nodes) < shape.hosts)
            if damaged:
                broken[unit_id] = unit_nodes
        if not broken:
            return [], set()

        broken_nodes = [n for uns in broken.values() for n in uns]
        by_node = self._pods_by_node(broken_nodes, pods)
        broken_keys: set[tuple] = set()
        for unit_nodes in broken.values():
            for n in unit_nodes:
                for p in by_node.get(n.name, ()):
                    if p.is_workload and p.gang_key is not None:
                        broken_keys.add(p.gang_key)
        deferred = {g.key for g in gangs if g.key in broken_keys}

        advisory: list[tuple[Gang, str]] = []
        emitted: set[tuple] = set()
        for unit_id, st in self._slice_repairs.items():
            unit_names = {n.name for n in broken.get(unit_id, ())}
            for key in st["gang_keys"]:
                if key in emitted:
                    continue
                members = self._gang_members(pods, key)
                if not members:
                    continue  # eviction gap; the in-flight entry covers it
                if any(p.node_name and p.node_name not in unit_names
                       and p.phase == "Running" for p in members):
                    # A member already runs OFF the broken unit: the
                    # replacement landed (or is landing) and the rest
                    # of the gang binds beside it — more advisory
                    # demand would double-provision the repair.
                    continue
                emitted.add(key)
                advisory.append((Gang(key=key, pods=members),
                                 st["shape_name"]))
        return advisory, deferred

    def _maybe_start_repair(self, unit_id: str, unit_nodes: list[Node],
                            unit_pods: list[Pod], now: float) -> None:
        """Open an ICI-atomic repair for a broken, workload-bearing TPU
        slice: whole-slice cordon + checkpoint drain now, advisory
        like-for-like replacement demand from the next pass on."""
        from tpu_autoscaler.topology.catalog import shape_from_selectors

        if unit_id in self._slice_repairs \
                or unit_id in self._drain_started:
            return
        try:
            shape = shape_from_selectors(unit_nodes[0].labels)
        except KeyError:
            shape = None
        if shape is None:
            # Unknown shape: no like-for-like replacement to name —
            # fall back to the plain unhealthy-replace path.
            self._handle_unhealthy_legacy(unit_id, unit_nodes, unit_pods,
                                          now)
            return
        missing = len(unit_nodes) < shape.hosts
        since = self._unhealthy_since.setdefault(unit_id, now)
        if not missing \
                and now - since < self.config.slice_repair_after_seconds:
            return  # NotReady flap window still open
        gang_keys = tuple(sorted({p.gang_key for p in unit_pods
                                  if p.is_workload
                                  and p.gang_key is not None}))
        why = (("slice short of hosts (deleted from a live slice, or a "
                "partial slice that never completed)") if missing
               else "NotReady host in live slice")
        span = self.tracer.start(
            "slice_repair", trace_id=self.tracer.new_trace("repair"),
            t=now, attrs={"unit": unit_id, "reason": why,
                          "shape": shape.name,
                          "gangs": [("/".join(str(p) for p in k))
                                    for k in gang_keys]})
        drain_span = self.tracer.start("repair_drain", parent=span, t=now,
                                       attrs={"unit": unit_id})
        self._slice_repairs[unit_id] = {
            "gang_keys": gang_keys, "shape_name": shape.name,
            "started": now, "span": span, "drain_span": drain_span,
            "provision_id": None,
        }
        for key in gang_keys:
            self._repair_roots[key] = span
        self.metrics.inc("slice_repairs_started")
        log.warning("slice repair: %s (%s) — cordon + drain, replacing "
                    "the whole slice", unit_id, why)
        self._explain(unit_id, "slice repair started", why,
                      shape=shape.name)
        self._notify(f"repairing {unit_id}: {why}; replacing the whole "
                     f"slice ({shape.name})")
        self._begin_drain(unit_id, unit_nodes, unit_pods, now,
                          reason=f"slice repair: {why}")

    def _end_repair(self, unit_id: str, st: dict, now: float, *,
                    outcome: str, attrs: dict | None = None,
                    metric: str | None = None) -> None:
        self.tracer.end(st.pop("drain_span", None), t=now)
        # Stamp the repair's bill on the closing trace (ISSUE 11):
        # chip-seconds the broken unit burned in the repair state plus
        # the served gangs' attribution — cost-to-repair, next to
        # latency, on the same span operators already read.
        attrs = dict(attrs or {})
        repair_cs = self.cost.accrued_chip_seconds([unit_id], now,
                                                   state="repair")
        if repair_cs:
            attrs["cost_repair_chip_seconds"] = round(repair_cs, 3)
        gang_cs = 0.0
        for key in st["gang_keys"]:
            gattrs = self.cost.gang_attrs(key, now)
            if gattrs:
                gang_cs += gattrs["cost_chip_seconds"]
        if gang_cs:
            attrs["cost_chip_seconds"] = round(gang_cs, 3)
        self.tracer.end(st["span"], t=now, attrs=attrs or None,
                        metric=metric,
                        value=(now - st["started"]) if metric else None)
        for key in st["gang_keys"]:
            if self._repair_roots.get(key) is st["span"]:
                del self._repair_roots[key]
        del self._slice_repairs[unit_id]
        self._unhealthy_since.pop(unit_id, None)
        self._explain(unit_id, f"slice repair {outcome}")

    def _sweep_repairs(self, units: dict[str, list[Node]],
                       pods: list[Pod], now: float) -> None:
        """Advance repair bookkeeping: close repairs whose gang runs
        again on healthy supply, bound every repair by the timeout."""
        for unit_id, st in list(self._slice_repairs.items()):
            repack = st.get("kind") == "repack"
            if now - st["started"] \
                    > self.config.slice_repair_timeout_seconds:
                if repack:
                    # Same cleanup as a budget abort (cancel the
                    # replacement, uncordon an un-landed source) —
                    # a timed-out migration must not leak either.
                    self._abort_repack(unit_id, st, units.get(unit_id),
                                       now, "migration timed out",
                                       outcome="abandoned")
                    continue
                self.metrics.inc("slice_repairs_abandoned")
                log.warning("slice repair for %s abandoned after %.0fs",
                            unit_id, now - st["started"])
                self._end_repair(unit_id, st, now, outcome="abandoned",
                                 attrs={"error": "repair timed out"})
                continue
            if unit_id in units:
                continue  # broken unit still draining/deleting
            if st.get("drain_span") is not None:
                self.tracer.end(st.pop("drain_span"), t=now)
            members = [p for key in st["gang_keys"]
                       for p in self._gang_members(pods, key)]
            if not members:
                # Broken unit gone AND the gang has zero pods.  The
                # normal eviction gap (drain deleted members, the Job
                # controller recreates them next pass) closes within
                # seconds — a gang still absent after the grace means
                # the job itself was deleted or completed mid-repair,
                # and nothing will ever consume the replacement: close
                # the repair instead of holding its bookkeeping (and
                # its supply-guard riders) until the 3600 s timeout.
                gone_since = st.setdefault("members_gone_since", now)
                if now - gone_since > self.config.drain_grace_seconds \
                        + 30.0:
                    if repack:
                        # The gang is gone: cancel the replacement
                        # (nothing will consume it); the workload-free
                        # source is NOT uncordoned — its drain
                        # finishing IS the reclaim (units is the
                        # observed set and this unit already left it).
                        self._abort_repack(
                            unit_id, st, None, now,
                            "gang disappeared mid-migration",
                            outcome="abandoned")
                        continue
                    self.metrics.inc("slice_repairs_abandoned")
                    log.warning("slice repair for %s closed: gang "
                                "disappeared mid-repair (job deleted "
                                "or completed)", unit_id)
                    self._end_repair(
                        unit_id, st, now, outcome="abandoned",
                        attrs={"error": "gang disappeared mid-repair"})
                continue
            st.pop("members_gone_since", None)
            if members and all(p.phase == "Running" for p in members):
                latency = now - st["started"]
                if repack:
                    self._complete_repack(unit_id, st, members, units,
                                          now, latency)
                    continue
                self.metrics.inc("slice_repairs_completed")
                log.info("slice repair for %s complete in %.1fs",
                         unit_id, latency)
                self._notify(f"slice repair complete: {unit_id} replaced "
                             f"in {latency:.0f}s")
                self._end_repair(unit_id, st, now, outcome="completed",
                                 attrs={"latency_s": round(latency, 3)},
                                 metric="slice_repair_seconds")

    def _note_repair_provision(self, req, status, now: float) -> None:
        """Link a just-dispatched provision to the repair it serves."""
        if req.gang_key is None:
            return
        for st in self._slice_repairs.values():
            if req.gang_key in st["gang_keys"]:
                st["provision_id"] = status.id
                self.tracer.event(st["span"], "replacement_submitted",
                                  {"provision_id": status.id,
                                   "shape": req.shape_name}, t=now)

    # ---- cost-aware continuous repacking (ISSUE 12) --------------------

    def _repack_pass(self, units: dict[str, list[Node]],
                     pods: list[Pod],
                     pods_by_node: dict[str, list[Pod]],
                     spare_ids: set[str], now: float) -> None:
        """One repack pass: budget-guard every in-flight migration,
        then ask the Repacker for new ones (docs/REPACK.md).

        Migrations ride ``_slice_repairs`` with ``kind="repack"`` —
        the repair pipeline's cordon + checkpoint drain, advisory
        like-for-like (or right-sized) replacement, solo-planning
        deferral and supply-guard holds all generalize for free; only
        the economics and the trace story are repack's own.
        """
        pq = self.cost.placement_quality()
        idle_spot = pq["idle_spot_chips"]
        self.repacker.settle(now)
        # Candidates are re-counted per pass; zero NOW so the early
        # returns below (max concurrency, no eligible rows) never
        # leave a previous pass's count frozen on the gauge.
        self.metrics.set_gauge("repack_candidates", 0)
        self._guard_repacks(units, pods, idle_spot, now)

        active = sum(1 for st in self._slice_repairs.values()
                     if st.get("kind") == "repack")
        self.metrics.set_gauge("repack_active_migrations", active)
        if active >= self.repacker.config.max_concurrent_migrations:
            return
        burning: set[str] = set()
        if self.serving_scaler is not None:
            adapter = getattr(self.serving_scaler, "adapter", None)
            if adapter is not None and hasattr(adapter,
                                               "burning_pools"):
                burning = adapter.burning_pools(
                    self.repacker.config.slo_attainment_floor)

        from tpu_autoscaler.repack import UnitRow

        # Mechanical exclusions first (the Repacker handles the
        # economics): units already draining/held/spare, units whose
        # workload cannot honor the checkpoint contract, gangs not
        # fully settled or not wholly aboard one unit, multislice
        # members (a jobset migrates as a cohort — out of scope), and
        # gangs inside their post-migration cooldown.
        excluded = (set(self._slice_repairs) | set(self._drain_started)
                    | self._requested_drains | self._policy_holds
                    | spare_ids)
        rows: list[UnitRow] = []
        rightsize: dict[str, tuple[str, int]] = {}
        unit_pods_of: dict[str, list[Pod]] = {}
        for r in pq["rows"]:
            uid = r["unit_id"]
            if uid in excluded or uid not in units:
                continue
            unit_nodes = units[uid]
            if not unit_nodes[0].is_tpu:
                continue
            if any(n.unschedulable or not n.is_ready
                   for n in unit_nodes):
                continue  # a damaged unit is the repair path's business
            unit_pods = [p for n in unit_nodes
                         for p in pods_by_node.get(n.name, [])]
            workload = [p for p in unit_pods if p.is_workload]
            if not workload or any(p.phase != "Running"
                                   or not p.is_drainable
                                   or p.jobset_name
                                   or p.gang_key is None
                                   for p in workload):
                continue
            keys = {p.gang_key for p in workload}
            if self.repacker.gang_cooled(keys, now):
                continue
            if burning and any(
                    isinstance(k[-1], str)
                    and any(k[-1].startswith(f"serve-{bp}-")
                            for bp in burning)
                    for k in keys):
                # Serving replicas carry their pool in the gang NAME
                # (the scaler's serve-<pool>-<n> convention) — the
                # adapter's pool names are LOGICAL and need not match
                # node-pool labels, so the row.pool check in
                # plan_candidates alone would never fire for them.
                # Conservative on purpose: a false name match merely
                # skips a candidate.
                continue
            names = {n.name for n in unit_nodes}
            if any(p.node_name not in names
                   for key in keys
                   for p in self._gang_members(pods, key)
                   if p.node_name):
                continue  # gang spans units: never migrate a fraction
            unit_pods_of[uid] = unit_pods
            rows.append(UnitRow(**r))
            if r["used_chips"] < r["chips"] and len(keys) == 1:
                gang = Gang(key=next(iter(keys)), pods=list(workload))
                target = self._rightsize_target(gang, r["accel"],
                                                r["chips"])
                if target is not None:
                    rightsize[uid] = target
        if not rows:
            return
        plans = self.repacker.advise(
            rows, idle_spot, now, active_migrations=active,
            burning_pools=burning, rightsize_targets=rightsize)
        for plan in plans:
            self._start_repack(plan, units[plan.unit_id],
                               unit_pods_of[plan.unit_id], now)

    def _rightsize_target(self, gang: Gang, accel: str,
                          unit_chips: int) -> tuple[str, int] | None:
        """Smallest catalog shape that actually fits the gang AND can
        admit its pods: same accelerator type as the unit it runs on
        (the fitter's accelerator-pin resolution is generation-wide,
        which would happily name a shape the pods' selector can never
        bind to — a migration onto it would strand the gang).  A
        topology-pinned gang is never right-sized: the pin demands
        this exact torus."""
        from tpu_autoscaler.engine.fitter import shape_feasible_for_gang
        from tpu_autoscaler.topology.catalog import (
            SLICE_SHAPES,
            TOPOLOGY_LABEL,
        )

        if TOPOLOGY_LABEL in gang.node_selectors:
            return None
        chips = gang.tpu_chips
        if chips <= 0:
            return None
        for shape in sorted(SLICE_SHAPES.values(),
                            key=lambda s: s.chips):
            if shape.accelerator_type != accel \
                    or shape.chips < chips \
                    or shape.chips >= unit_chips:
                continue
            if shape_feasible_for_gang(shape, gang) is None:
                return (shape.name, shape.chips)
        return None

    def _guard_repacks(self, units: dict[str, list[Node]],
                       pods: list[Pod], idle_spot: dict[str, int],
                       now: float) -> None:
        """Refresh every in-flight migration's realized cost off the
        ledger and re-run the budget verdict: the migration aborts the
        moment projected cost exceeds projected savings — unless the
        gang already landed on the destination (past the point of no
        return, the cheapest way out is through)."""
        for unit_id, st in list(self._slice_repairs.items()):
            if st.get("kind") != "repack":
                continue
            plan = st["plan"]
            cs = self.cost.accrued_chip_seconds([unit_id], now,
                                                state="repair")
            if cs is not None:
                st["realized_cost_cs"] = cs
            pid = st.get("provision_id")
            prov_pending = False
            if plan.kind == "rightsize":
                prov_pending = pid is None
                if pid is not None:
                    submitted = self._submitted_at.get(pid)
                    in_flight = any(s.id == pid and s.in_flight
                                    for s in self.actuator.statuses())
                    prov_pending = in_flight
                    if in_flight and submitted is not None:
                        # Replacement chips burning behind the barrier
                        # count against the migration, not for it.
                        st["dest_cost_cs"] = (plan.target_chips
                                              * (now - submitted))
            members = [p for key in st["gang_keys"]
                       for p in self._gang_members(pods, key)]
            landed = any(p.node_name
                         and p.node_name not in st["src_nodes"]
                         and p.phase in ("Pending", "Running")
                         for p in members)
            if landed:
                st["landed"] = True
            if st.get("landed"):
                continue
            dest_avail = (plan.kind == "rightsize"
                          or idle_spot.get(plan.shape, 0) >= plan.chips)
            verdict = self.repacker.guard(
                plan, now, started=st["started"],
                realized_cost_cs=(st.get("realized_cost_cs", 0.0)
                                  + st.get("dest_cost_cs", 0.0)),
                destination_available=dest_avail,
                provision_pending=prov_pending)
            if verdict is not None:
                self._abort_repack(unit_id, st, units.get(unit_id),
                                   now, verdict)

    def _start_repack(self, plan, unit_nodes: list[Node],
                      unit_pods: list[Pod], now: float) -> None:
        """Open one migration: ``repack`` trace root, drain the source
        whole (ICI-atomic, checkpoint-aware), advisory replacement
        demand from the next pass on — the repair lifecycle wearing
        cost clothes."""
        gang_keys = tuple(sorted({p.gang_key for p in unit_pods
                                  if p.is_workload
                                  and p.gang_key is not None}))
        span = self.tracer.start(
            "repack", trace_id=self.tracer.new_trace("repack"), t=now,
            attrs={"unit": plan.unit_id, "kind": plan.kind,
                   "reason": plan.reason, "shape": plan.shape,
                   "target_shape": plan.target_shape,
                   "projected_saving_chip_seconds":
                       round(plan.projected_saving_cs, 3),
                   "projected_cost_chip_seconds":
                       round(plan.projected_cost_cs, 3),
                   "gangs": [("/".join(str(p) for p in k))
                             for k in gang_keys]})
        drain_span = self.tracer.start("repack_drain", parent=span,
                                       t=now,
                                       attrs={"unit": plan.unit_id})
        self._slice_repairs[plan.unit_id] = {
            "kind": "repack", "gang_keys": gang_keys,
            "shape_name": plan.target_shape, "started": now,
            "span": span, "drain_span": drain_span,
            "provision_id": None, "plan": plan,
            "src_nodes": tuple(n.name for n in unit_nodes),
            "realized_cost_cs": 0.0,
        }
        for key in gang_keys:
            self._repair_roots[key] = span
        self.repacker.note_started(plan, gang_keys, now)
        log.info("repack (%s): migrating %s off %s — %s", plan.kind,
                 "/".join(str(p) for p in gang_keys[0])
                 if gang_keys else "?", plan.unit_id, plan.reason)
        self._explain(plan.unit_id, "repack migration started",
                      plan.reason, kind=plan.kind,
                      target=plan.target_shape)
        self._notify(f"repacking {plan.unit_id} ({plan.kind}): "
                     f"{plan.reason}")
        self._begin_drain(plan.unit_id, unit_nodes, unit_pods, now,
                          reason=f"repack ({plan.kind}): {plan.reason}")

    def _abort_repack(self, unit_id: str, st: dict,
                      unit_nodes: list[Node] | None, now: float,
                      reason: str, *, outcome: str = "aborted") -> None:
        """Stop a migration and hand the fleet back planner-reachable:
        cancel any replacement provision (nothing will ever consume
        it), uncordon the source so the gang re-binds where it was —
        unless the gang already landed off it, or is gone entirely (a
        workload-free source should finish draining to reclaim) — and
        close the trace explained.  ``outcome`` is "aborted" for
        budget-guard verdicts, "abandoned" for the timeout /
        gang-deleted closes (same cleanup, different books)."""
        pid = st.get("provision_id")
        if pid is not None and any(s.id == pid and s.in_flight
                                   for s in self.actuator.statuses()):
            try:
                self.actuator.cancel(pid)
            except Exception:  # noqa: BLE001 — abort must not wedge
                self.metrics.inc("repack_errors")
                log.exception("could not cancel repack provision %s",
                              pid)
        if unit_nodes and not st.get("landed"):
            self._cancel_drain(unit_id, unit_nodes)
        log.warning("repack of %s %s: %s", unit_id, outcome, reason)
        self._notify(f"repack of {unit_id} {outcome}: {reason}")
        self._close_repack(unit_id, st, now, outcome=outcome,
                           reason=reason)

    def _repack_realized(self, unit_id: str, st: dict,
                         now: float) -> float:
        """Freshest realized migration cost: the ledger's live repair
        accrual when the unit is still tracked (it outlives the node
        observation by one sweep), else the last per-pass snapshot."""
        cs = self.cost.accrued_chip_seconds([unit_id], now,
                                            state="repair")
        if cs is not None:
            st["realized_cost_cs"] = cs
        return (st.get("realized_cost_cs", 0.0)
                + st.get("dest_cost_cs", 0.0))

    def _close_repack(self, unit_id: str, st: dict, now: float, *,
                      outcome: str, reason: str) -> None:
        """Close an aborted/abandoned migration's books + trace."""
        realized = self._repack_realized(unit_id, st, now)
        self.repacker.note_closed(st["plan"], now, outcome=outcome,
                                  realized_cost_cs=realized,
                                  reason=reason)
        self._end_repair(unit_id, st, now, outcome=outcome,
                         attrs={"aborted": True, "reason": reason,
                                "migration_cost_chip_seconds":
                                    round(realized, 3)})

    def _complete_repack(self, unit_id: str, st: dict,
                         members: list[Pod],
                         units: dict[str, list[Node]], now: float,
                         latency: float) -> None:
        """The gang runs again off the source: settle the migration's
        bill against the tier it ACTUALLY landed on and stamp the
        chip-seconds-saved / $-proxy-saved attribution on the closing
        ``repack`` trace (the acceptance surface)."""
        from tpu_autoscaler.cost.pricebook import tier_of_labels

        plan = st["plan"]
        landed_rate = None
        node_of = {n.name: n for uns in units.values() for n in uns}
        for p in members:
            node = node_of.get(p.node_name or "")
            if node is not None and node.is_tpu:
                landed_rate = self.repacker.rate(
                    node.tpu_accelerator or plan.accel,
                    tier_of_labels(node.labels))
                break
        realized = self._repack_realized(unit_id, st, now)
        attrs = self.repacker.note_completed(
            plan, now, realized_cost_cs=realized,
            landed_rate=landed_rate)
        log.info("repack of %s complete in %.1fs: %s chip-s saved "
                 "net (~$%.2f proxy)", unit_id, latency,
                 attrs["chip_seconds_saved"],
                 attrs["dollar_proxy_saved"])
        self._notify(
            f"repack complete: {unit_id} migrated in {latency:.0f}s, "
            f"{attrs['chip_seconds_saved']:.0f} chip-s saved net")
        self._end_repair(unit_id, st, now, outcome="completed",
                         attrs={"latency_s": round(latency, 3),
                                "kind": plan.kind, **attrs},
                         metric="repack_seconds")

    def repack_route(self, params: dict | None = None) -> dict:
        """The ``/debugz/repack`` body: the Repacker's books plus the
        live in-flight migration table (docs/REPACK.md).  Read from
        the /debugz thread — bounded-retry copy, degrade-not-500."""
        del params
        if self.repacker is None:
            return {"disabled": True, "active": [], "totals": {},
                    "recent": [], "last_rejections": []}
        out = self.repacker.debug_state()
        for _ in range(5):
            try:
                out["active"] = [
                    {"unit": uid, "kind": st["plan"].kind,
                     "target_shape": st["plan"].target_shape,
                     "started": st["started"],
                     "realized_cost_cs": round(
                         st.get("realized_cost_cs", 0.0)
                         + st.get("dest_cost_cs", 0.0), 3),
                     "projected_saving_cs": round(
                         st["plan"].projected_saving_cs, 3),
                     "gangs": ["/".join(str(p) for p in k)
                               for k in st["gang_keys"]]}
                    for uid, st in list(self._slice_repairs.items())
                    if st.get("kind") == "repack"]
                break
            except (RuntimeError, KeyError):  # mutated mid-copy
                continue
        else:
            out["active"] = []
        return out

    # ---- observe-side index reads (ISSUE 7 satellite) ------------------

    def _pod_cache(self):
        cache = getattr(self.informer, "pod_cache", None) \
            if self.informer is not None else None
        return cache if cache is not None and cache.synced else None

    def _pods_by_node(self, nodes: list[Node], pods: list[Pod]
                      ) -> dict[str, list[Pod]]:
        """Pending/Running pods bound to the given nodes, keyed by node
        name — the informer's node index when synced (O(result)), else
        one scan of the pod snapshot.  The index may run a delta ahead
        of the pass's snapshot; maintenance states all sit behind grace
        windows, so a one-delta skew only shifts a decision by a pass.
        """
        names = [n.name for n in nodes]
        cache = self._pod_cache()
        if cache is not None:
            hits = cache.select_many("node", names)
            if hits is not None:
                out: dict[str, list[Pod]] = {}
                for name, sel in zip(names, hits):
                    kept = [p for p in sel
                            if p.phase in ("Pending", "Running")]
                    if kept:
                        out[name] = kept
                return out
        wanted = set(names)
        out = {}
        for p in pods:
            if p.node_name in wanted \
                    and p.phase in {"Pending", "Running"}:
                out.setdefault(p.node_name, []).append(p)
        return out

    def _gang_members(self, pods: list[Pod], key: tuple) -> list[Pod]:
        """All pods of one gang (any phase) — the informer's gang index
        when synced, else a snapshot scan."""
        cache = self._pod_cache()
        if cache is not None:
            sel = cache.select("gang", key)
            if sel is not None:
                return sel
        return [p for p in pods if p.gang_key == key]

    # ---- delta-driven planning (ISSUE 6) -------------------------------

    def _plan_scope(self, settled: list[Gang], pending: list[Gang],
                    nodes: list[Node], now: float
                    ) -> tuple[list[Gang], str]:
        """Which gangs this pass feeds the planner, and why.

        Full mode (everything): delta planning off, no informer
        indices, fair-share/preemption active (their admission depends
        on the whole demand set), or the periodic resync pass.  Delta
        mode: only gangs whose inputs digest changed — member pods,
        the supply digest of their candidate accelerator class, the
        in-flight/supply-guard entries serving them, their backoff and
        failure-streak state.  CPU gangs aggregate into shared node
        demand, so one dirty CPU gang re-plans all of them.  The
        planner itself stays pure — it just sees a shorter gang list.
        """
        cfg = self.config
        live = {g.key for g in pending}
        for key in [k for k in self._gang_plan_digests if k not in live]:
            del self._gang_plan_digests[key]
        supply = None
        if (cfg.delta_planning and self.informer is not None
                and not cfg.policy.fair_share
                and not cfg.enable_preemption
                and hasattr(self.informer, "supply_digests")):
            supply = self.informer.supply_digests(nodes)
        if supply is None:
            self._pass_plan_info = {"mode": "full",
                                    "pending": len(settled),
                                    "planned": len(settled)}
            self.metrics.set_gauge("gangs_replanned", len(settled))
            return settled, "full"
        resync = (cfg.plan_resync_passes > 0
                  and self._pass_seq % cfg.plan_resync_passes == 0)
        serving = self._serving_digests()
        # Per-class demand-set digest: gangs of one accelerator class
        # compete for the same free slices, so a gang ARRIVING, leaving,
        # or resizing must dirty its classmates — otherwise a newcomer
        # could be planned alone and claim the free slice an unchanged
        # gang was already matched to.  (uid,rv)-free on purpose: pure
        # annotation churn on one gang must not dirty the class.
        demand: dict[str, int] = {}
        for gang in settled:
            if gang.requests_tpu:
                contrib = hash((gang.key, gang.size))
                for cls in self._candidate_accels(gang):
                    demand[cls] = demand.get(cls, 0) ^ contrib
        dirty: list[Gang] = []
        cpu_dirty = False
        digests: dict[tuple, int] = {}
        for gang in settled:
            d = self._gang_digest(gang, supply, serving, demand, now)
            digests[gang.key] = d
            if self._gang_plan_digests.get(gang.key) != d:
                dirty.append(gang)
                if not gang.requests_tpu:
                    cpu_dirty = True
        if resync or len(dirty) == len(settled):
            self._gang_plan_digests.update(digests)
            if resync:
                self.metrics.inc("plan_full_resyncs")
            self._pass_plan_info = {"mode": "full",
                                    "pending": len(settled),
                                    "planned": len(settled)}
            self.metrics.set_gauge("gangs_replanned", len(settled))
            return settled, "full"
        if cpu_dirty:
            # CPU demand packs into shared nodes: all-or-none.
            dirty_keys = {g.key for g in dirty}
            fed = [g for g in settled
                   if g.key in dirty_keys or not g.requests_tpu]
        else:
            fed = dirty
        self._gang_plan_digests.update(digests)
        fed_keys = {g.key for g in fed}
        skipped = [g for g in settled if g.key not in fed_keys]
        if len(skipped) <= 32:
            for gang in skipped:
                self._explain(gang.name, "plan skipped",
                              "inputs unchanged since last pass")
        elif skipped:
            self._explain("planner", "plan skipped",
                          f"{len(skipped)} gangs with unchanged inputs")
        info = {"mode": "delta", "pending": len(settled),
                "planned": len(fed)}
        if len(fed) <= 32:
            info["planned_keys"] = ["/".join(str(p) for p in g.key)
                                    for g in fed]
        self._pass_plan_info = info
        self.metrics.set_gauge("gangs_replanned", len(fed))
        return fed, "delta"

    def _serving_digests(self) -> dict[tuple, int]:
        """Per-gang-key digest of the actuator statuses + supply-guard
        entries serving it (any state change — submit, ACTIVE, FAILED,
        prune, guard engage/release/expire — flips the digest).  The
        ("tpu",)/("cpu",) ledger keys aggregate EVERY entry of that
        kind: the chip/node clamps (max_total_chips, max_cpu_nodes,
        namespace quotas) are global across demand, so any in-flight
        state change — a spare landing, a FAILED prune freeing
        headroom — must dirty every gang of the kind."""
        out: dict[tuple, int] = {}

        def fold(key, contrib):
            if key is not None:
                out[key] = out.get(key, 0) ^ contrib

        for s in self.actuator.statuses():
            contrib = hash((s.id, s.state))
            fold(s.request.gang_key, contrib)
            for k in s.request.gang_keys or ():
                if k != s.request.gang_key:
                    fold(k, contrib)
            fold(("cpu",) if s.request.kind == "cpu-node" else ("tpu",),
                 contrib)
        for pid, (inf, unit_ids, _since) in \
                self._supply_awaiting_nodes.items():
            contrib = hash((pid, "guarded", unit_ids))
            fold(inf.gang_key, contrib)
            fold(("cpu",) if inf.kind == "cpu-node" else ("tpu",),
                 contrib)
        return out

    def _gang_digest(self, gang: Gang, supply: dict[str, int],
                     serving: dict[tuple, int],
                     demand: dict[str, int], now: float) -> int:
        """Everything that could change this gang's slice of the plan,
        folded to one integer.  Conservative over-approximation: a
        digest change that doesn't alter the plan costs one redundant
        (pure) re-plan; the reverse would be a miss, so every input the
        planner or the dispatch gate reads is represented."""
        members = hash(frozenset(
            (p.uid, p.resource_version or "", p.phase, p.node_name or "")
            for p in gang.pods))
        if gang.requests_tpu:
            classes = self._candidate_accels(gang)
        else:
            classes = ("cpu",)
        # Hash the per-class tuple, never XOR across classes: two
        # classes carrying IDENTICAL digests (e.g. every v5e accel
        # type with the same pending set) would cancel to 0 under XOR
        # and mask real changes.
        supply_d = hash(tuple(
            (cls, supply.get(cls, 0), demand.get(cls, 0))
            for cls in classes))  # demand: classmates compete for it
        group_key = gang.multislice_group_key
        serving_d = serving.get(gang.key, 0)
        if group_key is not None:
            serving_d ^= serving.get(group_key, 0)
        # The kind-wide ledger: global clamps mean any in-flight change
        # of the kind can alter this gang's plan.
        serving_d ^= serving.get(
            ("tpu",) if gang.requests_tpu else ("cpu",), 0)
        # Backoff is keyed by the request's gang_key — the multislice
        # GROUP key for cohort provisions — so check both.
        retry_at = self._retry_at.get(gang.key, 0.0)
        if group_key is not None:
            retry_at = max(retry_at,
                           self._retry_at.get(group_key, 0.0))
        in_backoff = now < retry_at
        streak = self._failure_streak.get(gang.key, 0)
        if group_key is not None:
            streak = max(streak,
                         self._failure_streak.get(group_key, 0))
        return hash((members, supply_d, serving_d, in_backoff, streak,
                     gang.size))

    def _candidate_accels(self, gang: Gang) -> tuple[str, ...]:
        """Accelerator classes whose supply could serve this gang —
        the pinned accelerator, or every accelerator of the default +
        fallback generations (over-approximation is safe; missing one
        would be a digest blind spot)."""
        from tpu_autoscaler.topology.catalog import (
            ACCELERATOR_LABEL,
            shapes_for_generation,
        )

        pinned = gang.node_selectors.get(ACCELERATOR_LABEL)
        if pinned is not None:
            return (pinned,)
        pol = self.config.policy
        gens = (pol.default_generation, *pol.generation_fallbacks)
        out: list[str] = []
        for gen in gens:
            try:
                shapes = shapes_for_generation(gen)
            except KeyError:
                continue
            for s in shapes:
                if s.accelerator_type not in out:
                    out.append(s.accelerator_type)
        return tuple(out)

    # ---- time-series health layer (ISSUE 10) --------------------------- #

    def _obs_pass(self, now: float) -> dict:
        """Fold this pass's metrics into the TSDB and evaluate the
        alert catalog.  Crash-only on both halves: retention or
        alerting failing must degrade the controller's introspection,
        never its scaling.  Returns the pass record's ``alerts``
        section (empty when nothing is active or transitioning)."""
        try:
            exemplars = self._take_exemplars()
            self.tsdb.ingest(self.metrics.snapshot(), now,
                             exemplars=exemplars)
            self.metrics.set_gauge("tsdb_series",
                                   self.tsdb.series_count())
            if self.tsdb.series_dropped:
                self.metrics.set_gauge("tsdb_series_dropped",
                                       self.tsdb.series_dropped)
        except Exception:  # noqa: BLE001 — introspection only
            self.metrics.inc("tsdb_errors")
            log.exception("tsdb ingest failed; metric history degrades")
        if self.alerts is None or not self.alerts.rules:
            return {}
        try:
            result = self.alerts.evaluate(self.tsdb, now)
        except Exception:  # noqa: BLE001 — introspection only
            self.metrics.inc("alert_eval_errors")
            log.exception("alert evaluation failed; continuing unwatched")
            return {}
        for tr in result.transitions:
            gauge = (f"tpu_autoscaler_alerts_active_"
                     f"{tr.rule.replace('-', '_')}")
            self.metrics.set_gauge(gauge, 1.0 if tr.firing else 0.0)
            if tr.firing:
                self.metrics.inc("alerts_fired")
                log.warning("%s", tr.summary)
                self._explain(("alert", tr.rule), "alert firing",
                              tr.summary, severity=tr.severity)
                self._notify(tr.summary)
                if self.blackbox is not None \
                        and self.blackbox.capture_async(
                            f"alert:{tr.rule}"):
                    # The bundle builds + writes on a throwaway
                    # thread (O(series x points) serialization must
                    # never stall a pass); the writer counts
                    # incident_bundles_written on success.
                    self._explain(("alert", tr.rule),
                                  "incident capture scheduled")
            else:
                self.metrics.inc("alerts_resolved")
                log.info("%s", tr.summary)
                self._explain(("alert", tr.rule), "alert resolved",
                              tr.summary)
                self._notify(tr.summary)
        if result.active or result.transitions:
            return {"active": list(result.active)}
        return {}

    def _take_exemplars(self) -> dict[str, tuple[str, float]]:
        """This pass's (trace_id, value) exemplars, one per histogram
        family (ISSUE 14, docs/OBSERVABILITY.md "Request spans &
        exemplars"):

        - the serving adapter's taken exemplar — a sampled slow
          request's latency, whose value is observed into
          ``serving_request_latency_ticks`` HERE (the engines are
          out-of-process; their latencies reach the registry only
          through this path), so the exemplar is always a member of
          the same pass's observations;
        - control-plane span exemplars (``_span_exemplars``, e.g. the
          ``scale_up`` root close) whose values the tracer already
          observed — they must NOT be re-observed.
        """
        ex: dict[str, tuple[str, float]] = {}
        if self.serving_scaler is not None:
            adapter = getattr(self.serving_scaler, "adapter", None)
            if adapter is not None \
                    and hasattr(adapter, "take_exemplars"):
                for family, (tid, value) in \
                        adapter.take_exemplars().items():
                    self.metrics.observe(family, value)
                    ex[family] = (tid, value)
        ex.update(self._span_exemplars)
        self._span_exemplars.clear()
        if ex:
            self.metrics.inc("tsdb_exemplars_ingested",
                             float(len(ex)))
        return ex

    # ---- cost attribution ledger (ISSUE 11) ---------------------------- #

    def _cost_pass(self, now: float, fleet_chips: int) -> dict:
        """Close the cost ledger's pass.  Crash-only: a ledger bug
        degrades cost observability, never scaling.  Suspended under
        ``no_maintenance`` — the unit loop that feeds classifications
        did not run, so a conservation check would false-alarm."""
        if self.config.no_maintenance:
            return {}
        try:
            return self.cost.close_pass(now, fleet_chips)
        except Exception:  # noqa: BLE001 — observability only
            self.metrics.inc("cost_ledger_errors")
            log.exception("cost ledger close failed; attribution "
                          "degrades this pass")
            return {}

    def cost_route(self, params: dict | None = None) -> dict:
        """The ``/debugz/cost`` body: the ledger's full bill breakdown
        (docs/COST.md), plus the serving fleet census when a scaler is
        attached — the serving share of the bill with its live
        context."""
        del params  # no query filters yet
        out = self.cost.debug_state(now=self._last_pass_at)
        if self.serving_scaler is not None:
            adapter = getattr(self.serving_scaler, "adapter", None)
            if adapter is not None \
                    and hasattr(adapter, "fleet_summary"):
                for _ in range(5):
                    try:
                        out["serving"] = adapter.fleet_summary()
                        break
                    # The adapter registers pools in two steps
                    # (index first, sums after), so a read landing in
                    # that window raises IndexError, not just
                    # RuntimeError — degrade, never 500.
                    except (RuntimeError, IndexError, KeyError):
                        continue
                else:
                    out["serving"] = {"unavailable": "mutating"}
        return out

    def tsdb_route(self, params: dict | None = None) -> dict:
        """The ``/debugz/tsdb`` body: the TSDB dump, filterable by
        ``?prefix=`` and trimmable by ``?window=`` seconds."""
        params = params or {}
        window = None
        if params.get("window"):
            try:
                window = float(params["window"])
            except ValueError:
                window = None
        now = self._last_pass_at if self._last_pass_at is not None \
            else time.time()
        return self.tsdb.dump(prefix=params.get("prefix", ""),
                              window_seconds=window, now=now)

    def profile_route(self, params: dict | None = None) -> dict:
        """The ``/debugz/profile`` body: cumulative + recent per-pass
        phase ledgers, conservation state, and the sampler's collapsed
        stacks when one is attached (docs/OBSERVABILITY.md
        "Control-plane profiling")."""
        del params  # no query filters yet
        out = self.profiler.debug_state()
        if self.profiler.sampler is not None:
            out["collapsed"] = self.profiler.sampler.collapsed()
        return out

    def incident_bundle(self, reason: str = "manual") -> dict:
        """The black-box bundle: everything ``debug_dump`` serves plus
        the TSDB windows, the alert rules + state, informer store
        digests and a config summary — self-contained input for
        ``python -m tpu_autoscaler.obs replay`` (docs/OBSERVABILITY.md
        bundle format)."""
        from tpu_autoscaler.obs.blackbox import BUNDLE_VERSION

        out = self.debug_dump()
        out["bundle"] = {"version": BUNDLE_VERSION, "reason": reason,
                         "captured_at": time.time()}
        out["tsdb"] = self.tsdb.dump()
        # The ledger snapshot (ISSUE 11): `tpu-autoscaler cost-report
        # --from <bundle>` renders the bill an incident was captured
        # under, and `--window` reads the cost_* TSDB series above.
        out["cost"] = self.cost.debug_state(now=self._last_pass_at)
        # The repacker's books (ISSUE 12): `tpu-autoscaler
        # repack-report --from <bundle>` renders the migration ledger
        # an incident was captured under.
        out["repack"] = self.repack_route()
        # Tail-latency root-cause attribution recorded AT CAPTURE TIME
        # (ISSUE 14): the offline replay recomputes the same analysis
        # from the bundle and exits 2 on dominant-cause divergence —
        # crash-only, a broken analyzer degrades the bundle, never
        # the capture.
        try:
            from tpu_autoscaler.obs import tailcause

            out["tailcause"] = tailcause.analyze(out)
        except Exception:  # noqa: BLE001 — diagnostics only
            self.metrics.inc("tailcause_errors")
            log.exception("tailcause analysis failed; bundle carries "
                          "no tail-report section")
        # Control-plane profile recorded AT CAPTURE TIME (ISSUE 20):
        # the phase ledgers + collapsed stacks, plus the windowed
        # decomposition the offline replay recomputes from the
        # bundle's own TSDB and compares against (exit 2 on
        # divergence).  Crash-only like the tailcause section — a
        # broken profiler degrades the bundle, never the capture.
        try:
            from tpu_autoscaler.obs import perfreport

            profile = self.profiler.debug_state()
            if self.profiler.sampler is not None:
                profile["collapsed"] = self.profiler.sampler.collapsed()
            profile["report"] = perfreport.decompose(out["tsdb"])
            out["profile"] = profile
        except Exception:  # noqa: BLE001 — diagnostics only
            self.metrics.inc("profiler_report_errors")
            log.exception("profile capture failed; bundle carries no "
                          "profile section")
        out["informer"] = self._informer_digest()
        cfg = self.config
        out["config"] = {
            "idle_threshold_seconds": cfg.idle_threshold_seconds,
            "grace_seconds": cfg.grace_seconds,
            "drain_grace_seconds": cfg.drain_grace_seconds,
            "provision_timeout_seconds": cfg.provision_timeout_seconds,
            "delta_planning": cfg.delta_planning,
            "enable_slice_repair": cfg.enable_slice_repair,
            "enable_preemption": cfg.enable_preemption,
            "enable_repack": cfg.enable_repack,
            "max_total_chips": cfg.policy.max_total_chips,
            "default_generation": cfg.policy.default_generation,
        }
        return out

    def _informer_digest(self) -> dict | None:
        """Cheap informer-store summary for incident bundles: per-kind
        object counts, sync state and resource versions (the cache's
        identity — enough to tell two bundles' world views apart
        without serializing 100k objects)."""
        if self.informer is None:
            return None
        out: dict = {}
        for kind in ("pod", "node"):
            cache = getattr(self.informer, f"{kind}_cache", None)
            if cache is None:
                continue
            out[kind + "s"] = {
                "synced": bool(cache.synced),
                "objects": len(cache),
                "resource_version": cache.resource_version,
            }
        return out

    # ---- observability helpers ----------------------------------------- #

    def debug_dump(self) -> dict:
        """The flight-recorder dump served on /debugz and written on
        SIGUSR1: completed spans, decision records, still-open spans
        (what a stuck pass is waiting on), and the metrics snapshot —
        everything needed to diagnose a live controller without a
        restart (docs/OBSERVABILITY.md)."""
        out = self.recorder.dump(tracer=self.tracer)
        out["metrics"] = self.metrics.snapshot()
        if self.policy_engine is not None:
            # Prewarm table + provision estimate (reconcile-thread
            # state read concurrently; values are scalars/copies).
            out["policy"] = self.policy_engine.debug_state()
        if self.serving_scaler is not None:
            # Scale-out table + replica census: scalar copies, same
            # bounded-concurrency caveats as the policy table.
            for _ in range(5):
                try:
                    out["serving"] = self.serving_scaler.debug_state()
                    break
                except RuntimeError:  # mutated mid-copy; retry
                    continue
            else:
                out["serving"] = {"unavailable": "mutating"}
        if self.alerts is not None and self.alerts.rules:
            # Rule catalog + hysteresis state (bounded-retry copy
            # inside debug_state — same /debugz concurrency caveats).
            out["alerts"] = self.alerts.debug_state()
        # This dict is reconcile-thread-owned and deliberately
        # lock-free (giving the Controller a lock would put EVERY
        # field under the thread-discipline checker); the /debugz
        # thread reads it concurrently, so copy with a bounded retry —
        # a resize mid-copy raises RuntimeError, and a diagnostic
        # endpoint must degrade, not 500, exactly when the controller
        # is busy.
        for _ in range(5):
            try:
                out["supply_guard"] = {
                    pid: {"units": list(unit_ids), "since": since}
                    for pid, (_inf, unit_ids, since)
                    in list(self._supply_awaiting_nodes.items())}
                break
            except RuntimeError:  # mutated mid-copy; retry
                continue
        else:
            out["supply_guard"] = {"unavailable": "mutating"}
        return out

    def _notify(self, message: str) -> None:
        """Notifier calls are advisory: a webhook outage (or a buggy
        custom Notifier) must never abort a reconcile pass.  Counted,
        logged, swallowed — like the other advisory paths."""
        try:
            self.notifier.notify(message)
        except Exception:  # noqa: BLE001 — advisory only
            self.metrics.inc("notifier_errors")
            log.warning("notifier failed for %r", message, exc_info=True)

    def _explain(self, subject, decision: str, reason: str = "",
                 **attrs) -> None:
        """Append one per-unit reason to this pass's decision record
        (flight recorder; `tpu-autoscaler explain`)."""
        event = {"subject": str(subject), "decision": decision}
        if reason:
            event["reason"] = reason
        event.update({k: v for k, v in attrs.items() if v is not None})
        self._pass_events.append(event)

    def _trace_roots(self, request) -> list[Span]:
        """Root spans of every pending gang a provision serves (the
        multislice cohort's members each get the story in their own
        trace; CPU requests aggregate demand and map to no one gang).
        A gang under ICI-atomic repair adds its ``slice_repair`` root,
        so replacement provisions trace under the repair story too —
        and are the ONLY root while the gang's pods are still Running
        on the broken slice (repair-ahead provisioning)."""
        keys: list[tuple] = []
        if request.gang_key is not None:
            keys.append(request.gang_key)
        for key in request.gang_keys or ():
            if key not in keys:
                keys.append(key)
        roots = [self._gang_traces[k] for k in keys
                 if k in self._gang_traces]
        for key in keys:
            span = self._repair_roots.get(key)
            if span is not None and all(span is not r for r in roots):
                roots.append(span)
        return roots

    def _fresh_nodes(self) -> list[Node]:
        """Direct LIST, bypassing the informer cache (memo-parsed, so
        only nodes that actually changed are re-parsed)."""
        from tpu_autoscaler.k8s.objects import parse_node

        if self.informer is None:
            return [Node(p) for p in self.client.list_nodes()]
        self.metrics.inc("informer_bypass_lists")
        return [parse_node(p) for p in self.client.list_nodes()]

    def close(self) -> None:
        """Release process resources the controller owns (today: the
        shard worker pool).  Idempotent; only harnesses that build
        many controllers per process (chaos corpora, benches, tests)
        need it — a production controller lives as long as the
        process."""
        if self.sharder is not None:
            self.sharder.close()
        if self.profiler.sampler is not None:
            self.profiler.sampler.stop()

    def run_forever(self, interval_seconds: float = 5.0,
                    watch: bool = True, leader_lock=None) -> None:
        """Reconcile loop (reference: main.py while True / sleep).

        The interval is seconds-scale, not the reference's 60 s — detection
        latency is part of the north-star budget — and when ``watch`` is on
        an informer (k8s/informer.py) both wakes the loop the instant
        demand changes AND feeds reconcile passes from its watch-fed
        cache, making the interval only a fallback and the observe path
        O(churn) instead of O(cluster).  Each pass is wrapped in a
        catch-all so the loop is crash-only (reference parity).
        """
        from tpu_autoscaler import concurrency

        wake = concurrency.Event()
        if watch and self.informer is None \
                and hasattr(self.client, "watch_pods"):
            from tpu_autoscaler.k8s.informer import ClusterInformer

            self.informer = ClusterInformer(
                self.client, wake=wake, metrics=self.metrics,
                tracer=self.tracer)
            self.informer.start()
        elif self.informer is not None:
            # Injected informer: sleep on ITS wake event so its deltas
            # still cut detection latency.
            wake = self.informer.wake
        while True:
            try:
                if leader_lock is not None and not leader_lock.try_acquire(
                        time.time()):
                    self.metrics.set_gauge("is_leader", 0)
                else:
                    if leader_lock is not None:
                        self.metrics.set_gauge("is_leader", 1)
                    self.reconcile_once()
            except Exception:  # noqa: BLE001 — crash-only loop
                log.exception("reconcile pass failed")
                self.metrics.inc("reconcile_errors")
            wake.wait(timeout=interval_seconds)
            wake.clear()

    def _settled(self, gangs: list[Gang], now: float) -> list[Gang]:
        """Filter out TPU gangs still inside the settle window.

        Only applies to un-pinned TPU gangs (no gke-tpu-topology selector)
        whose observed chip demand could still be partial.  The window is
        QUIESCENCE-based: it restarts whenever the gang grows, so slow pod
        materialization extends the wait instead of racing it — the gang
        is sized only after ``settle`` seconds without a new member.  The
        wait still counts toward the reported scale-up latency (no hidden
        time).
        """
        settle = self.config.gang_settle_seconds
        if settle <= 0:
            return gangs
        from tpu_autoscaler.topology.catalog import TOPOLOGY_LABEL

        out, settling = [], 0
        for gang in gangs:
            if (not gang.requests_tpu
                    or TOPOLOGY_LABEL in gang.node_selectors):
                out.append(gang)
                continue
            size, since = self._gang_sizes.get(gang.key, (0, now))
            if gang.size != size:
                since = now  # grew (or first seen): restart the clock
            self._gang_sizes[gang.key] = (gang.size, since)
            if now - since < settle:
                settling += 1
                self._explain(gang.name, "sizing deferred",
                              "inside the gang-settle window")
            else:
                out.append(gang)
        self.metrics.set_gauge("gangs_settling", settling)
        return out

    def _attach_columnar(self, nodes: list[Node], pods: list[Pod]):
        """The informer-maintained columnar planner state for THIS
        pass (docs/PLANNER.md), or None to plan purely in Python.

        Attachment is gated three ways, all crash-only: the view must
        refresh (both caches synced), its digest stamps must equal the
        store digests captured with this pass's observation (the watch
        threads may have moved the caches since ``_observe``), and the
        cheap ``attachable`` alignment check must pass.  Any failure
        or mismatch just forfeits the fast path for one pass —
        ``columnar_passes``/``columnar_stale``/``columnar_fallbacks``
        count how often each happens.
        """
        if (not self.config.columnar_planning or self.informer is None
                or not hasattr(self.informer, "columnar_view")):
            return None
        # One attach (and one set of counters) per pass: _scale and
        # _maintain plan over the same observation.  The memo is reset
        # in _observe, so the id() pair can never alias across passes.
        memo = getattr(self, "_columnar_memo", None)
        if memo is not None and memo[0] == (id(nodes), id(pods)):
            return memo[1]
        state = self._attach_columnar_uncached(nodes, pods)
        self._columnar_memo = ((id(nodes), id(pods)), state)
        return state

    def _attach_columnar_uncached(self, nodes: list[Node],
                                  pods: list[Pod]):
        try:
            state = self.informer.columnar_view().refresh()
            if state is None:
                self.metrics.inc("columnar_fallbacks")
                return None
            digests = self._observed_cache_digests
            if (digests is None
                    or state.node_digest != digests[0]
                    or state.pod_digest != digests[1]
                    or not state.attachable(nodes, pods)):
                self.metrics.inc("columnar_stale")
                return None
            self.metrics.inc("columnar_passes")
            return state
        except Exception:  # noqa: BLE001 — the columnar state is a
            # pure optimization; the Python planner carries the pass.
            self.metrics.inc("columnar_fallbacks")
            log.exception("columnar attach failed; Python planner "
                          "this pass")
            return None

    # ---- scale-up ------------------------------------------------------ #

    def _scale(self, gangs: list[Gang], nodes: list[Node],
               pods: list[Pod], now: float,
               all_gangs: list[Gang] | None = None,
               plan_mode: str = "full",
               advisory: list[tuple[Gang, str]] = ()) -> None:
        # ``gangs`` is the planning scope (all settled gangs in full
        # mode; only input-changed ones in delta mode); ``all_gangs``
        # is the complete settled list, used for side-effect-bearing
        # bookkeeping that must not depend on the scope and for the
        # verify-mode full plan.  ``advisory`` is slice-repair
        # replacement demand (gang, like-for-like shape) the planner
        # admits alongside — always in scope, never delta-skipped.
        if all_gangs is None:
            all_gangs = gangs
        # Process failures FIRST so a provision that failed since last pass
        # sets its backoff before we consider re-submitting for its demand.
        self._note_failures(now, pods)
        overrides = self._generation_overrides(all_gangs, now)
        t_plan = time.perf_counter()
        in_flight = self._in_flight()
        columnar = self._attach_columnar(nodes, pods)
        if self.sharder is not None and not self.config.enable_preemption:
            # Sharded planning (ISSUE 13): byte-identical to the
            # serial call below by the merge contract; preemption
            # keeps the serial path (its victim choice reads the
            # whole unsatisfiable set, like fair_share).
            plan = self.sharder.plan(
                gangs, nodes, pods, in_flight,
                generation_overrides=overrides, advisory_gangs=advisory,
                candidate_accels=self._candidate_accels,
                columnar=columnar)
            self._pass_plan_info["sharding"] = dict(
                self.sharder.last_info)
        else:
            plan = self.planner.plan(gangs, nodes, pods, in_flight,
                                     generation_overrides=overrides,
                                     advisory_gangs=advisory,
                                     columnar=columnar)
        if columnar is not None and self.config.verify_columnar_plans:
            # Parity gate (docs/PLANNER.md, the delta/shard landing
            # pattern): the Python planner is the property oracle —
            # replan without the columnar state and gate byte-identical
            # decisions.  On mismatch the oracle's plan is ADOPTED, so
            # verify mode cannot actuate a columnar bug.
            oracle = self.planner.plan(gangs, nodes, pods, in_flight,
                                       generation_overrides=overrides,
                                       advisory_gangs=advisory)
            same = (oracle.requests == plan.requests
                    and [(g.key, r) for g, r in oracle.unsatisfiable]
                    == [(g.key, r) for g, r in plan.unsatisfiable]
                    and [(g.key, r) for g, r in oracle.deferred]
                    == [(g.key, r) for g, r in plan.deferred])
            if not same:
                self.metrics.inc("columnar_plan_mismatches")
                log.error(
                    "columnar plan diverged from the Python oracle: "
                    "%d vs %d requests; adopting the oracle's plan",
                    len(plan.requests), len(oracle.requests))
                self._explain("planner", "columnar plan mismatch",
                              f"columnar={len(plan.requests)} "
                              f"oracle={len(oracle.requests)} requests")
                plan = oracle
        self._pass_plan_s = time.perf_counter() - t_plan
        for gang, reason in plan.deferred:
            # Advisory demand waiting for clamp/quota headroom:
            # explained, never reported unsatisfiable (a repair's
            # replacement is queued behind policy; a prewarm simply
            # does not fire — organic demand keeps its headroom).
            what = ("prewarm" if gang.key and gang.key[0] == "prewarm"
                    else "repair")
            self._explain(gang.name, f"{what} provisioning deferred",
                          reason)
        if plan_mode == "delta" and self.config.verify_delta_plans:
            # Parity gate (tests/bench): the incremental path must
            # produce byte-identical requests to full planning.
            full = self.planner.plan(all_gangs, nodes, pods, in_flight,
                                     generation_overrides=overrides,
                                     advisory_gangs=advisory)
            if full.requests != plan.requests:
                self.metrics.inc("delta_plan_mismatches")
                log.error(
                    "delta plan diverged from full plan: %d vs %d "
                    "requests", len(plan.requests), len(full.requests))
                self._explain("planner", "delta plan mismatch",
                              f"delta={len(plan.requests)} "
                              f"full={len(full.requests)} requests")
        # One lookup table for the dispatch loop below: rebuilding
        # served_gangs by scanning the gang list per request was
        # O(requests × gangs) — a measurable slice of the million-pod
        # pass (ISSUE 13 audit).
        gang_by_key = {g.key: g for g in gangs}
        gang_pos = {g.key: i for i, g in enumerate(gangs)}
        for req in plan.requests:
            # Respect retry backoff after a failed provision for the same
            # demand (gang, or shape for gang-less spare provisions).
            backoff_key = req.gang_key or ("shape", req.shape_name)
            if now < self._retry_at.get(backoff_key, 0.0):
                self._explain(
                    backoff_key, "provision deferred",
                    "retry backoff after a failed provision",
                    retry_at=round(self._retry_at[backoff_key], 3),
                    shape=req.shape_name)
                continue
            with self.profiler.phase("actuate_dispatch"):
                status = self._dispatch_provision(req, now)
            log.info("provisioning %s x%d (%s): %s", req.shape_name,
                     req.count, status.id, req.reason)
            self._note_repair_provision(req, status, now)
            self._submitted_at[status.id] = now
            self.metrics.inc("provisions_submitted")
            self._explain(req.gang_key or ("shape", req.shape_name),
                          "provision submitted", req.reason,
                          provision_id=status.id, shape=req.shape_name,
                          count=req.count)
            if req.kind == "tpu-slice":
                self.metrics.observe("stranded_chips", req.stranded_chips)
            self._notify(
                f"scaling up: {req.count}x {req.shape_name} — {req.reason}")
            if req.kind == "cpu-node":
                # CPU provisions aggregate demand across gangs (no
                # gang_key): every pending CPU gang is being detected by
                # this submission for the phase anatomy's purposes.
                self._observe_detect(
                    (g.key for g in gangs if not g.requests_tpu), now)
            if req.gang_key is not None:
                # gang_keys lists the exact cohort a multislice request
                # serves (a sibling bound to an existing free slice is not
                # in it and must not get a misleading scale-up event).
                member_keys = set(req.gang_keys) or {req.gang_key}
                self._observe_detect(member_keys, now)
                served_gangs = [gang_by_key[k] for k in
                                sorted((k for k in member_keys
                                        if k in gang_by_key),
                                       key=gang_pos.__getitem__)]
                for pod in (p for g in served_gangs for p in g.pods):
                    self._emit_event(
                        pod, now, "TriggeredScaleUp",
                        f"provisioning {req.shape_name} for this job "
                        f"({req.reason})")
        handled_by_preemption: set[tuple] = set()
        if self.config.enable_preemption and not self.config.no_maintenance:
            handled_by_preemption = self._consider_preemption(
                plan, nodes, pods, now)
        for gang, reason in plan.unsatisfiable:
            if gang.key in handled_by_preemption:
                self._explain(gang.name, "not provisioned",
                              "preemption is making room")
                continue  # being actively made room for: not unsatisfiable
            if self._repair_depends_on(gang.key):
                # Clamp-blocked only until the repair deletes the broken
                # slice — room is being made, same as preemption.
                self._explain(gang.name, "not provisioned",
                              "slice repair is making room")
                continue
            self._explain(gang.name, "unsatisfiable", reason)
            if gang.key not in self._reported_unsatisfiable:
                self._reported_unsatisfiable.add(gang.key)
                log.warning("unsatisfiable %s: %s", gang, reason)
                self.metrics.inc("unsatisfiable_gangs")
                self._notify(f"cannot satisfy {gang.name}: {reason}")
                # Stamp the verdict on the pods so `kubectl describe`
                # answers "why is my job not scaling" without log access.
                for pod in gang.pods:
                    self._emit_event(pod, now, "NotTriggerScaleUp",
                                     reason, warning=True)
                for pod in gang.pods:
                    try:
                        self.client.patch_pod(pod.namespace, pod.name, {
                            "metadata": {"annotations": {
                                UNSATISFIABLE_ANNOTATION: reason[:500]}}})
                    except Exception:  # noqa: BLE001 — advisory only
                        self.metrics.inc("advisory_errors")
                        log.debug("could not annotate %s", pod.name,
                                  exc_info=True)

    def _dispatch_provision(self, req, now: float):
        """Submit one provision with its trace story attached.

        The pass's shared observe/plan windows are replayed into every
        served gang's trace (a pass observes once no matter how many
        gangs it serves), then the actual ``actuator.provision`` call
        runs inside a ``dispatch`` span made current — so actuator- and
        executor-level spans (create POSTs, including ones that resolve
        at a later drain) parent under it, across the pool boundary.
        Span timestamps ride the injected reconcile clock offset by the
        measured perf-counter phase durations, keeping one coherent
        time base per trace even under simulated time.
        """
        roots = self._trace_roots(req)
        if not roots:
            return self.actuator.provision(req)
        pass_now, observe_s = self._pass_obs
        t_obs_end = pass_now + observe_s
        t_plan_end = t_obs_end + self._pass_plan_s
        for root in roots:
            self.tracer.record("observe", start=pass_now, end=t_obs_end,
                               parent=root)
            self.tracer.record("plan", start=t_obs_end, end=t_plan_end,
                               parent=root)
        dspan = self.tracer.start(
            "dispatch", parent=roots[0], t=t_plan_end,
            attrs={"shape": req.shape_name, "count": req.count,
                   "reason": req.reason})
        t_d0 = time.perf_counter()
        try:
            with self.tracer.use(dspan):
                status = self.actuator.provision(req)
        except Exception as e:
            self.tracer.end(dspan, t=t_plan_end
                            + (time.perf_counter() - t_d0),
                            attrs={"error": str(e)})
            raise
        t_d_end = t_plan_end + (time.perf_counter() - t_d0)
        self.tracer.end(dspan, t=t_d_end,
                        attrs={"provision_id": status.id})
        self._provision_roots[status.id] = roots
        for root in roots[1:]:
            # Multislice siblings: each member's trace carries the
            # shared dispatch (same timestamps, cross-linked by id).
            self.tracer.record("dispatch", start=t_plan_end, end=t_d_end,
                               parent=root,
                               attrs={"shape": req.shape_name,
                                      "count": req.count,
                                      "provision_id": status.id,
                                      "shared_with": roots[0].trace_id})
        return status

    def _consider_preemption(self, plan, nodes: list[Node],
                             pods: list[Pod], now: float) -> set[tuple]:
        """Reclaim chips from lower-priority busy units for clamp-blocked
        higher-priority gangs.  Victims go through the normal
        checkpoint-aware drain; the freed budget lets the planner
        provision for the preemptor on a later pass.  Returns the gang
        keys being made room for (so they are not reported unsatisfiable
        while the room is being made).
        """
        from tpu_autoscaler.engine.fitter import (
            FitError,
            choose_shape_for_gang,
        )
        from tpu_autoscaler.topology.catalog import TPU_RESOURCE

        handled: set[tuple] = set()
        blocked = [(g, r) for g, r in plan.unsatisfiable
                   if "max_total_chips" in r]
        if not blocked:
            return handled
        pods_by_node: dict[str, list[Pod]] = {}
        for p in pods:
            if p.node_name:
                pods_by_node.setdefault(p.node_name, []).append(p)
        units = self._units(nodes)

        def unit_chips(unit_nodes):
            return sum(int(n.allocatable.get(TPU_RESOURCE))
                       for n in unit_nodes)

        existing_chips = sum(unit_chips(ns) for ns in units.values()
                             if ns[0].is_tpu)
        # The planner's max_total_chips check counts in-flight slices as
        # supply (including supply-guarded just-ACTIVE ones), so the
        # overshoot must too — otherwise with provisions in flight
        # preemption frees too few chips and the gang stays
        # clamp-blocked through repeated victim rounds.
        from tpu_autoscaler.topology.catalog import shape_by_name

        inflight_chips = sum(
            shape_by_name(f.shape_name).chips * f.count
            for f in self._in_flight() if f.kind == "tpu-slice")
        # Chips already on their way out (drains in progress) free up
        # without new victims — credit them before choosing more.
        draining_ids = (set(self._drain_started)
                        | self._requested_drains) & set(units)
        draining_chips = sum(unit_chips(units[uid]) for uid in draining_ids
                             if units[uid][0].is_tpu)

        # Clamp-blocked sibling gangs of one jobset are provisioned as ONE
        # atomic multislice unit (planner cohorts), so preemption must
        # make room for ALL their slices in one round — per-gang rounds
        # would free one slice's worth, see need<=0 for the siblings, and
        # leave the unit rejected for ~N drain cycles.
        demand_units: list[list[Gang]] = []
        grouped: dict[tuple, list[Gang]] = {}
        for gang, _reason in blocked:
            group_key = gang.multislice_group_key
            if group_key is None:
                demand_units.append([gang])
            elif group_key not in grouped:
                grouped[group_key] = [gang]
                demand_units.append(grouped[group_key])
            else:
                grouped[group_key].append(gang)

        for unit_gangs in demand_units:
            gang = max(unit_gangs, key=lambda g: g.priority)  # lead
            member_keys = {g.key for g in unit_gangs}
            cool_key = ("preempt",
                        gang.multislice_group_key or gang.key)
            if now < self._retry_at.get(cool_key, 0.0):
                handled |= member_keys  # room is being made; don't report
                continue
            try:
                demand_chips = sum(
                    choose_shape_for_gang(
                        g, self.config.policy.default_generation).shape.chips
                    for g in unit_gangs)
            except FitError:
                continue  # not actually clamp-only blocked
            # Free exactly the overshoot, not the gang's whole demand:
            # existing + in-flight - freed - draining + demand
            #   <= max_total_chips.
            need = (existing_chips + inflight_chips - draining_chips
                    + demand_chips - self.config.policy.max_total_chips)
            if need <= 0:
                handled |= member_keys  # in-progress drains already suffice
                continue
            candidates = []
            for unit_id, unit_nodes in units.items():
                if not unit_nodes[0].is_tpu or unit_id in draining_ids:
                    continue
                workload = [p for n in unit_nodes
                            for p in pods_by_node.get(n.name, [])
                            if p.is_workload]
                if not workload:
                    continue  # idle units free up via normal reclaim
                unit_prio = max(p.priority for p in workload)
                if unit_prio >= gang.priority:
                    continue
                candidates.append((unit_prio, unit_chips(unit_nodes),
                                   unit_id))
            # Lowest priority first, smallest unit first; then prune
            # victims made redundant by later (bigger) picks so the set
            # destroys the least work that still covers the need.
            candidates.sort()
            freed, victims = 0, []
            for _prio, chips, unit_id in candidates:
                if freed >= need:
                    break
                victims.append((unit_id, chips))
                freed += chips
            if freed < need:
                continue  # preemption cannot help this gang
            for unit_id, chips in list(victims):
                if freed - chips >= need:
                    victims.remove((unit_id, chips))
                    freed -= chips
            victims = [unit_id for unit_id, _ in victims]
            for unit_id in victims:
                log.warning("preempting unit %s for higher-priority gang "
                            "%s", unit_id, gang.name)
                self.metrics.inc("preemptions")
                self._explain(unit_id, "preempted",
                              f"making room for higher-priority "
                              f"{gang.name}")
                self._notify(
                    f"preempting {unit_id} for higher-priority "
                    f"{gang.name}")
                self.request_drain(unit_id)
            draining_chips += freed
            handled |= member_keys
            # Cooldown: give the drain window time to play out before
            # considering more victims for this demand unit.
            self._retry_at[cool_key] = (
                now + self.config.drain_grace_seconds + 60.0)
        return handled

    def _generation_overrides(self, gangs: list[Gang],
                              now: float) -> dict[tuple, str]:
        """Capacity-stockout fallback: after ``fallback_after_failures``
        consecutive failed provisions for a demand unit, fit it on the
        next generation in ``policy.generation_fallbacks`` instead of the
        default.  Selector-pinned gangs are unaffected (the fit engine
        honors pins regardless of the generation argument)."""
        pol = self.config.policy
        fallbacks = pol.generation_fallbacks
        overrides: dict[tuple, str] = {}
        if not fallbacks:
            return overrides
        from tpu_autoscaler.topology.catalog import (
            ACCELERATOR_LABEL,
            TOPOLOGY_LABEL,
        )

        after = max(1, pol.fallback_after_failures)
        for gang in gangs:
            selectors = gang.node_selectors
            if (TOPOLOGY_LABEL in selectors
                    or ACCELERATOR_LABEL in selectors):
                # Pinned: the fitter honors the pin regardless of the
                # generation argument — no override, and crucially no
                # false "falling back" notification either.
                continue
            group_key = gang.multislice_group_key
            streak = self._failure_streak.get(gang.key, 0)
            if group_key is not None:
                streak = max(streak,
                             self._failure_streak.get(group_key, 0))
            if streak < after:
                continue
            gen = fallbacks[min(streak // after - 1, len(fallbacks) - 1)]
            overrides[gang.key] = gen
            note_key = group_key or gang.key
            if self._fallback_noted.get(note_key) != gen:
                self._fallback_noted[note_key] = gen
                self.metrics.inc("generation_fallbacks")
                log.warning(
                    "capacity fallback for %s after %d failed "
                    "provisions: trying %s", gang.name, streak, gen)
                self._explain(gang.name, "generation fallback",
                              f"{streak} failed provisions on the "
                              f"default generation", fallback=gen)
                self._notify(
                    f"capacity stockout for {gang.name}: falling back "
                    f"to {gen}")
                for pod in gang.pods:
                    self._emit_event(
                        pod, now, "GenerationFallback",
                        f"provisioning on {gen} after {streak} failed "
                        "attempts on the default generation",
                        warning=True)
        return overrides

    def _note_failures(self, now: float, pods: list[Pod] = ()) -> None:
        # Cancel provisions stuck in flight past the timeout; the FAILED
        # status this produces is then handled by the normal backoff path.
        timeout = self.config.provision_timeout_seconds
        for status in self.actuator.statuses():
            submitted = self._submitted_at.get(status.id)
            if (status.in_flight and submitted is not None
                    and now - submitted > timeout):
                log.warning("provision %s stuck in flight for %.0fs; "
                            "cancelling", status.id, now - submitted)
                self.metrics.inc("provisions_timed_out")
                self._explain(status.id, "provision cancelled",
                              f"stuck in flight > {timeout:g}s")
                self.actuator.cancel(status.id)
        # Submit→ACTIVE latency per provision (the actuation slice of the
        # north-star budget; SURVEY.md §4.2 latency anatomy).
        for status in self.actuator.statuses():
            if status.state == ACTIVE and status.id in self._submitted_at:
                submitted = self._submitted_at.pop(status.id)
                value = now - submitted
                # The "provision" span (submit → ACTIVE) lands in every
                # served gang's trace; the FIRST emission feeds the
                # provision_latency_seconds histogram so the metric is
                # observed exactly once per provision — gang-less
                # provisions (CPU aggregate, spares) keep the direct
                # observation.  Dispatch-time roots win: the gang's
                # trace may have closed since (it ran off other supply)
                # and the span still belongs in it.
                roots = (self._provision_roots.pop(status.id, None)
                         or self._trace_roots(status.request))
                for i, root in enumerate(roots):
                    self.tracer.record(
                        "provision", start=submitted, end=now, parent=root,
                        attrs={"provision_id": status.id,
                               "units": ",".join(status.unit_ids)},
                        metric=("provision_latency_seconds" if i == 0
                                else None), value=value)
                if not roots:
                    self.metrics.observe("provision_latency_seconds",
                                         value)
                self._explain(status.id, "provision ACTIVE",
                              units=",".join(status.unit_ids),
                              latency_s=round(value, 3))
                if roots and status.id in self._supply_awaiting_nodes:
                    # Supply guard engaged earlier this pass: open the
                    # registration spans NOW (after the provision span,
                    # so seq order stays causal); the guard's release
                    # or expiry in _update_supply_guard ends them.  One
                    # span PER served trace: a multislice cohort's
                    # sibling traces each carry the full phase anatomy
                    # (trace_gaps holds per trace), mirroring the
                    # provision-span loop above.
                    self._registration_spans[status.id] = [
                        self.tracer.start(
                            "node_registration", parent=root, t=now,
                            attrs={"provision_id": status.id,
                                   "units": ",".join(status.unit_ids)})
                        for root in roots]
                elif roots:
                    # Units already registered when ACTIVE was observed
                    # (the fake cloud; fast node pools): the
                    # registration phase collapsed to a point — record
                    # it so every trace shows the full anatomy.
                    for root in roots:
                        self.tracer.record(
                            "node_registration", start=now, end=now,
                            parent=root,
                            attrs={"provision_id": status.id})
                success_key = (status.request.gang_key
                               or ("shape", status.request.shape_name))
                self._failure_streak.pop(success_key, None)
                self._fallback_noted.pop(success_key, None)
        for status in self.actuator.statuses():
            if status.state == FAILED and status.id not in self._seen_failures:
                self._seen_failures.add(status.id)
                self.metrics.inc("provision_failures")
                for root in (self._provision_roots.pop(status.id, None)
                             or self._trace_roots(status.request)):
                    self.tracer.record(
                        "provision_failed",
                        start=self._submitted_at.get(status.id, now),
                        end=now, parent=root,
                        attrs={"provision_id": status.id,
                               "error": (status.error or "")[:200],
                               "reason": getattr(status, "reason", None)})
                self._explain(
                    status.id, "provision FAILED",
                    (status.error or "")[:200],
                    reason_class=getattr(status, "reason", None))
                # Per-cause counter + annotation (actuators/errors.py
                # taxonomy): operators see stockout-vs-quota on the
                # metrics endpoint and on the starved pods themselves.
                reason = getattr(status, "reason", None)
                if reason:
                    self.metrics.inc(
                        f"provision_failures_{reason.replace('-', '_')}")
                    self._annotate_failure_reason(status, reason, pods)
                backoff_key = (status.request.gang_key
                               or ("shape", status.request.shape_name))
                self._failure_streak[backoff_key] = (
                    self._failure_streak.get(backoff_key, 0) + 1)
                self._retry_at[backoff_key] = (
                    now + self.config.provision_retry_seconds)
                log.warning("provision %s failed (retry in %gs): %s",
                            status.id, self.config.provision_retry_seconds,
                            status.error)
                self._notify(
                    f"provision {status.request.shape_name} failed: "
                    f"{status.error}")

    def _observe_detect(self, gang_keys, now: float) -> None:
        """Detect phase: gang first seen Unschedulable → first provision
        submitted on its behalf.  Once per gang lifetime."""
        for key in gang_keys:
            first = self._gang_first_pending.get(key)
            if first is not None and key not in self._gang_detect_observed:
                self._gang_detect_observed.add(key)
                root = self._gang_traces.get(key)
                if root is not None:
                    # Span AND histogram in one emission (the tracer
                    # feeds the metric), so they can never disagree.
                    self.tracer.record(
                        "detect", start=first, end=now, parent=root,
                        metric="detect_latency_seconds",
                        value=max(0.0, now - first))
                else:
                    self.metrics.observe("detect_latency_seconds",
                                         max(0.0, now - first))

    def _annotate_failure_reason(self, status, reason: str,
                                 pods: list[Pod]) -> None:
        """Stamp the failed provision's taxonomy category on the pods it
        was serving, so `kubectl describe` / `status --json` answer
        "why is my job not starting" with stockout-vs-quota-vs-config
        instead of a log hunt.  Advisory: never fails the loop."""
        served = set(status.request.gang_keys or ())
        if status.request.gang_key is not None:
            served.add(status.request.gang_key)
        if not served:
            return
        note = f"provision failed ({reason}): {status.error or ''}"[:500]
        for pod in pods:
            if pod.gang_key in served and pod.phase == "Pending":
                try:
                    self.client.patch_pod(pod.namespace, pod.name, {
                        "metadata": {"annotations": {
                            UNSATISFIABLE_ANNOTATION: note}}})
                except Exception:  # noqa: BLE001 — advisory only
                    self.metrics.inc("advisory_errors")
                    log.debug("could not annotate %s", pod.name,
                              exc_info=True)

    def _track_gang_latency(self, pending: list[Gang], pods: list[Pod],
                            nodes: list[Node], now: float) -> None:
        for gang in pending:
            if gang.key not in self._gang_first_pending:
                self._gang_first_pending[gang.key] = now
                # Mint THE trace for this scale-up: everything from here
                # to last-pod-Running hangs off this root span.
                self._gang_traces[gang.key] = self.tracer.start(
                    "scale_up",
                    trace_id=self.tracer.new_trace("scaleup"), t=now,
                    attrs={"gang": "/".join(str(p) for p in gang.key
                                            or ()),
                           "pods": gang.size})
        if not self._gang_first_pending:
            return
        # Tracked gangs read off the informer's gang index when synced
        # — O(tracked gangs) instead of a full pod-list scan per pass
        # (the ISSUE 6 leftover); one scan-built map otherwise.
        by_key: dict[tuple, list[Pod]] | None = None
        if self._pod_cache() is None:
            by_key = {}
            for p in pods:
                by_key.setdefault(p.gang_key, []).append(p)
        node_by_name = {n.name: n for n in nodes}
        for key, first in list(self._gang_first_pending.items()):
            members = (by_key.get(key, []) if by_key is not None
                       else self._gang_members(pods, key))
            if members and all(p.phase == "Running" for p in members):
                latency = now - first
                root = self._gang_traces.pop(key, None)
                bind_start = self._bind_start(members, node_by_name)
                if bind_start is not None:
                    start = max(bind_start, first)
                    if root is not None:
                        self.tracer.record(
                            "pods_running", start=start, end=now,
                            parent=root, metric="bind_latency_seconds",
                            value=max(0.0, now - start))
                    else:
                        self.metrics.observe("bind_latency_seconds",
                                             max(0.0, now - start))
                elif root is not None:
                    # Barrier untracked this process lifetime: no honest
                    # bind number, but the trace still shows the phase.
                    self.tracer.record("pods_running", start=first,
                                       end=now, parent=root,
                                       attrs={"bind_start": "untracked"})
                if root is not None:
                    # Cost-to-serve so far (ISSUE 11): the ledger's
                    # attribution for this gang incarnation, when it
                    # has one — a gang whose members ran across passes
                    # (or rode a repair) closes with its bill attached.
                    attrs = {"latency_s": round(latency, 3)}
                    cost_attrs = self.cost.gang_attrs(key, now)
                    if cost_attrs:
                        attrs.update(cost_attrs)
                    self.tracer.end(root, t=now,
                                    metric="scale_up_latency_seconds",
                                    value=latency, attrs=attrs)
                    # Histogram exemplar (ISSUE 14): this pass's
                    # north-star p99 links to the SLOWEST scale-up
                    # trace that closed in it.  Value already observed
                    # by the span end above — _obs_pass must not
                    # re-observe it.
                    cur = self._span_exemplars.get(
                        "scale_up_latency_seconds")
                    if cur is None or latency >= cur[1]:
                        self._span_exemplars[
                            "scale_up_latency_seconds"] = (
                                root.trace_id, latency)
                else:
                    self.metrics.observe("scale_up_latency_seconds",
                                         latency)
                self._explain(key, "gang running",
                              f"Unschedulable→Running in {latency:.1f}s")
                log.info("gang %s Unschedulable→Running in %.1fs", key,
                         latency)
                del self._gang_first_pending[key]
                self._gang_detect_observed.discard(key)
            elif not members:
                # Gang's pods were deleted while pending: drop the entry so
                # a reused Job name doesn't inherit a stale start time.
                root = self._gang_traces.pop(key, None)
                if root is not None:
                    self.tracer.end(
                        root, t=now,
                        attrs={"aborted": "pods deleted while pending"})
                del self._gang_first_pending[key]
                self._gang_detect_observed.discard(key)
        cache = self._pod_cache()
        index_keys = cache.index_keys("gang") if cache is not None else None
        live_keys = (set(index_keys) if index_keys is not None
                     else {p.gang_key for p in pods})
        for key in [k for k in self._gang_sizes if k not in live_keys]:
            del self._gang_sizes[key]

    def _bind_start(self, members: list[Pod],
                    node_by_name: dict[str, Node]) -> float | None:
        """Start of the bind phase: when the slowest supply unit the
        gang bound to cleared its readiness barrier.  The caller clamps
        to first-pending (a gang that binds to a slice Ready long
        before it arrived spent no time waiting on the scheduler's
        account) and feeds ``bind_latency_seconds`` through the
        ``pods_running`` span.  None = no honest number (a member's
        node already gone, or the barrier untracked this process
        lifetime)."""
        from tpu_autoscaler.k8s.units import group_supply_units

        bound_nodes = [node_by_name[p.node_name] for p in members
                       if p.node_name in node_by_name]
        if len(bound_nodes) < len(members):
            return None  # a member's node is already gone
        ready_times = []
        for unit_id in group_supply_units(bound_nodes):
            since = self.tracker.all_ready_since(unit_id)
            if since is None:
                return None  # barrier not tracked this process lifetime
            ready_times.append(since)
        return max(ready_times) if ready_times else None

    # ---- scale-down / maintenance -------------------------------------- #

    def _emit_event(self, pod: Pod, now: float, reason: str, message: str,
                    warning: bool = False) -> None:
        """Best-effort core/v1 Event on a pod, kubectl-describe visible
        (upstream cluster-autoscaler behavior; the reference had only
        Slack).  Never fails the loop.  Timestamps use the injected clock
        (canonical Z form, like payloads._iso) so e2e events are
        deterministic under simulated time."""
        import datetime

        ts = datetime.datetime.fromtimestamp(
            now, tz=datetime.timezone.utc).isoformat().replace(
            "+00:00", "Z")
        body = {
            "metadata": {"generateName": "tpu-autoscaler-",
                         "namespace": pod.namespace},
            "involvedObject": {"kind": "Pod", "namespace": pod.namespace,
                               "name": pod.name, "uid": pod.uid},
            "reason": reason,
            "message": message[:1000],
            "type": "Warning" if warning else "Normal",
            "source": {"component": "tpu-autoscaler"},
            "firstTimestamp": ts,
            "lastTimestamp": ts,
            "count": 1,
        }
        try:
            self.client.create_event(pod.namespace, body)
        except Exception:  # noqa: BLE001 — advisory only
            self.metrics.inc("advisory_errors")
            log.debug("event emission failed", exc_info=True)

    def request_drain(self, unit_id: str) -> None:
        """Ask for a unit to be evacuated (spot reclamation notice,
        scale-to-zero, operator action).  Honored checkpoint-aware on the
        next reconcile pass."""
        self._requested_drains.add(unit_id)

    def _units(self, nodes: list[Node]) -> dict[str, list[Node]]:
        """Group nodes into supply units (shared rule: k8s/units.py)."""
        from tpu_autoscaler.k8s.units import group_supply_units

        return group_supply_units(nodes)

    def _spare_units(self, units: dict[str, list[Node]],
                     pods_by_node: dict[str, list[Pod]]) -> set[str]:
        """Pick which idle units the spare policy retains.

        CPU: newest ``spare_nodes`` idle nodes.  TPU: per shape, the newest
        ``spare_slices[shape]`` idle slices.  (Reference: --spare-agents
        kept N free agents, cluster.py §SPARE_AGENT.)
        """
        pol = self.config.policy
        spare: set[str] = set()

        def idle(unit_nodes: list[Node]) -> bool:
            return not any(
                p for n in unit_nodes for p in pods_by_node.get(n.name, [])
                if p.is_workload)

        def created(unit_nodes: list[Node]) -> float:
            times = [n.created.timestamp() for n in unit_nodes if n.created]
            return max(times) if times else 0.0

        cpu_idle = sorted(
            (uid for uid, ns in units.items()
             if not ns[0].is_tpu and idle(ns)),
            key=lambda uid: -created(units[uid]))
        spare.update(cpu_idle[:pol.spare_nodes])

        for shape_name, want in pol.spare_slices.items():
            tpu_idle = sorted(
                (uid for uid, ns in units.items()
                 if ns[0].is_tpu and idle(ns)
                 and f"{_gen_of(ns[0], self.metrics)}-{_chips_of(ns)}"
                 == shape_name),
                key=lambda uid: -created(units[uid]))
            spare.update(tpu_idle[:want])
        return spare

    def _claimed_by_pending(self, units: dict[str, list[Node]],
                            pending_gangs: list[Gang],
                            pods: list[Pod],
                            columnar=None) -> set[str]:
        """Units that currently-pending demand will bind to: NOT
        drainable.  The scan itself is a pure function
        (controller/shard.py claimed_by_pending — O(units × gangs),
        the maintenance pass's superlinear term); with sharding on and
        enough demand it partitions by accelerator class/pool across
        the same worker pool as planning (ISSUE 13).  With a columnar
        state attached to the pass it vectorizes instead
        (engine/columnar.py ``claimed_units``)."""
        from tpu_autoscaler.controller import shard

        if (self.sharder is not None
                and len(pending_gangs) >= self.config.shard_min_gangs):
            return self.sharder.claimed_by_pending(
                units, pending_gangs, pods,
                candidate_accels=self._candidate_accels,
                columnar=columnar)
        return shard.claimed_by_pending(units, pending_gangs, pods,
                                        columnar=columnar)

    def _maintain(self, nodes: list[Node], pods: list[Pod],
                  now: float, pending_gangs: list[Gang] = ()) -> None:
        cfg = self.config
        # Informer node-index read when synced (O(bound pods of these
        # nodes)) instead of the full pod-list scan — the ISSUE 6
        # leftover that kept a 100k-pod control loop O(cluster).
        pods_by_node = self._pods_by_node(nodes, pods)

        units = self._units(nodes)
        spare_ids = self._spare_units(units, pods_by_node)
        claimed_ids = self._claimed_by_pending(
            units, list(pending_gangs), pods,
            columnar=self._attach_columnar(nodes, pods))
        state_counts: dict[str, int] = {}
        # At most one consolidation drain per pass: gentle repacking, no
        # mass eviction (the reference drained under-utilized nodes one
        # loop iteration at a time too, by construction).
        consolidated_this_pass = False

        for unit_id, unit_nodes in units.items():
            unit_pods = [p for n in unit_nodes
                         for p in pods_by_node.get(n.name, [])]
            self._unit_first_seen.setdefault(unit_id, now)
            view = self.tracker.observe(unit_id, unit_nodes, unit_pods, now)
            if view.all_ready_since == now:
                # Readiness barrier just cleared: record how long the
                # slowest host took after the first host appeared.
                created = [n.created.timestamp() for n in unit_nodes
                           if n.created]
                if created:
                    self.metrics.observe("ready_barrier_seconds",
                                         max(0.0, now - min(created)))
            # Per-unit idle threshold: the policy engine's SLO/cost
            # tradeoff (ISSUE 8) — stretched when demand is forecast
            # for this unit's class, shrunk toward the floor when the
            # class shows no predicted demand (early reclaim).
            idle_threshold = self._policy_idle_overrides.get(
                unit_id, cfg.idle_threshold_seconds)
            state = classify_slice(
                view, grace_seconds=cfg.grace_seconds,
                idle_threshold_seconds=idle_threshold,
                spare=unit_id in spare_ids,
                utilization_threshold=cfg.utilization_threshold)
            state_counts[state.value] = state_counts.get(state.value, 0) + 1
            # Cost attribution (ISSUE 11): fold this unit's observation
            # into the ledger off the classification the pass already
            # computed — O(1), a tuple compare when nothing changed.
            # Crash-only: ledger bugs never starve maintenance.
            try:
                self.cost.note_unit(
                    unit_id, unit_nodes, unit_pods, state.value, now,
                    under_repair=unit_id in self._slice_repairs,
                    cancellable_drain=unit_id in self._drain_cancellable,
                    policy_hold=unit_id in self._policy_holds,
                    spare=unit_id in spare_ids,
                    first_seen=self._unit_first_seen.get(unit_id))
            except Exception:  # noqa: BLE001 — observability only
                self.metrics.inc("cost_ledger_errors")
                log.exception("cost ledger observe failed for %s",
                              unit_id)

            doomed = any(t.get("key") in TERMINATION_TAINT_KEYS
                         for n in unit_nodes for t in n.taints)
            try:
                if (state in (SliceState.BUSY, SliceState.IDLE,
                              SliceState.LAUNCH_GRACE, SliceState.SPARE)
                        and (unit_id in self._requested_drains or doomed)):
                    self._begin_drain(
                        unit_id, unit_nodes, unit_pods, now,
                        reason=("impending node termination" if doomed
                                and unit_id not in self._requested_drains
                                else "drain requested"))
                elif state is SliceState.IDLE_DRAINABLE:
                    if unit_id in self._policy_holds:
                        # An un-consumed prewarm rides this unit: a
                        # warm slice reclaimed seconds before its
                        # predicted gang arrives is the worst of both
                        # worlds.  Bounded: the hold dies with the
                        # prediction's window (docs/POLICY.md).
                        self.metrics.inc("prewarm_holds")
                        self._explain(unit_id, "reclaim deferred",
                                      "held warm for a forecast "
                                      "prewarm")
                    elif unit_id in claimed_ids:
                        # Pending demand will bind here: hands off
                        # (reference: pending pods could use the node).
                        self.metrics.inc("reclaims_deferred_to_pending")
                        self._explain(unit_id, "reclaim deferred",
                                      "pending demand claims this unit")
                    else:
                        if idle_threshold < cfg.idle_threshold_seconds:
                            # The policy shrank this unit's threshold:
                            # cost won over a demand forecast that
                            # never came (docs/POLICY.md scale-down).
                            self.metrics.inc("policy_early_reclaims")
                        # The idle clock's waste bill comes from the
                        # ledger — the ONE source of truth for idle
                        # chip-seconds (ISSUE 11; the ad-hoc per-unit
                        # clocks only decide WHEN to reclaim).
                        idle_cs = self.cost.accrued_chip_seconds(
                            [unit_id], now, state="idle")
                        if idle_cs:
                            self.metrics.inc(
                                "cost_idle_chip_seconds_reclaimed",
                                idle_cs)
                            self._explain(
                                unit_id, "idle waste reclaimed",
                                f"{idle_cs:.0f} chip-seconds sat idle "
                                f"before this reclaim (cost ledger)")
                        self._begin_drain(
                            unit_id, unit_nodes, unit_pods, now,
                            reason=f"idle > {idle_threshold:g}s")
                elif (state is SliceState.UNDER_UTILIZED
                      and not consolidated_this_pass):
                    consolidated_this_pass = True
                    self.metrics.inc("consolidation_drains")
                    self._begin_drain(
                        unit_id, unit_nodes, unit_pods, now,
                        reason=(f"under-utilized "
                                f"({view.utilization:.0%} < "
                                f"{cfg.utilization_threshold:.0%})"))
                elif state is SliceState.DRAINING:
                    if (unit_id in claimed_ids
                            and unit_id in self._drain_cancellable):
                        # Demand that fits this unit appeared mid-drain:
                        # uncordon and hand it back instead of deleting
                        # and re-provisioning identical capacity.
                        self._cancel_drain(unit_id, unit_nodes)
                    else:
                        self._continue_drain(unit_id, unit_nodes,
                                             unit_pods, now)
                elif state is SliceState.UNHEALTHY:
                    self._handle_unhealthy(unit_id, unit_nodes, unit_pods,
                                           now)
                elif state is SliceState.PROVISIONING:
                    self._reclaim_if_orphaned(unit_id, unit_nodes,
                                              unit_pods, now)
                else:
                    self._unhealthy_since.pop(unit_id, None)
            except Exception:  # noqa: BLE001 — one unit's API failure must
                # not starve maintenance of every other unit.
                log.exception("maintenance failed for unit %s", unit_id)
                self.metrics.inc("maintain_errors")

        for key, count in state_counts.items():
            self.metrics.set_gauge(f"units_{key.replace('-', '_')}", count)
        self._sweep_repairs(units, pods, now)
        # Cost-aware continuous repacking (ISSUE 12): AFTER the unit
        # loop fed the ledger (placement rows are this pass's truth)
        # and the repair sweep settled migration completions.  Crash-
        # only: a repack bug leaves the fleet as placed, never breaks
        # maintenance.
        if self.repacker is not None:
            try:
                self._repack_pass(units, pods, pods_by_node, spare_ids,
                                  now)
            except Exception:  # noqa: BLE001 — advisory only
                self.metrics.inc("repack_errors")
                log.exception("repack pass failed; fleet stays as "
                              "placed")
        # Forget tracker state for units whose nodes are gone.
        # Ledger units not in this pass's observation left the fleet
        # (drain-complete deletes forget the tracker mid-pass, so the
        # tracker sweep below cannot be the removal signal — the
        # OBSERVED unit set is).
        try:
            for known in [u for u in self.cost.known_units()
                          if u not in units]:
                self.cost.remove_unit(known, now)
        except Exception:  # noqa: BLE001 — observability only
            self.metrics.inc("cost_ledger_errors")
            log.exception("cost ledger unit sweep failed")
        for known in self.tracker.known_slices():
            if known not in units:
                self.tracker.forget(known)
                self._drain_started.pop(known, None)
                self._drain_cancellable.discard(known)
                self._requested_drains.discard(known)
                self._unhealthy_since.pop(known, None)
                self._unit_first_seen.pop(known, None)

    def _begin_drain(self, unit_id: str, unit_nodes: list[Node],
                     unit_pods: list[Pod], now: float, reason: str) -> None:
        log.info("draining unit %s (%d nodes): %s", unit_id,
                 len(unit_nodes), reason)
        for node in unit_nodes:
            node.cordon(self.client)
            self.client.patch_node(node.name, {
                "metadata": {"annotations": {DRAIN_ANNOTATION: str(now)}}})
        # Checkpoint contract: tell the workload to save and exit.
        for pod in unit_pods:
            if pod.is_drainable:
                self.client.patch_pod(pod.namespace, pod.name, {
                    "metadata": {"annotations": {
                        CHECKPOINT_ANNOTATION: str(now)}}})
        self.tracker.note_cordoned(unit_id)
        self._drain_started[unit_id] = now
        if reason.startswith("idle"):
            self._drain_cancellable.add(unit_id)
        self.metrics.inc("drains_started")
        self._explain(unit_id, "drain started", reason)
        self._notify(f"draining {unit_id}: {reason}")

    def _cancel_drain(self, unit_id: str, unit_nodes: list[Node]) -> None:
        log.info("cancelling drain of %s: pending demand claims it",
                 unit_id)
        for node in unit_nodes:
            node.uncordon(self.client)
            self.client.patch_node(node.name, {
                "metadata": {"annotations": {DRAIN_ANNOTATION: None}}})
        self.tracker.forget(unit_id)
        self._drain_started.pop(unit_id, None)
        self._drain_cancellable.discard(unit_id)
        self.metrics.inc("drains_cancelled")
        self._explain(unit_id, "drain cancelled",
                      "pending demand claims this unit")

    def _continue_drain(self, unit_id: str, unit_nodes: list[Node],
                        unit_pods: list[Pod], now: float) -> None:
        started = self._drain_started.setdefault(unit_id, now)
        workload = [p for p in unit_pods if p.is_workload]
        if workload:
            if now - started < self.config.drain_grace_seconds:
                return  # checkpoint window still open
            # Deadline passed: evict what the eviction API allows, and
            # force-delete the rest (bare pods, safe-to-evict=false) — the
            # unit is going away regardless (spot reclamation semantics),
            # and leaving it cordoned-forever strands the whole slice.
            for node in unit_nodes:
                node.drain(self.client, unit_pods)
            for pod in workload:
                if not pod.is_drainable:
                    pod.delete(self.client)
            return
        # Unit is empty: reclaim it atomically.
        log.info("deleting unit %s (%d nodes)", unit_id, len(unit_nodes))
        self.actuator.delete(unit_id)
        for node in unit_nodes:
            node.delete(self.client)
        self.tracker.forget(unit_id)
        self._drain_started.pop(unit_id, None)
        self._drain_cancellable.discard(unit_id)
        self._requested_drains.discard(unit_id)
        self.metrics.inc("units_deleted")
        self._explain(unit_id, "unit deleted", "drain complete")
        self._notify(f"deleted idle unit {unit_id}")

    def _reclaim_if_orphaned(self, unit_id: str, unit_nodes: list[Node],
                             unit_pods: list[Pod], now: float) -> None:
        """Reclaim a unit stuck behind the provisioning barrier with no
        workload past ``provision_timeout_seconds`` — orphaned partial
        supply (fuzzer-found): a provision that FAILED after
        materializing some hosts, or a slice whose hosts never go
        Ready.  Any backing provision was already cancelled by
        ``_note_failures`` at the SAME timeout, so what remains is
        capacity nothing will ever complete or bind to.  Deleted whole,
        like every unit.

        With workload ABOARD (a scheduler bound pods to the partial
        slice's individually-Ready hosts before it completed — also
        fuzzer-found), the unit is a broken ICI domain serving pods:
        it goes through the slice-REPAIR path instead, after the same
        timeout."""
        first = self._unit_first_seen.get(unit_id, now)
        if now - first <= self.config.provision_timeout_seconds:
            return
        if any(p.is_workload for p in unit_pods):
            if self.config.enable_slice_repair and unit_nodes[0].is_tpu:
                self._maybe_start_repair(unit_id, unit_nodes, unit_pods,
                                         now)
            return
        log.warning("reclaiming orphaned partial unit %s (%d hosts, "
                    "behind the barrier for %.0fs with no backing "
                    "provision)", unit_id, len(unit_nodes), now - first)
        self.metrics.inc("orphaned_partial_units_reclaimed")
        self._explain(unit_id, "orphaned partial unit reclaimed",
                      f"stuck PROVISIONING > "
                      f"{self.config.provision_timeout_seconds:g}s with "
                      f"no workload")
        self._notify(f"reclaiming orphaned partial unit {unit_id}")
        self.actuator.delete(unit_id)
        for node in unit_nodes:
            node.delete(self.client)
        self.tracker.forget(unit_id)
        self.metrics.inc("units_deleted")

    def _handle_unhealthy(self, unit_id: str, unit_nodes: list[Node],
                          unit_pods: list[Pod], now: float) -> None:
        """A previously-Ready slice lost a host: the ICI domain is broken.

        Workload-bearing TPU slices go through the ICI-atomic REPAIR
        path (ISSUE 7): prompt whole-slice cordon + checkpoint drain
        with advisory like-for-like replacement demand, traced end to
        end.  Everything else keeps the flap-window replace: wait, then
        reclaim the whole slice — the gang it hosted re-pends and the
        scale path provisions anew.  Partial repair of a slice is
        impossible by construction either way.
        """
        if (self.config.enable_slice_repair and unit_nodes[0].is_tpu
                and any(p.is_workload for p in unit_pods)):
            self._maybe_start_repair(unit_id, unit_nodes, unit_pods, now)
            return
        self._handle_unhealthy_legacy(unit_id, unit_nodes, unit_pods, now)

    def _handle_unhealthy_legacy(self, unit_id: str,
                                 unit_nodes: list[Node],
                                 unit_pods: list[Pod],
                                 now: float) -> None:
        since = self._unhealthy_since.setdefault(unit_id, now)
        if now - since < self.config.unhealthy_timeout_seconds:
            return
        if unit_id in self._drain_started:
            return  # replacement drain already under way
        self.metrics.inc("unhealthy_units_replaced")
        self._begin_drain(unit_id, unit_nodes, unit_pods, now,
                          reason="unhealthy host in slice")


_warned_unknown_shapes: set = set()


def _gen_of(node: Node, metrics=None) -> str:
    from tpu_autoscaler.topology.catalog import SLICE_SHAPES

    for s in SLICE_SHAPES.values():
        if s.accelerator_type == node.tpu_accelerator \
                and s.topology_label == node.tpu_topology:
            return s.generation
    # A TPU node whose accelerator/topology labels match no catalog
    # shape: the spare-slice policy will never retain it, silently.
    # Count + log once per label combo (NOT per call: this runs inside
    # the per-shape spare filter every reconcile pass, so an undeduped
    # counter would measure loop iterations, not unknown nodes).
    combo = (node.tpu_accelerator, node.tpu_topology)
    if combo not in _warned_unknown_shapes:
        _warned_unknown_shapes.add(combo)
        if metrics is not None:
            metrics.inc("nodes_unknown_shape")
        import logging

        logging.getLogger(__name__).warning(
            "node %s has accelerator=%r topology=%r matching no catalog "
            "shape; spare-slice retention will skip it", node.name,
            combo[0], combo[1])
    return "unknown"


def _chips_of(nodes: list[Node]) -> int:
    from tpu_autoscaler.topology.catalog import TPU_RESOURCE

    return sum(int(n.allocatable.get(TPU_RESOURCE)) for n in nodes)
