"""Stateless cluster status rendering (`tpu-autoscaler status`).

A read-only snapshot an operator can take against any cluster: supply
units (slices / CPU nodes) with readiness and load, and pending gangs
with the fit engine's verdict — the same math the controller runs, with
no timers and no writes.
"""

from __future__ import annotations

from tpu_autoscaler.engine.fitter import FitError, choose_shape_for_gang
from tpu_autoscaler.k8s.gangs import group_into_gangs
from tpu_autoscaler.k8s.objects import (
    UNSATISFIABLE_ANNOTATION,
    Node,
    Pod,
)
from tpu_autoscaler.k8s.units import group_supply_units
from tpu_autoscaler.topology.catalog import TPU_RESOURCE


def build_plan(node_payloads: list[dict], pod_payloads: list[dict],
               default_generation: str = "v5e") -> dict:
    """What-if: the plan a FRESH controller with a default policy would
    compute from current cluster state.

    Read-only estimate for operators — it cannot see the running
    controller's in-flight provisions or its configured policy (spares,
    quotas, clamps), so it may show provisions the live controller is
    already making or would clamp.  Same fit math, different inputs.
    """
    from tpu_autoscaler.engine.planner import Planner, PoolPolicy

    nodes = [Node(p) for p in node_payloads]
    pods = [Pod(p) for p in pod_payloads]
    gangs = group_into_gangs([p for p in pods if p.is_unschedulable])
    plan = Planner(PoolPolicy(
        default_generation=default_generation, spare_nodes=0)).plan(
        gangs, nodes, pods, [])
    return {
        "requests": [
            {"kind": r.kind, "shape": r.shape_name, "count": r.count,
             "gang": r.gang_key[2] if r.gang_key else None,
             "stranded_chips": r.stranded_chips, "reason": r.reason}
            for r in plan.requests
        ],
        "unsatisfiable": [
            {"gang": g.name, "namespace": g.namespace, "reason": reason}
            for g, reason in plan.unsatisfiable
        ],
    }


def build_status(node_payloads: list[dict], pod_payloads: list[dict],
                 default_generation: str = "v5e") -> dict:
    """Structured snapshot (the --json output; text rendering sits on
    top)."""
    nodes = [Node(p) for p in node_payloads]
    pods = [Pod(p) for p in pod_payloads]
    pods_by_node: dict[str, int] = {}
    for p in pods:
        if p.node_name and p.is_workload:
            pods_by_node[p.node_name] = pods_by_node.get(p.node_name, 0) + 1

    units_out = []
    for unit_id, members in sorted(group_supply_units(nodes).items()):
        units_out.append({
            "id": unit_id,
            "kind": "tpu" if members[0].is_tpu else "cpu",
            "accelerator": members[0].tpu_accelerator,
            "topology": members[0].tpu_topology,
            "machine_type": members[0].instance_type,
            "hosts": len(members),
            "ready_hosts": sum(1 for n in members if n.is_ready),
            "cordoned_hosts": sum(1 for n in members if n.unschedulable),
            "chips": sum(int(n.allocatable.get(TPU_RESOURCE))
                         for n in members),
            "workload_pods": sum(pods_by_node.get(n.name, 0)
                                 for n in members),
        })

    gangs_out = []
    for gang in group_into_gangs([p for p in pods if p.is_unschedulable]):
        entry = {
            "name": gang.name,
            "namespace": gang.namespace,
            "pods": gang.size,
            "tpu_chips": gang.tpu_chips,
            "priority": gang.priority,
            "cpu": gang.total_resources.get("cpu"),
        }
        if gang.requests_tpu:
            try:
                choice = choose_shape_for_gang(gang, default_generation)
                entry["shape"] = choice.shape.name
                entry["stranded_chips"] = choice.stranded_chips
            except FitError as e:
                entry["unsatisfiable"] = str(e)
        # The controller stamps failed-provision causes (stockout /
        # quota / ... — actuators/errors.py taxonomy) on the pods; a
        # read-only status sees them without controller state.
        notes = {p.annotations.get(UNSATISFIABLE_ANNOTATION)
                 for p in gang.pods
                 if p.annotations.get(UNSATISFIABLE_ANNOTATION)}
        if notes:
            entry["provisioning_blocked"] = sorted(notes)[0]
        gangs_out.append(entry)
    return {"units": units_out, "pending_gangs": gangs_out}


def render_status(node_payloads: list[dict], pod_payloads: list[dict],
                  default_generation: str = "v5e") -> str:
    snap = build_status(node_payloads, pod_payloads, default_generation)
    lines = ["SUPPLY UNITS"]
    if not snap["units"]:
        lines.append("  (none)")
    for u in snap["units"]:
        kind = (f"tpu {u['accelerator']}/{u['topology']}"
                if u["kind"] == "tpu" else f"cpu {u['machine_type']}")
        flags = []
        if u["ready_hosts"] < u["hosts"]:
            flags.append(f"READY {u['ready_hosts']}/{u['hosts']}")
        if u["cordoned_hosts"]:
            flags.append(f"CORDONED {u['cordoned_hosts']}")
        lines.append(
            f"  {u['id']}: {kind}, hosts={u['hosts']}, "
            f"chips={u['chips']}, workload_pods={u['workload_pods']}"
            + (f" [{' '.join(flags)}]" if flags else ""))

    lines.append("PENDING GANGS")
    if not snap["pending_gangs"]:
        lines.append("  (none)")
    for g in snap["pending_gangs"]:
        if g["tpu_chips"]:
            verdict = (f"UNSATISFIABLE: {g['unsatisfiable']}"
                       if "unsatisfiable" in g else
                       f"-> {g['shape']} ({g['stranded_chips']} stranded)")
            lines.append(f"  {g['name']}: {g['pods']} pods, "
                         f"{g['tpu_chips']} chips {verdict}")
        else:
            lines.append(f"  {g['name']}: {g['pods']} pods, "
                         f"cpu={g['cpu']:g}")
    return "\n".join(lines)
