"""Stateless cluster status rendering (`tpu-autoscaler status`).

A read-only snapshot an operator can take against any cluster: supply
units (slices / CPU nodes) with readiness and load, and pending gangs
with the fit engine's verdict — the same math the controller runs, with
no timers and no writes.
"""

from __future__ import annotations

from tpu_autoscaler.engine.fitter import FitError, choose_shape_for_gang
from tpu_autoscaler.k8s.gangs import group_into_gangs
from tpu_autoscaler.k8s.objects import Node, Pod
from tpu_autoscaler.k8s.units import group_supply_units
from tpu_autoscaler.topology.catalog import TPU_RESOURCE


def render_status(node_payloads: list[dict], pod_payloads: list[dict],
                  default_generation: str = "v5e") -> str:
    nodes = [Node(p) for p in node_payloads]
    pods = [Pod(p) for p in pod_payloads]
    pods_by_node: dict[str, int] = {}
    for p in pods:
        if p.node_name and p.phase in {"Pending", "Running"} \
                and not p.is_daemonset and not p.is_mirrored:
            pods_by_node[p.node_name] = pods_by_node.get(p.node_name, 0) + 1

    lines = ["SUPPLY UNITS"]
    units = group_supply_units(nodes)
    if not units:
        lines.append("  (none)")
    for unit_id, members in sorted(units.items()):
        ready = sum(1 for n in members if n.is_ready)
        cordoned = sum(1 for n in members if n.unschedulable)
        chips = sum(int(n.allocatable.get(TPU_RESOURCE)) for n in members)
        workload = sum(pods_by_node.get(n.name, 0) for n in members)
        kind = (f"tpu {members[0].tpu_accelerator}"
                f"/{members[0].tpu_topology}" if members[0].is_tpu
                else f"cpu {members[0].instance_type}")
        flags = []
        if ready < len(members):
            flags.append(f"READY {ready}/{len(members)}")
        if cordoned:
            flags.append(f"CORDONED {cordoned}")
        lines.append(
            f"  {unit_id}: {kind}, hosts={len(members)}, chips={chips}, "
            f"workload_pods={workload}"
            + (f" [{' '.join(flags)}]" if flags else ""))

    lines.append("PENDING GANGS")
    pending = [p for p in pods if p.is_unschedulable]
    gangs = group_into_gangs(pending)
    if not gangs:
        lines.append("  (none)")
    for gang in gangs:
        if gang.requests_tpu:
            try:
                choice = choose_shape_for_gang(gang, default_generation)
                verdict = (f"-> {choice.shape.name} "
                           f"({choice.stranded_chips} stranded)")
            except FitError as e:
                verdict = f"UNSATISFIABLE: {e}"
            lines.append(f"  {gang.name}: {gang.size} pods, "
                         f"{gang.tpu_chips} chips {verdict}")
        else:
            cpu = gang.total_resources.get("cpu")
            lines.append(f"  {gang.name}: {gang.size} pods, cpu={cpu:g}")
    return "\n".join(lines)
